#!/usr/bin/env python3
"""Headline benchmark: ResNet-50 training images/sec/chip (bfloat16,
synthetic ImageNet shapes) on the attached TPU, via the framework's
compute path (models/resnet.py + parallel/train.py).

This is the BASELINE.md metric: the reference's TensorFlow-Distributed
recipe (ResNet-50/ImageNet) on 16xV100 — per-chip parity means one TPU
chip matching one V100. Published V100 reference throughput for TF
ResNet-50 (fp32, synthetic): ~405 images/sec (NVIDIA DGX-1 numbers);
vs_baseline is measured/405.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip",
   "vs_baseline": N}
Detailed sub-metrics (transformer tokens/sec, orchestration latency)
land in BENCH_DETAILS.json next to this file.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

V100_BASELINE_IMG_PER_SEC = 405.0

REPO_ROOT = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(REPO_ROOT))

from batch_shipyard_tpu.parallel import mfu as mfu_mod  # noqa: E402
from batch_shipyard_tpu.parallel import topology  # noqa: E402


def _mfu_fields(items_per_sec_per_chip: float,
                flops_per_item: float) -> dict:
    """Explicit MFU accounting per workload (VERDICT r4 next #1d):
    achieved model FLOPs vs the live chip's bf16 peak from the
    topology generation table. Absent (None) on non-TPU backends."""
    import jax
    kind = jax.devices()[0].device_kind
    peak = topology.peak_bf16_tflops_for_device_kind(kind)
    pct = mfu_mod.mfu_pct(items_per_sec_per_chip, flops_per_item,
                          peak)
    return {
        "model_flops_per_item": flops_per_item,
        "device_kind": kind,
        "peak_bf16_tflops_per_chip": peak,
        "mfu_pct": None if pct is None else round(pct, 2),
    }


def bench_resnet(batch_size: int = 256, image_size: int = 224,
                 warmup: int = 3, iters: int = 10) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from batch_shipyard_tpu.models import resnet as resnet_mod
    from batch_shipyard_tpu.parallel import mesh as mesh_mod
    from batch_shipyard_tpu.parallel import train as train_mod

    n_dev = len(jax.devices())
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    config = resnet_mod.ResNetConfig(dtype=jnp.bfloat16)
    harness = train_mod.build_resnet_train(
        mesh, config, batch_size=batch_size, image_size=image_size,
        learning_rate=0.1)
    rng = np.random.RandomState(0)
    batch = {
        "images": jnp.asarray(
            rng.randn(batch_size, image_size, image_size, 3),
            jnp.bfloat16),
        "labels": jnp.asarray(rng.randint(0, 1000, (batch_size,)),
                              jnp.int32),
    }
    params, opt_state = harness.params, harness.opt_state
    for _ in range(warmup):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
    float(metrics["loss"])  # host transfer = hard sync (the axon
    # platform's block_until_ready returns before execution finishes)
    start = time.perf_counter()
    for _ in range(iters):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
    final_loss = float(metrics["loss"])
    elapsed = time.perf_counter() - start
    images_per_sec = batch_size * iters / elapsed
    out = {
        "images_per_sec": images_per_sec,
        "images_per_sec_per_chip": images_per_sec / n_dev,
        "chips": n_dev,
        "batch_size": batch_size,
        "step_seconds": elapsed / iters,
        "final_loss": final_loss,
    }
    out.update(_mfu_fields(
        out["images_per_sec_per_chip"],
        mfu_mod.resnet50_train_flops_per_image(image_size)))
    return out


def bench_transformer(batch_size: int = 16, seq_len: int = 2048,
                      warmup: int = 2, iters: int = 5,
                      fused_norm: bool = False,
                      quantize: bool = False) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from batch_shipyard_tpu.parallel import mesh as mesh_mod
    from batch_shipyard_tpu.parallel import train as train_mod

    n_dev = len(jax.devices())
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    config = train_mod.make_transformer_config(
        mesh, vocab_size=32000, d_model=1024, n_layers=12, n_heads=16,
        d_head=64, d_ff=2816, max_seq_len=seq_len,
        dtype=jnp.bfloat16,
        # No layer remat: flash/blockwise attention already
        # rematerializes its block scores, and at b16 the rest of the
        # activations fit v5e HBM — measured 24.6k vs 15.2k tok/s.
        remat=False,
        # MFU levers (ROADMAP): Pallas fused RMSNorm+matmul
        # projections, or the int8 MXU path (2x bf16 rate on v5e).
        fused_norm=fused_norm, quantize_matmuls=quantize)
    harness = train_mod.build_transformer_train(
        mesh, config, batch_size=batch_size, seq_len=seq_len)
    rng = np.random.RandomState(0)
    batch = {
        "tokens": jnp.asarray(
            rng.randint(0, 32000, (batch_size, seq_len)), jnp.int32),
        "targets": jnp.asarray(
            rng.randint(0, 32000, (batch_size, seq_len)), jnp.int32),
    }
    params, opt_state = harness.params, harness.opt_state
    for _ in range(warmup):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
    float(metrics["loss"])  # hard sync (see bench_resnet)
    start = time.perf_counter()
    for _ in range(iters):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
    final_loss = float(metrics["loss"])
    elapsed = time.perf_counter() - start
    tokens_per_sec = batch_size * seq_len * iters / elapsed
    out = {
        "tokens_per_sec": tokens_per_sec,
        "tokens_per_sec_per_chip": tokens_per_sec / n_dev,
        "chips": n_dev,
        "step_seconds": elapsed / iters,
        "final_loss": final_loss,
        "fused_norm": fused_norm,
        "quantize_matmuls": quantize,
    }
    out.update(_mfu_fields(
        out["tokens_per_sec_per_chip"],
        mfu_mod.transformer_train_flops_per_token(config, seq_len)))
    return out


def bench_serving(num_requests: int = 48, rate_hz: float = 16.0,
                  num_slots: int = 8, max_decode_len: int = 512,
                  d_model: int = 1024, n_layers: int = 12,
                  n_heads: int = 16, d_ff: int = 2816,
                  kv_page_size=None, kv_cache_dtype=None,
                  overcommit: bool = False,
                  kv_num_pages=None) -> dict:
    """Serving TTFT/TPOT under Poisson load through the HTTP front
    end (models/server.py + models/loadgen.py) — the latency surface
    an Orca/vLLM-class engine is judged by. Runs the d_model=1024
    12-layer model single-host on whatever accelerator is present."""
    import jax
    import jax.numpy as jnp
    from batch_shipyard_tpu.models import inference as inf
    from batch_shipyard_tpu.models import serving
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.models.loadgen import run_load
    from batch_shipyard_tpu.models.server import ServingFrontEnd
    config = tfm.TransformerConfig(
        vocab_size=32000, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, d_head=d_model // n_heads, d_ff=d_ff,
        max_seq_len=max_decode_len, dtype=jnp.bfloat16,
        kv_cache_dtype=kv_cache_dtype)
    model = tfm.TransformerLM(config)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = serving.ContinuousBatcher(
        config, params, num_slots=num_slots,
        max_decode_len=max_decode_len,
        kv_page_size=kv_page_size, kv_num_pages=kv_num_pages,
        overcommit=overcommit,
        sampling=inf.SamplingConfig())
    front = ServingFrontEnd(engine, port=0).start()
    try:
        # Warmup outside the measurement so compiles don't pollute
        # TTFT.
        front.generate({"prompt": [1, 2, 3], "max_new_tokens": 2})
        # Load profile scales with the decode budget: prompt+generation
        # stays within max_decode_len so no request is rejected.
        quarter = max(8, max_decode_len // 4)
        report = run_load(
            front.url, num_requests, rate_hz=rate_hz,
            prompt_len=(quarter // 2, quarter),
            max_new_tokens=(quarter // 2, quarter),
            vocab_size=32000, seed=0)
    finally:
        front.shutdown()
    return report


def bench_serving_speculative(num_requests: int = 32,
                              rate_hz: float = 16.0,
                              num_slots: int = 8,
                              max_decode_len: int = 512,
                              d_model: int = 1024, n_layers: int = 12,
                              n_heads: int = 16, d_ff: int = 2816,
                              draft_d_model: int = 256,
                              draft_n_layers: int = 2,
                              gamma: int = 4,
                              kv_page_size=None,
                              vocab_size: int = 32000) -> dict:
    """Speculative serving phase: the continuous-batching engine with
    a draft model drafting gamma tokens per slot per step and ONE
    batched target verify — measured through the same HTTP front end
    + Poisson loadgen as bench_serving, plus the engine's measured
    acceptance rate. The draft is random-init (no trained draft in
    the bench container), so acceptance is the worst case — the
    number to watch on silicon is tokens/s at a REAL draft's
    acceptance, which this phase measures once a draft checkpoint is
    wired in; TTFT/TPOT and acceptance-rate accounting are real
    either way. kv_page_size switches the target to the paged pool
    (the speculative verify block crosses page boundaries)."""
    import jax
    import jax.numpy as jnp
    from batch_shipyard_tpu.models import inference as inf
    from batch_shipyard_tpu.models import serving
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.models.loadgen import run_load
    from batch_shipyard_tpu.models.server import ServingFrontEnd
    config = tfm.TransformerConfig(
        vocab_size=vocab_size, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, d_head=d_model // n_heads, d_ff=d_ff,
        max_seq_len=max_decode_len, dtype=jnp.bfloat16)
    model = tfm.TransformerLM(config)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    draft_config = tfm.TransformerConfig(
        vocab_size=vocab_size, d_model=draft_d_model,
        n_layers=draft_n_layers, n_heads=n_heads,
        d_head=draft_d_model // n_heads, d_ff=draft_d_model * 3,
        max_seq_len=max_decode_len, dtype=jnp.bfloat16)
    draft_params = tfm.TransformerLM(draft_config).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32))["params"]
    engine = serving.ContinuousBatcher(
        config, params, num_slots=num_slots,
        max_decode_len=max_decode_len,
        kv_page_size=kv_page_size,
        sampling=inf.SamplingConfig(),
        speculative=serving.SpeculativeConfig(
            draft_config, draft_params, gamma=gamma))
    front = ServingFrontEnd(engine, port=0).start()
    try:
        front.generate({"prompt": [1, 2, 3], "max_new_tokens": 2})
        quarter = max(8, max_decode_len // 4)
        report = run_load(
            front.url, num_requests, rate_hz=rate_hz,
            prompt_len=(quarter // 2, quarter),
            max_new_tokens=(quarter // 2, quarter),
            vocab_size=vocab_size, seed=0)
        report["speculative"] = engine.spec_stats()
        report["kv_page_size"] = kv_page_size
    finally:
        front.shutdown()
    return report


def bench_serving_fleet(num_replicas: int = 2,
                        num_requests: int = 64,
                        rate_hz: float = 24.0,
                        num_slots: int = 8,
                        max_decode_len: int = 512,
                        d_model: int = 1024, n_layers: int = 12,
                        n_heads: int = 16, d_ff: int = 2816) -> dict:
    """Fleet phase: N replica engines (sharing one param set) behind
    the queue-depth-aware router (models/router.py), loadgen pointed
    at the single router URL — the deployment shape a real serving
    fleet uses, measured end to end."""
    import jax
    import jax.numpy as jnp
    from batch_shipyard_tpu.models import inference as inf
    from batch_shipyard_tpu.models import serving
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.models.loadgen import run_load
    from batch_shipyard_tpu.models.router import ServingRouter
    from batch_shipyard_tpu.models.server import ServingFrontEnd
    config = tfm.TransformerConfig(
        vocab_size=32000, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, d_head=d_model // n_heads, d_ff=d_ff,
        max_seq_len=max_decode_len, dtype=jnp.bfloat16)
    model = tfm.TransformerLM(config)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    fronts = []
    router = None
    try:
        for _ in range(num_replicas):
            engine = serving.ContinuousBatcher(
                config, params, num_slots=num_slots,
                max_decode_len=max_decode_len,
                sampling=inf.SamplingConfig())
            fronts.append(ServingFrontEnd(engine, port=0).start())
        router = ServingRouter([f.url for f in fronts],
                               health_interval=1.0).start()
        # Warmup through the router so compiles stay out of TTFT.
        for f in fronts:
            f.generate({"prompt": [1, 2, 3], "max_new_tokens": 2})
        quarter = max(8, max_decode_len // 4)
        report = run_load(
            router.url, num_requests, rate_hz=rate_hz,
            prompt_len=(quarter // 2, quarter),
            max_new_tokens=(quarter // 2, quarter),
            vocab_size=32000, seed=0)
        report["router"] = router.stats()
        report["num_replicas"] = num_replicas
        return report
    finally:
        if router is not None:
            router.shutdown()
        for f in fronts:
            f.shutdown()


def bench_serving_slo(num_requests: int = 24, rate_hz: float = 16.0,
                      num_slots: int = 4, max_decode_len: int = 128,
                      kv_page_size: int = 16,
                      shared_prefix_len: int = 96,
                      seed: int = 0,
                      artifact: bool = True) -> dict:
    """Cross-request prefix-cache + SLO phase (ISSUE 18): the SAME
    shared-prefix diurnal workload (identical seed => identical
    arrivals, prompts, and greedy outputs) through two engines that
    differ ONLY in ``prefix_cache`` — the treated arm reuses indexed
    KV pages across requests, the control re-prefills every prompt
    from scratch. Reports token-level prefix hit rate, per-class SLO
    attainment, and the exact (unbinned) TTFT mean/p99 deltas, and
    asserts the two arms' outputs are byte-identical (sha256 over
    every request's token ids) — the reuse must be free in tokens,
    paid for only in work skipped.

    fp32 end to end so "byte-identical" is a statement about the
    gather-vs-recompute paths, not about accumulated rounding.

    CPU marker: sized for the CPU bench container (d_model=256,
    4 layers); the deltas are honest relative measurements on
    whatever backend runs them."""
    import jax
    import jax.numpy as jnp
    from batch_shipyard_tpu.models import inference as inf
    from batch_shipyard_tpu.models import serving
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.models.loadgen import run_load
    from batch_shipyard_tpu.models.server import ServingFrontEnd
    config = tfm.TransformerConfig(
        vocab_size=4096, d_model=256, n_layers=4, n_heads=4,
        d_head=64, d_ff=1024, max_seq_len=max_decode_len,
        dtype=jnp.float32, param_dtype=jnp.float32)
    model = tfm.TransformerLM(config)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    slo_classes = {
        "interactive": {"ttft_ms": 5000.0, "tpot_ms": 500.0},
        "standard": {"ttft_ms": 20000.0, "tpot_ms": 2000.0},
        "batch": {"ttft_ms": None, "tpot_ms": None},
    }
    pages = num_slots * (max_decode_len // kv_page_size) + \
        2 * (shared_prefix_len // kv_page_size) + 4

    def run_arm(prefix_cache: bool) -> dict:
        engine = serving.ContinuousBatcher(
            config, params, num_slots=num_slots,
            max_decode_len=max_decode_len,
            kv_page_size=kv_page_size, kv_num_pages=pages,
            prefix_cache=prefix_cache,
            sampling=inf.SamplingConfig())
        # Warm every prefill bucket AND (via the shared warm-up
        # prompts) the shared-prefill suffix buckets before traffic,
        # so no arm pays a mid-run compile; warmup clears the prefix
        # index afterwards, so the treated arm still starts cold.
        engine.warmup()
        front = ServingFrontEnd(engine, port=0,
                                slo_classes=slo_classes).start()
        try:
            front.generate({"prompt": [1, 2, 3],
                            "max_new_tokens": 2})
            report = run_load(
                front.url, num_requests, rate_hz=rate_hz,
                prompt_len=(9, 16), max_new_tokens=(4, 12),
                vocab_size=config.vocab_size, seed=seed,
                arrival="diurnal", day_seconds=20.0,
                shared_prefix_groups=2,
                shared_prefix_len=shared_prefix_len,
                slo_classes=slo_classes)
            report["prefix_cache"] = engine.prefix_stats()
            report["engine_slo"] = engine.slo_stats()
        finally:
            front.shutdown()
        return report

    on = run_arm(True)
    off = run_arm(False)
    keep = ("completed", "failed", "shed", "ttft_mean_ms",
            "tpot_mean_ms", "ttft_exact_ms", "tpot_exact_ms",
            "ttft_ms", "tpot_ms", "tokens_per_second",
            "slo_attainment", "outputs_sha256")
    result = {
        "seed": seed,
        "cpu_marker": True,
        "platform": jax.default_backend(),
        "num_requests": num_requests,
        "arrival": "diurnal",
        "shared_prefix_groups": 2,
        "shared_prefix_len": shared_prefix_len,
        "kv_page_size": kv_page_size,
        "prefix_cache_on": {k: on[k] for k in keep if k in on},
        "prefix_cache_off": {k: off[k] for k in keep if k in off},
        "prefix_hit_rate": on["prefix_cache"]["hit_rate"],
        "prefix_hit_tokens": on["prefix_cache"]["hit_tokens"],
        "prefix_published_pages":
            on["prefix_cache"]["published_pages"],
        "outputs_identical":
            on["outputs_sha256"] == off["outputs_sha256"],
        "ttft_mean_delta_ms":
            on["ttft_mean_ms"] - off["ttft_mean_ms"],
        "ttft_p99_delta_ms": (on["ttft_exact_ms"]["p99"] -
                              off["ttft_exact_ms"]["p99"]),
        "tpot_mean_delta_ms":
            on["tpot_mean_ms"] - off["tpot_mean_ms"],
    }
    if artifact:
        with open(REPO_ROOT / "BENCH_serving_slo.json", "w",
                  encoding="utf-8") as fh:
            json.dump({"serving_slo": result}, fh, indent=2)
    return result


def bench_checkpoint_overhead(num_saves: int = 3,
                              payload_mb: int = 64) -> dict:
    """Checkpoint stall phase: blocking ms/save of the sync
    full-durability save vs the async double-buffered pipeline
    (workloads/checkpoint.AsyncCheckpointManager) on a synthetic
    large pytree. The async number is the snapshot-only cost the
    training loop actually pays; the persist overlaps subsequent
    steps (goodput scores it PROGRAM_CHECKPOINT_ASYNC, docs/28).
    The drain between timed async saves keeps the depth-1 queue
    bound out of the measurement — each sample is a clean
    snapshot+enqueue."""
    import shutil
    import tempfile

    import jax.numpy as jnp
    import numpy as np

    from batch_shipyard_tpu.workloads import checkpoint

    n_arrays = 8
    elems = payload_mb * 1024 * 1024 // 4 // n_arrays
    rng = np.random.RandomState(0)
    params = {f"w{i}": jnp.asarray(
        rng.randn(elems).astype(np.float32)) for i in range(n_arrays)}
    opt_state = {f"m{i}": jnp.zeros((elems,), jnp.float32)
                 for i in range(n_arrays)}
    tmp = tempfile.mkdtemp(prefix="shipyard-ckpt-bench-")
    try:
        sync_ms = []
        for i in range(num_saves):
            t0 = time.perf_counter()
            checkpoint.save(os.path.join(tmp, "sync"), i + 1,
                            params, opt_state)
            sync_ms.append((time.perf_counter() - t0) * 1e3)
        async_ms = []
        with checkpoint.AsyncCheckpointManager(
                os.path.join(tmp, "async")) as manager:
            for i in range(num_saves):
                t0 = time.perf_counter()
                manager.save(i + 1, params, opt_state)
                async_ms.append((time.perf_counter() - t0) * 1e3)
                manager.wait_until_finished()
        sync_best = min(sync_ms)
        async_best = min(async_ms)
        return {
            "payload_mb": payload_mb,
            "saves": num_saves,
            "sync_blocking_ms_per_save": round(sync_best, 2),
            "async_blocking_ms_per_save": round(async_best, 2),
            "blocking_speedup": (round(sync_best / async_best, 2)
                                 if async_best else None),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# Cold vs warm compile is only honest across PROCESSES: within one
# process the jit dispatch cache would make every second compile
# "warm" regardless of the persistent cache. The child builds a small
# transformer train step directly on models/transformer (no mesh
# machinery — single device suffices to time XLA) and reports its
# time-to-first-step; run 1 starts from an empty cache dir, run 2
# shares it and adds --aot-precompile's lower().compile() path.
_COMPILE_WARM_CHILD = r"""
import functools, json, os, sys, time
sys.path.insert(0, os.environ["SHIPYARD_BENCH_REPO"])
import jax
import jax.numpy as jnp
import numpy as np
import optax
from batch_shipyard_tpu.compilecache import manager
mgr = manager.enable(os.environ["SHIPYARD_BENCH_CACHE_DIR"])
from batch_shipyard_tpu.models import transformer as tfm
config = tfm.TransformerConfig(
    vocab_size=512, d_model=128, n_layers=2, n_heads=4, d_head=32,
    d_ff=256, max_seq_len=128, remat=False)
model = tfm.TransformerLM(config)
optimizer = optax.adamw(3e-4, weight_decay=0.01)

def loss_fn(params, tokens, targets):
    hidden, _ = model.apply({"params": params}, tokens,
                            return_hidden=True, mutable=["losses"])
    return tfm.lm_loss_chunked(hidden, params["embed"]["embedding"],
                               targets)

@jax.jit
def step(params, opt_state, tokens, targets):
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss

rng = np.random.RandomState(0)
tokens = jnp.asarray(rng.randint(0, 512, (2, 128)), jnp.int32)
targets = jnp.asarray(rng.randint(0, 512, (2, 128)), jnp.int32)
entries_before = len(mgr.entries())
with mgr.track("bench_compile_warm") as tracked:
    start = time.perf_counter()
    params = jax.jit(
        lambda r: model.init(r, tokens)["params"])(
            jax.random.PRNGKey(0))
    opt_state = optimizer.init(params)
    fn = step
    if os.environ.get("SHIPYARD_BENCH_AOT"):
        abstract = jax.ShapeDtypeStruct((2, 128), jnp.int32)
        fn = step.lower(params, opt_state, abstract,
                        abstract).compile()
    t_first = time.perf_counter()
    params, opt_state, loss = fn(params, opt_state, tokens, targets)
    float(loss)
    first_ms = (time.perf_counter() - t_first) * 1e3
    to_first_ms = (time.perf_counter() - start) * 1e3
steady = []
for _ in range(5):
    t0 = time.perf_counter()
    params, opt_state, loss = fn(params, opt_state, tokens, targets)
    float(loss)
    steady.append((time.perf_counter() - t0) * 1e3)
print(json.dumps({
    "time_to_first_step_ms": round(to_first_ms, 2),
    "first_step_ms": round(first_ms, 2),
    "steady_step_ms": round(min(steady), 2),
    "entries_before": entries_before,
    "new_entries": tracked["new_entries"],
    "cache_hit": tracked["cache_hit"],
    "aot": bool(os.environ.get("SHIPYARD_BENCH_AOT")),
}))
"""


def bench_compile_warm(timeout: float = 600.0) -> dict:
    """Warm-start compilation phase (compilecache/): the same small
    transformer train step in two fresh processes sharing one
    persistent compilation cache dir. Run 1 compiles cold and
    populates the cache; run 2 (--aot-precompile path) deserializes
    warm — cold_ms vs warm_ms is the whole badput the pool-wide
    seeding removes per node per restart, and run 2's first step
    matching its steady step shows AOT leaves no cold-compile
    spike."""
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="shipyard-compilecache-")
    try:
        runs = []
        for aot in ("", "1"):
            env = dict(
                os.environ,
                SHIPYARD_BENCH_REPO=str(REPO_ROOT),
                SHIPYARD_BENCH_CACHE_DIR=cache_dir,
                SHIPYARD_BENCH_AOT=aot)
            proc = subprocess.run(
                [sys.executable, "-c", _COMPILE_WARM_CHILD],
                capture_output=True, text=True, timeout=timeout,
                env=env)
            if proc.returncode != 0:
                return {"error": (proc.stderr or proc.stdout)[-800:]}
            runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        cold, warm = runs
        cold_ms = cold["time_to_first_step_ms"]
        warm_ms = warm["time_to_first_step_ms"]
        return {
            "cold_ms": cold_ms,
            "warm_ms": warm_ms,
            "speedup": (round(cold_ms / warm_ms, 2)
                        if warm_ms else None),
            # Entries the warm run reused instead of recompiling.
            "cache_hits": max(0, warm["entries_before"]
                              - warm["new_entries"]),
            "cold_first_step_ms": cold["first_step_ms"],
            "aot_first_step_ms": warm["first_step_ms"],
            "steady_step_ms": warm["steady_step_ms"],
            "cache_entries": cold["new_entries"],
        }
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def bench_ring_collectives(
        sizes_bytes=(1 << 18, 1 << 20, 1 << 22),
        virtual_ring: int = 4) -> dict:
    """Ring-collective kernel phase (ops/ring_collectives.py):
    numeric parity of the async-DMA Pallas ring
    all-gather/reduce-scatter against the lax collectives, plus
    per-size bandwidth rows. With >1 TPU device the real shard_map
    remote-DMA ring runs over the sp axis AND the equivalent lax
    collective is timed as the baseline; on a single TPU chip the
    virtual-ring kernels are compiled and timed (same Mosaic
    DMA/semaphore lowering, no ICI — labeled, not a bandwidth claim);
    on a non-TPU backend the kernels run in interpret mode for the
    parity check only (timings omitted — interpreting is not
    measuring)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np
    from batch_shipyard_tpu.ops import ring_collectives as rc
    from batch_shipyard_tpu.ops.collectives import (_collective_fn,
                                                    _timeit)
    from batch_shipyard_tpu.parallel import mesh as mesh_mod

    n_dev = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    multi = n_dev > 1 and on_tpu
    feat = 128
    itemsize = 4  # fp32
    rows = []
    numeric_ok = True
    rng = np.random.RandomState(0)

    def add_row(op, impl, nbytes, fn, arg, timed):
        rows.append({
            "op": op, "impl": impl, "bytes": nbytes,
            "seconds": _timeit(fn, arg) if timed else None,
        })

    if multi:
        mode = "remote_dma"
        ring = n_dev
        mesh = mesh_mod.make_mesh(
            mesh_mod.auto_axis_sizes(n_dev, sp=n_dev))
        lax_ag = _collective_fn(mesh, "sp", "all_gather")
        lax_rs = _collective_fn(mesh, "sp", "reduce_scatter")
        for size in sizes_bytes:
            chunk = max(8, size // itemsize // (ring * feat))
            chunk -= chunk % 8
            x = jnp.asarray(
                rng.randn(ring * chunk, feat), jnp.float32)
            ag = jax.jit(lambda x: rc.ring_all_gather(x, mesh, "sp"))
            numeric_ok &= bool(np.allclose(np.asarray(ag(x)),
                                           np.asarray(x), atol=1e-5))
            nbytes = x.nbytes
            add_row("ring_all_gather", "pallas_dma", nbytes, ag, x,
                    True)
            add_row("ring_all_gather", "lax", nbytes, lax_ag,
                    x.reshape(-1), True)
            y = jnp.asarray(
                rng.randn(ring, ring * chunk, feat), jnp.float32)
            rs = jax.jit(
                lambda y: rc.ring_reduce_scatter(y, mesh, "sp"))
            numeric_ok &= bool(np.allclose(
                np.asarray(rs(y)), np.asarray(jnp.sum(y, axis=0)),
                atol=1e-4))
            add_row("ring_reduce_scatter", "pallas_dma", nbytes, rs,
                    y, True)
            add_row("ring_reduce_scatter", "lax", nbytes, lax_rs,
                    y.reshape(-1), True)
    else:
        # Compiled on a single TPU chip (lowering + schedule proof);
        # interpret mode anywhere else (parity only, never timed).
        mode = "virtual" if on_tpu else "virtual_interpret"
        ring = virtual_ring
        ag_fn = functools.partial(rc.ring_all_gather_virtual,
                                  interpret=not on_tpu)
        rs_fn = functools.partial(rc.ring_reduce_scatter_virtual,
                                  interpret=not on_tpu)
        if on_tpu:
            ag_fn, rs_fn = jax.jit(ag_fn), jax.jit(rs_fn)
        for size in sizes_bytes:
            chunk = max(8, size // itemsize // (ring * feat))
            chunk -= chunk % 8
            x = jnp.asarray(rng.randn(ring, chunk, feat), jnp.float32)
            got = np.asarray(ag_fn(x))
            ref = np.asarray(x).reshape(ring * chunk, feat)
            numeric_ok &= all(
                np.allclose(got[i], ref, atol=1e-5)
                for i in range(ring))
            add_row("ring_all_gather", f"pallas_{mode}",
                    ring * chunk * feat * itemsize, ag_fn, x, on_tpu)
            y = jnp.asarray(rng.randn(ring, ring * chunk, feat),
                            jnp.float32)
            numeric_ok &= bool(np.allclose(
                np.asarray(rs_fn(y)),
                np.asarray(jnp.sum(y, axis=0)).reshape(
                    ring, chunk, feat), atol=1e-4))
            add_row("ring_reduce_scatter", f"pallas_{mode}",
                    ring * chunk * feat * itemsize, rs_fn, y, on_tpu)
    for row in rows:
        row["algo_bw_gbps"] = (
            row["bytes"] / row["seconds"] / 1e9
            if row["seconds"] else None)
    best = {}
    for op in ("ring_all_gather", "ring_reduce_scatter"):
        vals = [r["algo_bw_gbps"] for r in rows
                if r["op"] == op and r["impl"].startswith("pallas")
                and r["algo_bw_gbps"] is not None]
        best[f"best_{op.removeprefix('ring_')}_gbps"] = (
            round(max(vals), 3) if vals else None)
    return {
        "mode": mode, "ring": ring, "chips": n_dev,
        "numeric_ok": bool(numeric_ok), "rows": rows, **best,
    }


def bench_scheduler_scale(num_tasks: int = 1_000_000, nodes: int = 8,
                          slots: int = 4, shards: int = 8,
                          timeout: float = 3600.0,
                          artifact: bool = True) -> dict:
    """10^6-task end-to-end scheduler proof (ROADMAP item 3 / the TPU
    concurrency-limits scale wall, arxiv 2011.03641): drive
    ``num_tasks`` through the REAL scheduling path — O(1) client
    submission of the generator spec (server_side_expansion), the
    pool's leader-gated expander materializing rows + messages via
    the streaming pipelined submitter, sharded queue fan-out with
    grow-only autoscale, batched claims, state transitions, goodput +
    trace emission, queue drain — on the CPU fakepod substrate with
    the in-process task runtime (runtime: "inproc": the task body is
    a function call in the agent's worker thread, so per-task
    fork/exec cost stops dominating and the number measures
    SCHEDULING). Reports end-to-end throughput, the submit-leg
    breakdown (encode vs entity-insert vs enqueue vs expansion wall)
    and the exact goodput partition over the whole run; the drain
    loop polls the O(1) counting summary, never the task list.

    CPU marker: this is an orchestration measurement — no accelerator
    is involved, and none is claimed."""
    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.goodput import accounting
    from batch_shipyard_tpu.jobs import expansion as expansion_mod
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    from batch_shipyard_tpu.pool import manager as pool_mgr
    from batch_shipyard_tpu.state import names
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=1.0,
                                 node_stale_seconds=60.0)
    # Wide visibility windows: at 10^6 tasks a redelivered duplicate
    # costs a wasted claim round; nothing here crashes, so recovery
    # latency is irrelevant.
    substrate.agent_kwargs = {"claim_visibility_seconds": 120.0,
                              "gang_sweep_interval": 3600.0,
                              "preempt_sweep_interval": 3600.0}
    pool_id = "schedscale"
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": nodes}},
        "task_slots_per_node": slots,
        "task_queue_shards": shards,
        "max_wait_time_seconds": 120}}
    pool = S.pool_settings(conf)
    result: dict = {
        "substrate": (f"CPU fakepod ({nodes} thread-nodes x {slots} "
                      f"slots, {shards} queue shards), in-process "
                      f"task mode — orchestration measurement, no "
                      f"accelerator involved or claimed"),
        "num_tasks": num_tasks,
        "nodes": nodes, "slots_per_node": slots,
        "queue_shards": shards,
    }
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             S.global_settings(conf), conf)
        jobs = S.job_settings_list({"job_specifications": [{
            "id": "scale",
            "server_side_expansion": True,
            "tasks": [{"task_factory": {"repeat": num_tasks},
                       "runtime": "inproc", "command": "noop"}],
        }]})
        t0 = time.perf_counter()
        jobs_mgr.add_jobs(store, pool, jobs)
        client_submit_seconds = time.perf_counter() - t0
        t1 = time.perf_counter()
        # Drain on the O(1) counting summary (count_entities_by): at
        # 10^6 tasks a poll that listed every row would itself be the
        # bottleneck. The full task list is never materialized.
        summary = jobs_mgr.wait_for_job_summary(
            store, pool_id, "scale", timeout=timeout,
            poll_interval=2.0)
        run_seconds = time.perf_counter() - t1
        by_state = summary["by_state"]
        # Submit-leg breakdown comes from the expansion row the
        # pool-side expander completed: encode vs entity-insert vs
        # enqueue seconds, plus the expansion wall (all overlapped
        # with the agents' drain).
        exp_row = store.get_entity(names.TABLE_EXPANSIONS, pool_id,
                                   "scale")
        exp_stats = dict(exp_row.get(names.EXPANSION_COL_STATS) or {})
        expansion_wall = float(exp_stats.get("expand_seconds", 0.0))
        submit_seconds = client_submit_seconds + expansion_wall
        result.update({
            "server_side_expansion": True,
            "client_submit_seconds": round(client_submit_seconds, 3),
            # The materialization leg: client round trip + the
            # expander's wall clock (which overlaps the drain).
            "submit_seconds": round(submit_seconds, 3),
            "submit_tasks_per_second": round(
                num_tasks / max(submit_seconds, 1e-9), 1),
            "submit_breakdown": {
                "encode_seconds": round(
                    float(exp_stats.get("encode_seconds", 0.0)), 3),
                "entity_seconds": round(
                    float(exp_stats.get("entity_seconds", 0.0)), 3),
                "enqueue_seconds": round(
                    float(exp_stats.get("enqueue_seconds", 0.0)), 3),
                "expansion_wall_seconds": round(expansion_wall, 3),
                "chunks": int(exp_stats.get("chunks", 0)),
                "messages": int(exp_stats.get("messages", 0)),
                "queue_shards_final": jobs_mgr.pool_queue_shards(
                    store, pool_id, ttl=0),
            },
            "run_seconds": round(run_seconds, 3),
            "end_to_end_seconds": round(
                client_submit_seconds + run_seconds, 3),
            # Expansion and drain overlap, so the honest headline is
            # end-to-end; the post-submit drain rate is reported
            # separately.
            "end_to_end_tasks_per_second": round(
                num_tasks / (client_submit_seconds + run_seconds), 1),
            "tasks_per_second": round(num_tasks / run_seconds, 1),
            "by_state": by_state,
            "completed": by_state.get("completed", 0) == num_tasks,
        })
        # Exact goodput partition over the whole run: 10^6 tasks of
        # accounting input is itself part of the proof (the sweep is
        # O(N log N); a scan that chokes here would choke a real
        # pool's heimdall poll too).
        t2 = time.perf_counter()
        report = accounting.pool_report(store, pool_id,
                                        include_jobs=False)
        total = (report["productive_seconds"]
                 + sum(report["badput_seconds"].values())
                 + sum(report["overlapped_seconds"].values()))
        result["goodput"] = {
            "report_seconds": round(time.perf_counter() - t2, 3),
            "wall_seconds": report["wall_seconds"],
            "partition_total": total,
            "partition_exact": bool(
                abs(total - report["wall_seconds"]) <= max(
                    1e-6 * max(1.0, report["wall_seconds"]), 1e-6)),
            "goodput_ratio": report["goodput_ratio"],
            "badput_seconds": report["badput_seconds"],
        }
        final_shards = max(
            jobs_mgr.pool_queue_shards(store, pool_id, ttl=0), shards)
        queues = names.task_queues(pool_id, final_shards)
        result["queue_depth_after"] = sum(
            store.queue_length(q) for q in queues)
    finally:
        substrate.stop_all()
    if artifact:
        with open(REPO_ROOT / "BENCH_scheduler_scale.json", "w",
                  encoding="utf-8") as fh:
            json.dump({"scheduler_scale": result}, fh, indent=2)
    return result


def bench_fleet_elasticity(seed: int = 1,
                           artifact: bool = True) -> dict:
    """Fleet-elasticity proof (ROADMAP item 5 / ISSUE 12): run the
    three chaos drills — forcible eviction, multi-host resize with
    per-host reshard-on-restore, cross-pool migration — and record
    seeds, the invariants each asserted, pass/fail, and the priced
    recovery-leg seconds. Every invariant is asserted INSIDE the
    drill (chaos/drill.py), so a recorded "pass" is a replayed
    proof, not a summary.

    CPU marker: orchestration + recovery measurement on the CPU
    fakepod substrate — no accelerator is involved, and none is
    claimed."""
    from batch_shipyard_tpu.chaos import drill as chaos_drill

    drills = (
        ("eviction", chaos_drill.run_eviction_drill,
         "eviction"),
        ("host_resize", chaos_drill.run_host_resize_drill,
         "preemption_recovery"),
        ("migration", chaos_drill.run_migration_drill,
         "migration"),
    )
    result: dict = {"seed": seed, "cpu_marker": True, "drills": {}}
    for name, runner, leg in drills:
        started = time.monotonic()
        entry: dict = {"seed": seed, "recovery_leg": leg}
        try:
            report = runner(seed=seed)
            entry.update({
                "passed": bool(report["invariants"].get("ok")),
                "fingerprint": report["fingerprint"],
                "invariants_checked": sorted(
                    k for k in report["invariants"] if k != "ok"),
                "recovery_leg_seconds": report.get(
                    "goodput", {}).get("badput_seconds", {}).get(
                    leg, 0.0),
                "wall_seconds": round(
                    time.monotonic() - started, 2),
            })
        except Exception as exc:  # noqa: BLE001 - record the failure
            entry.update({"passed": False, "error": str(exc)})
        result["drills"][name] = entry
    result["all_passed"] = all(d.get("passed")
                               for d in result["drills"].values())
    if artifact:
        with open(REPO_ROOT / "BENCH_fleet_elasticity.json", "w",
                  encoding="utf-8") as fh:
            json.dump({"fleet_elasticity": result}, fh, indent=2)
    return result


def bench_control_plane(seed: int = 1,
                        artifact: bool = True) -> dict:
    """Control-plane partition-tolerance proof (ISSUE 13): run the
    three chaos drills — store-outage ride-through, leader
    partition, agent crash-restart adoption — and record seeds, the
    invariants each asserted, pass/fail, and the priced recovery-leg
    seconds. Every invariant is asserted INSIDE the drill
    (chaos/drill.py), so a recorded "pass" is a replayed proof, not
    a summary.

    CPU marker: orchestration + recovery measurement on the CPU
    fakepod substrate — no accelerator is involved, and none is
    claimed."""
    from batch_shipyard_tpu.chaos import drill as chaos_drill

    drills = (
        ("store_outage", chaos_drill.run_store_outage_drill,
         "store_outage"),
        ("leader_partition", chaos_drill.run_leader_partition_drill,
         "preemption_recovery"),
        ("agent_restart", chaos_drill.run_agent_restart_drill,
         "adoption"),
    )
    result: dict = {"seed": seed, "cpu_marker": True, "drills": {}}
    for name, runner, leg in drills:
        started = time.monotonic()
        entry: dict = {"seed": seed, "recovery_leg": leg}
        try:
            report = runner(seed=seed)
            entry.update({
                "passed": bool(report["invariants"].get("ok")),
                "fingerprint": report["fingerprint"],
                "invariants_checked": sorted(
                    k for k in report["invariants"] if k != "ok"),
                "recovery_leg_seconds": report.get(
                    "goodput", {}).get("badput_seconds", {}).get(
                    leg, 0.0),
                "wall_seconds": round(
                    time.monotonic() - started, 2),
            })
        except Exception as exc:  # noqa: BLE001 - record the failure
            entry.update({"passed": False, "error": str(exc)})
        result["drills"][name] = entry
    result["all_passed"] = all(d.get("passed")
                               for d in result["drills"].values())
    if artifact:
        with open(REPO_ROOT / "BENCH_control_plane.json", "w",
                  encoding="utf-8") as fh:
            json.dump({"control_plane": result}, fh, indent=2)
    return result


def bench_serving_resilience(seed: int = 1,
                             artifact: bool = True) -> dict:
    """Serving-tier fault-tolerance proof: run the three serving
    chaos drills — replica kill, replica drain-on-notice, router
    restart (chaos/serving_drill.py) — and record seeds, the
    invariants each asserted, pass/fail, and the priced
    ``serving_recovery`` leg seconds. Every invariant (zero lost
    requests, exactly-once token delivery, byte-identical greedy
    streams across the fault, exact goodput partition) is asserted
    INSIDE the drill, so a recorded "pass" is a replayed proof, not
    a summary.

    CPU marker: real HTTP replicas + router over tiny fp32 CPU
    engines — no accelerator is involved, and none is claimed."""
    from batch_shipyard_tpu.chaos import serving_drill

    drills = (
        ("replica_kill", serving_drill.run_replica_kill_drill,
         "serving_recovery"),
        ("replica_drain", serving_drill.run_replica_drain_drill,
         "serving_recovery"),
        ("router_restart", serving_drill.run_router_restart_drill,
         "serving_recovery"),
    )
    result: dict = {"seed": seed, "cpu_marker": True, "drills": {}}
    for name, runner, leg in drills:
        started = time.monotonic()
        entry: dict = {"seed": seed, "recovery_leg": leg}
        try:
            report = runner(seed=seed)
            entry.update({
                "passed": bool(report["invariants"].get("ok")),
                "fingerprint": report["fingerprint"],
                "invariants_checked": sorted(
                    k for k in report["invariants"] if k != "ok"),
                "recovery_leg_seconds": report.get(
                    "goodput", {}).get("badput_seconds", {}).get(
                    leg, 0.0),
                "wall_seconds": round(
                    time.monotonic() - started, 2),
            })
        except Exception as exc:  # noqa: BLE001 - record the failure
            entry.update({"passed": False, "error": str(exc)})
        result["drills"][name] = entry
    result["all_passed"] = all(d.get("passed")
                               for d in result["drills"].values())
    if artifact:
        with open(REPO_ROOT / "BENCH_serving_resilience.json", "w",
                  encoding="utf-8") as fh:
            json.dump({"serving_resilience": result}, fh, indent=2)
    return result


def bench_fleet_sim(seed: int = 1, nodes: int = 2000,
                    tasks: int = 100_000,
                    artifact: bool = True) -> dict:
    """Fleet-simulator policy proof (ISSUE 17): run the discrete-
    event simulator (sim/) at fleet scale — >=2,000 virtual nodes,
    >=10^5 tasks — under every policy bundle (sched/policy.py
    POLICIES) on three scenarios, and record each policy's FULL
    goodput partition plus its delta vs the baseline bundle:

      * ``steady``          — warm-cache claim affinity territory,
      * ``preemption_wave`` — the chaos-schedule scenario (a seeded
        provider wave kills 30% of the fleet mid-run in virtual
        time),
      * ``priority_burst``  — goodput-cost victim selection
        territory (a narrow high-priority burst must elect victims).

    The policies under test are the same pure functions the live
    agent claim path, preemption sweep, and pool autoscaler import
    (no forked copies — asserted by tests/test_fleet_sim.py), so a
    delta here is a statement about production decision code under
    the production pricing engine (goodput/accounting.py). Every
    recorded partition is exact: productive + badput + overlapped ==
    node-seconds wall to fp tolerance.

    CPU marker: a discrete-event simulation on a virtual clock — no
    accelerator is involved, and none is claimed."""
    from batch_shipyard_tpu.sched import policy as sched_policy
    from batch_shipyard_tpu.sim import scenarios as sim_scenarios
    from batch_shipyard_tpu.sim import simulator as sim_mod

    result: dict = {"seed": seed, "nodes": nodes, "tasks": tasks,
                    "cpu_marker": True,
                    "policies": sorted(sched_policy.POLICIES),
                    "scenarios": {}}
    for scenario in ("steady", "preemption_wave", "priority_burst"):
        reports: dict = {}
        wall: dict = {}
        for policy in sched_policy.POLICIES:
            started = time.monotonic()
            kwargs = sim_scenarios.build(scenario, seed, nodes, tasks)
            reports[policy] = sim_mod.run_sim(policy=policy, **kwargs)
            wall[policy] = round(time.monotonic() - started, 2)
        compared = sim_mod.compare(reports)
        section: dict = {}
        for policy, entry in compared.items():
            rep = entry["report"]
            row = {
                "fingerprint": rep["fingerprint"],
                "partition_exact": rep["partition_exact"],
                "virtual_seconds": rep["virtual_seconds"],
                "bench_wall_seconds": wall[policy],
                "goodput": rep["goodput"],
                "scheduler": {
                    k: rep["scheduler"][k]
                    for k in ("tasks_completed", "queue_wait_mean",
                              "deferrals", "sweep_victims",
                              "preemptions", "evictions",
                              "replayed_steps", "nodes_added",
                              "nodes_removed")
                    if k in rep["scheduler"]},
            }
            if "delta_vs_baseline" in entry:
                row["delta_vs_baseline"] = entry["delta_vs_baseline"]
                row["queue_wait_mean_delta"] = \
                    entry["queue_wait_mean_delta"]
            section[policy] = row
        result["scenarios"][scenario] = section
    result["all_partitions_exact"] = all(
        row["partition_exact"]
        for section in result["scenarios"].values()
        for row in section.values())
    if artifact:
        with open(REPO_ROOT / "BENCH_fleet_sim.json", "w",
                  encoding="utf-8") as fh:
            json.dump({"fleet_sim": result}, fh, indent=2)
    return result


def bench_orchestration_latency() -> dict:
    """pool-add -> task-start latency through the framework (the
    second BASELINE.md metric), on the LOCALHOST substrate: real
    subprocess node agents over the localfs store running the real
    nodeprep path — honest framework overhead, not fake-thread timing
    (round-1 weak #5). Docker is absent in the bench container, so the
    image-prefetch phase is reported as unavailable rather than faked;
    every other phase comes from the perf-event pipeline
    (agent/perf.py), and the text gantt is published to
    BENCH_GANTT.txt."""
    import shutil
    import tempfile

    import numpy as _np

    from batch_shipyard_tpu.agent import cascade
    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.graph import perf_graph
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    from batch_shipyard_tpu.pool import manager as pool_mgr
    from batch_shipyard_tpu.state.localfs import LocalFSStateStore
    from batch_shipyard_tpu.substrate.localhost import (
        LocalhostSubstrate)

    tmp = tempfile.mkdtemp(prefix="shipyard-bench-")
    store = LocalFSStateStore(os.path.join(tmp, "store"))
    conf = {"pool_specification": {
        "id": "benchpool", "substrate": "localhost",
        "vm_configuration": {"vm_count": {"dedicated": 2}},
        "max_wait_time_seconds": 120}}
    # Image prefetch rides cascade's direct-download mode (docker is
    # absent in the bench container): preload two 24 MB "image"
    # tarballs into the object store; both nodes stream them through
    # the lease gate during nodeprep — real bytes, real store path.
    image_mb = 24
    images = ["bench/imageA:1", "bench/imageB:1"]
    rng_blob = _np.random.RandomState(0)
    for image in images:
        blob = rng_blob.bytes(1024 * 1024)
        cascade.preload_image_tarball(
            store, "benchpool", image,
            (blob for _ in range(image_mb)))
    conf["global_resources"] = {"docker_images": list(images)}
    creds = S.credentials_settings({"credentials": {"storage": {
        "backend": "localfs", "root": os.path.join(tmp, "store")}}})
    substrate = LocalhostSubstrate(
        store, creds, work_root=os.path.join(tmp, "nodes"),
        pool_config=conf, run_nodeprep=True)
    pool = S.pool_settings(conf)
    try:
        t0 = time.perf_counter()
        pool_mgr.create_pool(store, substrate, pool,
                             S.global_settings(conf), conf)
        pool_ready = time.perf_counter() - t0
        jobs = S.job_settings_list({"job_specifications": [{
            "id": "benchjob",
            "tasks": [{"command": "true"}]}]})
        t1 = time.perf_counter()
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "benchpool", "benchjob",
                                        timeout=120)
        task_done = time.perf_counter() - t1

        # Phase breakdown from the perf-event pipeline.
        from batch_shipyard_tpu.agent import perf as perf_mod
        events = perf_mod.query(store, "benchpool")
        by_node: dict = {}
        for ev in events:
            by_node.setdefault(ev["node_id"], {})[
                f"{ev['source']}:{ev['event']}"] = ev["timestamp"]
        phases = {}
        for node, evs in by_node.items():
            np_start = evs.get("nodeprep:start")
            np_end = evs.get("nodeprep:end")
            if np_start and np_end:
                phases.setdefault("nodeprep_seconds", []).append(
                    np_end - np_start)
            pull_starts = [ts for name, ts in evs.items()
                           if name.startswith("cascade:pull.start:")]
            pull_ends = [ts for name, ts in evs.items()
                         if name.startswith("cascade:pull.end:")]
            if pull_starts and pull_ends:
                phases.setdefault("image_prefetch_seconds", []).append(
                    max(pull_ends) - min(pull_starts))
        summary = {k: max(v) for k, v in phases.items()}
        summary["image_prefetch_mb_per_image"] = image_mb
        summary["image_prefetch_images"] = len(images)
        try:
            with open(REPO_ROOT / "BENCH_GANTT.txt", "w",
                      encoding="utf-8") as fh:
                fh.write(perf_graph.render_text_gantt(
                    perf_graph.coalesce_data(store, "benchpool")))
        except Exception:
            pass
        started = tasks[0].get("started_at")
        return {
            "substrate": "localhost (real subprocess agents, real "
                         "nodeprep; image prefetch via cascade "
                         "direct-download of preloaded tarballs — "
                         "docker absent in bench container)",
            "pool_add_to_ready_seconds": pool_ready,
            "submit_to_task_complete_seconds": task_done,
            "image_prefetch_seconds": None,
            "task_started_at": started,
            **summary,
        }
    finally:
        substrate.deallocate_pool("benchpool")
        shutil.rmtree(tmp, ignore_errors=True)


def _probe_devices(timeout: float = 240.0):
    """Device init in a subprocess with a hard timeout: a wedged
    accelerator relay must produce an honest failure record — with
    the real cause — not a hung bench run. Returns None on success,
    else a reason string (shared helper: utils/util.py
    probe_default_devices, also used by __graft_entry__)."""
    from batch_shipyard_tpu.utils.util import probe_default_devices
    count, reason = probe_default_devices(timeout=timeout)
    if reason is not None:
        return reason
    if count < 1:
        return "device probe found no devices"
    return None


def _apply_persisted_tuning_winner() -> None:
    """If a tuning A/B has been run (tools/silicon_proof.py writes
    TUNING_SELECTED.json), default to its winning profile so every
    later bench — including the driver's end-of-round run — keeps the
    measured win. An explicit SHIPYARD_XLA_TUNING always overrides."""
    if os.environ.get("SHIPYARD_XLA_TUNING"):
        return
    try:
        with open(REPO_ROOT / "TUNING_SELECTED.json",
                  encoding="utf-8") as fh:
            winner = json.load(fh).get("winner")
    except (OSError, ValueError):
        return
    if winner:
        os.environ["SHIPYARD_XLA_TUNING"] = winner


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--workloads", default="resnet,transformer,serving,"
        "orchestration",
        help="comma-separated subset to run (resnet, transformer, "
        "serving, serving_speculative, checkpoint_overhead, "
        "compile_warm, ring_collectives, orchestration, "
        "scheduler_scale, fleet_sim, serving_slo, "
        "serving_resilience; "
        "serving_speculative, "
        "checkpoint_overhead, compile_warm, ring_collectives, "
        "scheduler_scale, fleet_sim, serving_slo and "
        "serving_resilience are opt-in — the "
        "silicon-proof pipeline runs each as its own phase; "
        "scheduler_scale drives 10^6 in-process tasks through the "
        "CPU fakepod scheduler end-to-end; fleet_sim runs the "
        "discrete-event policy simulator at 2000 virtual nodes)")
    parser.add_argument(
        "--scale-tasks", type=int, default=1_000_000,
        help="scheduler_scale task count (the 10^6 proof)")
    parser.add_argument(
        "--quick", action="store_true",
        help="fewer timed iterations (tuning A/B mode)")
    parser.add_argument(
        "--details-out", default=str(REPO_ROOT / "BENCH_DETAILS.json"),
        help="where to write the detailed sub-metrics JSON")
    args = parser.parse_args(argv)
    workloads = {w.strip() for w in args.workloads.split(",") if
                 w.strip()}
    details_out = pathlib.Path(args.details_out)

    # Tuning profile (SHIPYARD_XLA_TUNING) must land in the env before
    # the first backend init in this process (parallel/tuning.py).
    from batch_shipyard_tpu.parallel.tuning import apply_tuning_env
    _apply_persisted_tuning_winner()
    # Partial runs (--workloads subset) must not destroy the sections
    # other runs committed: seed from the existing details file and
    # refresh only the keys this invocation owns.
    details: dict = {"platform": None}
    if details_out.exists():
        try:
            with open(details_out, encoding="utf-8") as fh:
                prev_details = json.load(fh)
            if isinstance(prev_details, dict):
                details = prev_details
        except Exception:  # noqa: BLE001 - corrupt file: start fresh
            pass
    details["platform"] = None
    details["xla_tuning_profile"] = apply_tuning_env()
    probe_error = _probe_devices()
    if probe_error is not None:
        # Orchestration latency needs no accelerator; measure it and
        # report the compute metric as an explicit failure.
        if "orchestration" in workloads:
            try:
                details["orchestration"] = (
                    bench_orchestration_latency())
            except Exception as exc:  # noqa: BLE001
                details["orchestration"] = {"error": str(exc)}
        if "scheduler_scale" in workloads:
            # Pure orchestration too: the 10^6 proof runs on CPU
            # thread-nodes regardless of accelerator health.
            try:
                details["scheduler_scale"] = bench_scheduler_scale(
                    num_tasks=args.scale_tasks)
            except Exception as exc:  # noqa: BLE001
                details["scheduler_scale"] = {"error": str(exc)}
        if "fleet_elasticity" in workloads:
            # CPU-fakepod recovery drills: no accelerator involved.
            try:
                details["fleet_elasticity"] = (
                    bench_fleet_elasticity())
            except Exception as exc:  # noqa: BLE001
                details["fleet_elasticity"] = {"error": str(exc)}
        if "control_plane" in workloads:
            # CPU-fakepod control-plane drills: no accelerator
            # involved.
            try:
                details["control_plane"] = bench_control_plane()
            except Exception as exc:  # noqa: BLE001
                details["control_plane"] = {"error": str(exc)}
        if "fleet_sim" in workloads:
            # Discrete-event simulator on a virtual clock: no
            # accelerator involved.
            try:
                details["fleet_sim"] = bench_fleet_sim()
            except Exception as exc:  # noqa: BLE001
                details["fleet_sim"] = {"error": str(exc)}
        if "serving_slo" in workloads:
            # Prefix-cache A/B + SLO attainment: runs on whatever
            # backend jax falls back to (cpu_marker in artifact).
            try:
                details["serving_slo"] = bench_serving_slo()
            except Exception as exc:  # noqa: BLE001
                details["serving_slo"] = {"error": str(exc)}
        if "serving_resilience" in workloads:
            # Serving chaos drills on CPU fakepod replicas: no
            # accelerator involved.
            try:
                details["serving_resilience"] = (
                    bench_serving_resilience())
            except Exception as exc:  # noqa: BLE001
                details["serving_resilience"] = {"error": str(exc)}
        details["error"] = (f"accelerator unreachable "
                            f"({probe_error}); compute benches "
                            f"not run")
        details.pop("devices", None)  # no backend initialized
        # Demote the seeded previous run's compute figures to the
        # stale record (chaining through consecutive failures: the
        # seeded details already carry any earlier stale record).
        stale = {}
        for key in ("resnet50", "transformer"):
            section = details.pop(key, None)
            if isinstance(section, dict) and "error" not in section:
                stale[key] = section
        if stale:
            details["last_successful_run_stale"] = stale
        with open(details_out, "w", encoding="utf-8") as fh:
            json.dump(details, fh, indent=2)
        print(json.dumps({
            "metric": "ResNet-50 train images/sec/chip (bf16, b=256, "
                      "synthetic)",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": "accelerator unreachable",
        }))
        return 1
    import jax
    details["platform"] = jax.default_backend()
    details["devices"] = [str(d) for d in jax.devices()]
    # The probe SUCCEEDED: a seeded unreachable-accelerator marker
    # from a previous failed run no longer describes this record,
    # whatever subset of workloads runs — the live platform/devices
    # fields above would contradict it.
    details.pop("error", None)
    if workloads & {"resnet", "transformer", "serving"}:
        # Compute benches ARE running this time: fresh figures
        # supersede the stale ones kept for reference.
        details.pop("last_successful_run_stale", None)
    quick = {"warmup": 2, "iters": 4} if args.quick else {}
    resnet = None
    if "resnet" in workloads:
        resnet = bench_resnet(**quick)
        details["resnet50"] = resnet
    if "transformer" in workloads:
        tquick = ({"warmup": 1, "iters": 3} if args.quick else {})
        # Fused RMSNorm+matmul Pallas projections first (the MFU
        # lever); if Mosaic rejects the kernel on this chip, fall
        # back to the unfused path and record both outcomes.
        try:
            details["transformer"] = bench_transformer(
                fused_norm=True, **tquick)
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["transformer_fused_error"] = str(exc)
            try:
                details["transformer"] = bench_transformer(**tquick)
            except Exception as exc2:  # noqa: BLE001
                details["transformer"] = {"error": str(exc2)}
        if ("error" not in details.get("transformer", {})
                and "transformer_fused_error" not in details
                and not args.quick):
            # Unfused comparison point for the A/B. Skipped when the
            # fused kernel failed — the fallback already ran unfused.
            try:
                details["transformer_unfused"] = bench_transformer()
            except Exception as exc:  # noqa: BLE001
                details["transformer_unfused"] = {"error": str(exc)}
        if not args.quick:
            try:
                details["transformer_int8"] = bench_transformer(
                    quantize=True)
            except Exception as exc:  # noqa: BLE001 - experimental
                details["transformer_int8"] = {"error": str(exc)}
    if "serving" in workloads:
        try:
            details["serving"] = bench_serving()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["serving"] = {"error": str(exc)}
        if not args.quick:
            try:
                # The 2x-capacity configuration: int8 paged pool
                # with overcommit admission, sized BELOW worst case
                # (40 of 64 pages) so the preemption/pressure path
                # actually runs under the measured load.
                details["serving_paged_int8"] = bench_serving(
                    kv_page_size=64, kv_cache_dtype="int8",
                    overcommit=True, kv_num_pages=40)
            except Exception as exc:  # noqa: BLE001 - secondary
                details["serving_paged_int8"] = {"error": str(exc)}
        try:
            details["serving_fleet"] = bench_serving_fleet()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["serving_fleet"] = {"error": str(exc)}
    if "serving_speculative" in workloads:
        # Dense and paged variants: tokens/s, TTFT/TPOT, and the
        # measured acceptance rate. Opt-in ONLY (not implied by
        # "serving"): tools/silicon_proof.py runs it as its own
        # serving_speculative phase, so the full final_bench doesn't
        # pay these heavy benches a second time.
        try:
            details["serving_speculative"] = (
                bench_serving_speculative())
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["serving_speculative"] = {"error": str(exc)}
        try:
            details["serving_speculative_paged"] = (
                bench_serving_speculative(kv_page_size=64))
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["serving_speculative_paged"] = {
                "error": str(exc)}
    if "checkpoint_overhead" in workloads:
        # Opt-in (the silicon-proof checkpoint_overhead phase): sync
        # vs async blocking ms/save on a synthetic large pytree.
        try:
            details["checkpoint_overhead"] = bench_checkpoint_overhead(
                payload_mb=16 if args.quick else 64)
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["checkpoint_overhead"] = {"error": str(exc)}
    if "compile_warm" in workloads:
        # Opt-in (the silicon-proof compile_warm phase): cold vs warm
        # persistent-cache compile wall time in fresh subprocesses —
        # runs on CPU, no orchestration needed.
        try:
            details["compile_warm"] = bench_compile_warm()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["compile_warm"] = {"error": str(exc)}
    if "ring_collectives" in workloads:
        # Opt-in (the silicon-proof ring_collectives phase): async-DMA
        # ring kernel bandwidth + parity vs the lax collectives.
        try:
            details["ring_collectives"] = bench_ring_collectives()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["ring_collectives"] = {"error": str(exc)}
    if "orchestration" in workloads:
        try:
            details["orchestration"] = bench_orchestration_latency()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["orchestration"] = {"error": str(exc)}
    if "scheduler_scale" in workloads:
        # Opt-in (the 10^6-task end-to-end scheduler proof): CPU
        # fakepod + in-process task mode, no accelerator involved.
        try:
            details["scheduler_scale"] = bench_scheduler_scale(
                num_tasks=args.scale_tasks)
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["scheduler_scale"] = {"error": str(exc)}
    if "fleet_elasticity" in workloads:
        # Opt-in (the ISSUE 12 fleet-elasticity drills): CPU fakepod
        # recovery proof, no accelerator involved.
        try:
            details["fleet_elasticity"] = bench_fleet_elasticity()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["fleet_elasticity"] = {"error": str(exc)}
    if "control_plane" in workloads:
        # Opt-in (the ISSUE 13 control-plane drills): store-outage
        # ride-through, leader partition, crash-restart adoption on
        # the CPU fakepod — no accelerator involved.
        try:
            details["control_plane"] = bench_control_plane()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["control_plane"] = {"error": str(exc)}
    if "fleet_sim" in workloads:
        # Opt-in (the ISSUE 17 fleet-simulator policy proof): the
        # discrete-event simulator at >=2,000 virtual nodes under
        # every policy bundle — virtual clock, no accelerator
        # involved.
        try:
            details["fleet_sim"] = bench_fleet_sim()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["fleet_sim"] = {"error": str(exc)}
    if "serving_slo" in workloads:
        # Opt-in (the ISSUE 18 prefix-cache proof): the SAME
        # shared-prefix diurnal workload through prefix-cache-on and
        # -off engines at one seed — hit rate, SLO attainment, exact
        # TTFT deltas, byte-identical greedy outputs.
        try:
            details["serving_slo"] = bench_serving_slo()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["serving_slo"] = {"error": str(exc)}
    if "serving_resilience" in workloads:
        # Opt-in (the ISSUE 20 serving fault-tolerance proof): the
        # three serving chaos drills — replica kill, drain-on-notice,
        # router restart — each asserting zero lost requests,
        # exactly-once token delivery, and byte-identical greedy
        # streams across the fault. CPU fakepod replicas.
        try:
            details["serving_resilience"] = bench_serving_resilience()
        except Exception as exc:  # noqa: BLE001 - secondary metric
            details["serving_resilience"] = {"error": str(exc)}
    with open(details_out, "w", encoding="utf-8") as fh:
        json.dump(details, fh, indent=2)
    if resnet is not None:
        print(json.dumps({
            "metric": "ResNet-50 train images/sec/chip (bf16, b=256, "
                      "synthetic)",
            "value": round(resnet["images_per_sec_per_chip"], 2),
            "unit": "images/sec/chip",
            "vs_baseline": round(
                resnet["images_per_sec_per_chip"] /
                V100_BASELINE_IMG_PER_SEC, 3),
            "mfu_pct": resnet.get("mfu_pct"),
        }))
    else:
        tfm = details.get("transformer", {})
        print(json.dumps({
            "metric": "transformer train tokens/sec/chip "
                      "(bf16, 303M params, T=2048)",
            "value": round(tfm.get("tokens_per_sec_per_chip", 0.0),
                           1),
            "unit": "tokens/sec/chip",
            "vs_baseline": 0.0,
            "mfu_pct": tfm.get("mfu_pct"),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())

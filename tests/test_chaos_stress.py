"""Scheduler stress + chaos (fault-injection) tests: the continuous
failure-recovery exercise SURVEY.md 5.3 notes the reference never had.

Deterministic seeded fault schedules live in tests/test_chaos_recovery
(chaos/); this file keeps the randomized soak/stress load. Timing
rules: completion waits are poll-with-deadline (wait_for_tasks), and
wall-clock budget assertions only appear in tests small enough that
container load can't starve them — the 10k-task variant is marked
``slow`` (excluded from tier-1) because a loaded CI container can't
promise 10k subprocess spawns inside any honest fixed budget."""

import json
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def test_scheduler_stress_120_tasks():
    """120 tasks across 4 nodes x 4 slots complete, each exactly
    once."""
    conf = {"pool_specification": {
        "id": "stress", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16"},
        "task_slots_per_node": 4,
        "max_wait_time_seconds": 30}}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    try:
        pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "big",
            "tasks": [{"id": f"t{i:03d}",
                       "command": f"echo done-{i}"}
                      for i in range(120)],
        }]})
        start = time.monotonic()
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "stress", "big",
                                        timeout=120,
                                        poll_interval=0.5)
        elapsed = time.monotonic() - start
        assert len(tasks) == 120
        assert all(t["state"] == "completed" for t in tasks)
        # Exactly-once effects: every task's stdout has one line.
        for i in (0, 59, 119):
            out = jobs_mgr.get_task_output(
                store, "stress", "big", f"t{i:03d}")
            assert out.strip() == f"done-{i}".encode()
        # Sanity throughput: 16 slots should crush 120 echoes well
        # inside the wait deadline (the poll above IS the budget;
        # this catches a pathological near-timeout crawl).
        assert elapsed < 115
    finally:
        substrate.stop_all()


def test_chaos_tasks_survive_agent_crashes():
    """Random agent crashes + revivals while 40 tasks run: everything
    still completes via redelivery + orphan reclaim."""
    conf = {"pool_specification": {
        "id": "chaos", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16"},
        "task_slots_per_node": 2,
        "max_wait_time_seconds": 30}}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, node_stale_seconds=3.0)
    pool = settings_mod.pool_settings(conf)
    stop_chaos = None
    try:
        pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
        stop_chaos = substrate.start_chaos(
            "chaos", kill_interval=0.7, revive_after=0.3, seed=42)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "survivor",
            "tasks": [{"id": f"t{i:02d}",
                       "command": f"sleep 0.2 && echo alive-{i}"}
                      for i in range(40)],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "chaos", "survivor",
                                        timeout=180, poll_interval=0.5)
        assert all(t["state"] == "completed" for t in tasks), {
            t["_rk"]: t["state"] for t in tasks
            if t["state"] != "completed"}
        for i in (0, 39):
            out = jobs_mgr.get_task_output(
                store, "chaos", "survivor", f"t{i:02d}")
            assert out.strip() == f"alive-{i}".encode()
    finally:
        if stop_chaos is not None:
            stop_chaos.set()
        substrate.stop_all()


@pytest.mark.slow
def test_scheduler_stress_10k_tasks_sharded_queues():
    """10,000 tasks across 16 fake nodes with 8-way sharded task
    queues complete exactly once under a time budget (VERDICT r1 #8:
    two orders of magnitude beyond the old 120-task regime).

    ``slow``: 10k subprocess spawns take minutes and the wall budget
    is honest only on an unloaded machine — run explicitly via
    `pytest -m slow`; tier-1 covers the same invariants at 120-task
    scale plus the seeded drills in test_chaos_recovery."""
    conf = {"pool_specification": {
        "id": "stress10k", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-64"},
        "task_slots_per_node": 2,
        "task_queue_shards": 8,
        "max_wait_time_seconds": 60}}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    assert pool.tpu.total_workers == 16
    try:
        pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "huge",
            "tasks": [{"id": f"t{i:05d}", "command": "true",
                       "runtime": "none"}
                      for i in range(10_000)],
        }]})
        start = time.monotonic()
        jobs_mgr.add_jobs(store, pool, jobs)
        submit_elapsed = time.monotonic() - start
        # Batched entity writes: submission itself must be fast.
        assert submit_elapsed < 30, submit_elapsed
        # The crc32 fan-out spreads tasks over every shard (checked on
        # the routing function — live queue lengths race with the
        # already-consuming agents).
        from collections import Counter

        from batch_shipyard_tpu.state import names
        spread = Counter(names.task_queue_for("stress10k", f"t{i:05d}", 8)
                         for i in range(10_000))
        assert len(spread) == 8 and min(spread.values()) > 500, spread
        tasks = jobs_mgr.wait_for_tasks(store, "stress10k", "huge",
                                        timeout=420)
        elapsed = time.monotonic() - start
        assert len(tasks) == 10_000
        states = {}
        for t in tasks:
            states[t["state"]] = states.get(t["state"], 0) + 1
        assert states == {"completed": 10_000}, states
        assert elapsed < 420, elapsed
    finally:
        substrate.stop_all()


def test_submission_scale_100k_queueing():
    """10^5-task submission scale (ROADMAP 'scheduler scale'):
    batched entity+message writes stay fast, the crc32 fan-out stays
    balanced at 16 shards, and queue pops drain correctly — the
    queueing layer itself, without paying 10^5 subprocess executions
    (the 10k test above covers end-to-end execution)."""
    from collections import Counter

    from batch_shipyard_tpu.state import names

    n = 100_000
    conf = {"pool_specification": {
        "id": "s100k", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-64"},
        "task_queue_shards": 16,
        "max_wait_time_seconds": 60}}
    store = MemoryStateStore()
    pool = settings_mod.pool_settings(conf)
    # No substrate/agents: pure queueing-layer scale.
    store.insert_entity(names.TABLE_POOLS, "pools", "s100k", {
        "state": "ready", "spec": conf})
    jobs = settings_mod.job_settings_list({"job_specifications": [{
        "id": "vast",
        "tasks": [{"command": "true", "runtime": "none",
                   "task_factory": {"repeat": n}}],
    }]})
    start = time.monotonic()
    counts = jobs_mgr.add_jobs(store, pool, jobs)
    submit_elapsed = time.monotonic() - start
    assert counts["vast"] == n
    assert submit_elapsed < 120, f"submission took {submit_elapsed:.0f}s"
    # Sustained submission may GROW the shard count mid-stream
    # (grow-only autoscale); count at the final width — the original
    # 16 queue names are a strict subset, so nothing is stranded.
    final_shards = jobs_mgr.pool_queue_shards(store, "s100k", ttl=0)
    assert final_shards >= 16
    assert set(names.task_queues("s100k", 16)) <= set(
        names.task_queues("s100k", final_shards))
    queues = names.task_queues("s100k", final_shards)
    lengths = {q: store.queue_length(q) for q in queues}
    assert sum(lengths.values()) == n
    populated = {q: c for q, c in lengths.items() if c}
    assert len(populated) >= 16, populated.keys()
    # Balance is only guaranteed over the ORIGINAL width: the grown
    # shards receive just the post-growth tail, whose share depends
    # on when the rate threshold tripped.
    original = [c for q, c in lengths.items()
                if q in set(names.task_queues("s100k", 16)) and c]
    assert min(original) > n / 64, populated
    # Pop a sample from every shard: messages parse and reference
    # real task entities.
    seen = Counter()
    popped = 0
    for q in populated:
        for msg in store.get_messages(q, max_messages=32,
                                      visibility_timeout=60.0):
            payload = json.loads(msg.payload)
            seen[payload["task_id"]] += 1
            store.delete_message(msg)
            popped += 1
    assert popped >= 16 * 32
    assert len(seen) == popped  # every message a distinct task
    assert max(seen.values()) == 1


def test_soak_concurrent_pools_with_chaos():
    """Multi-pool soak (ROADMAP Quality): three pools on ONE shared
    state store run concurrent workloads — one of them under
    continuous agent-kill chaos — and every task on every pool
    completes exactly once with no cross-pool interference."""
    import threading

    store = MemoryStateStore()
    pools = {}
    substrates = {}
    n_tasks = {"soak-a": 150, "soak-b": 100, "soak-c": 60}
    stop_chaos = None
    try:
        for pool_id, accel, slots in (
                ("soak-a", "v5litepod-16", 4),
                ("soak-b", "v5litepod-8", 2),
                ("soak-c", "v5litepod-4", 2)):
            conf = {"pool_specification": {
                "id": pool_id, "substrate": "fake",
                "tpu": {"accelerator_type": accel},
                "task_slots_per_node": slots,
                "task_queue_shards": 4,
                "max_wait_time_seconds": 30}}
            substrates[pool_id] = FakePodSubstrate(
                store, node_stale_seconds=3.0)
            pools[pool_id] = settings_mod.pool_settings(conf)
            pool_mgr.create_pool(store, substrates[pool_id],
                                 pools[pool_id], GLOBAL, conf)
        # Chaos on the middle pool only: its kills must not disturb
        # the other pools' agents or task state.
        stop_chaos = substrates["soak-b"].start_chaos(
            "soak-b", kill_interval=0.8, revive_after=0.3, seed=7)

        results: dict = {}

        def drive(pool_id: str) -> None:
            try:
                jobs = settings_mod.job_settings_list(
                    {"job_specifications": [{
                        "id": "load",
                        "tasks": [{"id": f"t{i:04d}",
                                   "command": f"echo {pool_id}-{i}"}
                                  for i in range(n_tasks[pool_id])],
                    }]})
                jobs_mgr.add_jobs(store, pools[pool_id], jobs)
                results[pool_id] = jobs_mgr.wait_for_tasks(
                    store, pool_id, "load", timeout=240,
                    poll_interval=0.5)
            except Exception as exc:  # noqa: BLE001
                results[pool_id] = exc

        threads = [threading.Thread(target=drive, args=(p,),
                                    daemon=True) for p in pools]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert not any(t.is_alive() for t in threads), \
            "soak drive thread still running after join budget"
        assert set(results) == set(pools), results.keys()
        for pool_id, tasks in results.items():
            assert not isinstance(tasks, Exception), (pool_id, tasks)
            assert len(tasks) == n_tasks[pool_id]
            bad = {t["_rk"]: t["state"] for t in tasks
                   if t["state"] != "completed"}
            assert not bad, (pool_id, bad)
        # Exactly-once effects sampled per pool, incl. the chaos one.
        for pool_id in pools:
            last = n_tasks[pool_id] - 1
            for i in (0, last):
                out = jobs_mgr.get_task_output(
                    store, pool_id, "load", f"t{i:04d}")
                assert out.strip() == f"{pool_id}-{i}".encode()
    finally:
        if stop_chaos is not None:
            stop_chaos.set()
        for substrate in substrates.values():
            substrate.stop_all()

"""gcloud error-classification corpus: real captured payload shapes
from `gcloud compute tpus tpu-vm create` / queued-resources failures
pinned against the table-driven classifier (VERDICT r1 weak #7: the
classification logic must be table-driven and tested against real
payloads, not ad-hoc substring checks)."""

import pytest

from batch_shipyard_tpu.substrate import gcloud_errors as ge

# (payload, expected kind, expected fatal, expected retry)
CORPUS = [
    # --- quota: CLI text form ---
    ("ERROR: (gcloud.compute.tpus.tpu-vm.create) Could not fetch "
     "resource:\n - Quota exceeded for quota metric 'TPUV5sLitepod"
     "Cores' and limit 'TPUV5sLitepodCoresPerProjectPerZone' of "
     "service 'tpu.googleapis.com' for consumer "
     "'project_number:123456789'.",
     "quota", True, "none"),
    # --- quota: JSON API form ---
    ('{"error": {"code": 429, "message": "Quota exceeded for quota '
     'metric \'TPU v4 cores\'", "status": "RESOURCE_EXHAUSTED", '
     '"details": [{"reason": "RATE_LIMIT_EXCEEDED"}]}}',
     "quota", True, "none"),
    # --- stockout: the classic zone-dry message ---
    ("ERROR: (gcloud.compute.tpus.tpu-vm.create) {\"code\": 8, "
     "\"message\": \"There is no more capacity in the zone "
     "\\\"us-central2-b\\\"; you can try in another zone where "
     "Cloud TPU Nodes are offered\"}",
     "stockout", False, "other_zone"),
    # --- stockout: RESOURCE_EXHAUSTED without quota wording ---
    ('{"error": {"code": 8, "status": "RESOURCE_EXHAUSTED", '
     '"message": "Not enough available capacity for request."}}',
     "stockout", False, "other_zone"),
    # --- stockout: GCE-style resources message ---
    ("ERROR: The zone 'projects/p/zones/us-east1-d' does not have "
     "enough resources available to fulfill the request. Try a "
     "different zone, or try again later.",
     "stockout", False, "other_zone"),
    # --- permission ---
    ("ERROR: (gcloud.compute.tpus.tpu-vm.create) User "
     "[sa@project.iam.gserviceaccount.com] does not have permission "
     "to access projects instance [my-project] (or it may not "
     "exist): Permission 'tpu.nodes.create' denied on "
     "'projects/my-project/locations/us-central2-b'",
     "permission", True, "none"),
    ('{"error": {"code": 401, "message": "Request had insufficient '
     'authentication scopes.", "status": "UNAUTHENTICATED"}}',
     "permission", True, "none"),
    # --- invalid argument ---
    ("ERROR: (gcloud.compute.tpus.tpu-vm.create) INVALID_ARGUMENT: "
     "v5litepod-3 is not a valid accelerator-type for this project "
     "in zone us-central2-b.",
     "invalid_argument", True, "none"),
    ('{"error": {"code": 400, "message": "Invalid value for field '
     "'runtime_version': 'tpu-ubuntu2204-base-nonexistent'.\", "
     '"status": "INVALID_ARGUMENT"}}',
     "invalid_argument", True, "none"),
    # --- conflict (idempotent create race) ---
    ("ERROR: (gcloud.compute.tpus.tpu-vm.create) ALREADY_EXISTS: "
     "Resource 'projects/p/locations/z/nodes/shipyard-pool-s0' "
     "already exists",
     "conflict", False, "none"),
    # --- not found on delete ---
    ("ERROR: (gcloud.compute.tpus.tpu-vm.delete) NOT_FOUND: Resource "
     "'projects/p/locations/z/nodes/shipyard-pool-s0' was not found",
     "not_found", False, "none"),
    # --- transient service errors ---
    ('{"error": {"code": 503, "message": "The service is currently '
     'unavailable.", "status": "UNAVAILABLE"}}',
     "unavailable", False, "backoff"),
    ("ERROR: gcloud crashed (ConnectionError): ('Connection aborted."
     "', ConnectionResetError(104, 'Connection reset by peer'))",
     "unavailable", False, "backoff"),
    ('{"error": {"code": 500, "message": "Internal error encountered'
     '.", "status": "INTERNAL"}}',
     "internal", False, "backoff"),
    ('{"error": {"code": 504, "status": "DEADLINE_EXCEEDED", '
     '"message": "Timed out waiting for operation."}}',
     "unavailable", False, "backoff"),
]


@pytest.mark.parametrize(
    "payload,kind,fatal,retry", CORPUS,
    ids=[f"{row[1]}-{i}" for i, row in enumerate(CORPUS)])
def test_corpus_classification(payload, kind, fatal, retry):
    got = ge.classify(payload)
    assert got.kind == kind, (got, payload[:80])
    assert got.fatal == fatal
    assert got.retry == retry


def test_unknown_payload_defaults_to_retryable():
    got = ge.classify("ERROR: something nobody has seen before")
    assert got.kind == "unknown"
    assert not got.fatal          # never brick a pool on new wording
    assert got.retry == "backoff"


def test_quota_beats_resource_exhausted():
    """A quota error often carries RESOURCE_EXHAUSTED status; the
    quota rule must win (it is fatal, stockout is not)."""
    got = ge.classify(
        '{"status": "RESOURCE_EXHAUSTED", "message": "Quota exceeded '
        "for quota metric 'TPU v5 cores'\"}")
    assert got.kind == "quota"
    assert got.fatal


def test_preemption_states():
    assert ge.is_preemption_state("PREEMPTED")
    assert ge.is_preemption_state("terminated")
    assert ge.is_preemption_state("SUSPENDED")
    assert not ge.is_preemption_state("READY")
    assert not ge.is_preemption_state(None)


def test_substrate_records_classification(tmp_path, monkeypatch):
    """_create_slice failure writes kind/fatal/retry into the pool
    entity (the _block_for_nodes_ready consumer surface)."""
    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate import gcp_tpu

    monkeypatch.setattr(gcp_tpu.shutil, "which",
                        lambda _name: "/usr/bin/gcloud")
    creds = S.credentials_settings({"credentials": {
        "storage": {"backend": "memory"},
        "gcp": {"project": "p", "zone": "us-central2-b"}}})
    store = MemoryStateStore()
    sub = gcp_tpu.GcpTpuSubstrate(store, creds)
    stderr = ("ERROR: There is no more capacity in the zone "
              '"us-central2-b"; you can try in another zone')
    monkeypatch.setattr(
        gcp_tpu.util, "subprocess_capture",
        lambda cmd: (1, "", stderr))
    pool = S.pool_settings({"pool_specification": {
        "id": "errpool", "substrate": "tpu_vm",
        "tpu": {"accelerator_type": "v5litepod-16"}}})
    store.insert_entity("pools", "pools", "errpool", {})
    with pytest.raises(RuntimeError):
        sub.allocate_pool(pool)
    row = store.get_entity("pools", "pools", "errpool")
    assert row["allocation_error_kind"] == "stockout"
    assert row["allocation_error_fatal"] is False
    assert row["allocation_error_retry"] == "other_zone"


def test_manager_fails_fast_on_stockout(tmp_path, monkeypatch):
    """A dry zone (retry=other_zone) must fail the pool wait
    immediately — the zone is fixed by credentials, so waiting out
    max_wait_time_seconds cannot help (review follow-up: the old
    marker list treated stockout as fatal; the taxonomy keeps it
    non-fatal but the manager still fails fast on it)."""
    import time

    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.pool import manager as pool_mgr
    from batch_shipyard_tpu.state.memory import MemoryStateStore

    store = MemoryStateStore()
    store.insert_entity("pools", "pools", "drypool", {
        "allocation_error": "no more capacity in the zone",
        "allocation_error_kind": "stockout",
        "allocation_error_fatal": False,
        "allocation_error_retry": "other_zone",
    })
    pool = S.pool_settings({"pool_specification": {
        "id": "drypool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16"},
        "max_wait_time_seconds": 300}})
    class _NullSubstrate:
        def list_nodes(self, pool_id):
            return []

        def recreate_slice(self, pool, slice_index):
            raise AssertionError("not expected")

    start = time.monotonic()
    with pytest.raises(pool_mgr.PoolAllocationError) as exc:
        pool_mgr.wait_for_pool_ready(store, _NullSubstrate(), pool,
                                     poll_interval=0.05)
    assert time.monotonic() - start < 10  # not the 300 s timeout
    assert "stockout" in str(exc.value)


def test_bare_resource_exhausted_backs_off():
    """RESOURCE_EXHAUSTED with no capacity wording is GCP's API
    rate-limit shape (HTTP 429); other_zone would abort allocation on
    a transient, so it must back off instead (advisor r2 #1)."""
    got = ge.classify(
        '{"error": {"code": 429, "status": "RESOURCE_EXHAUSTED", '
        '"message": "Too many requests; try again later."}}')
    assert got.kind == "unavailable"
    assert not got.fatal
    assert got.retry == "backoff"


def test_capacity_worded_resource_exhausted_is_stockout():
    got = ge.classify(
        '{"status": "RESOURCE_EXHAUSTED", "message": "There is no '
        'more capacity in the zone \"us-central2-b\"."}')
    assert got.kind == "stockout"
    assert got.retry == "other_zone"


def test_accelerator_not_found_beats_generic_not_found():
    """'Accelerator type X was not found' is a fatal config error;
    the generic 'was not found' rule must not swallow it into a
    non-fatal not_found that polls to timeout (advisor r2 #2)."""
    got = ge.classify(
        "ERROR: (gcloud.compute.tpus.tpu-vm.create) Accelerator type "
        "v5litepod-4 was not found in zone us-east1-d")
    assert got.kind == "invalid_argument"
    assert got.fatal
    assert got.retry == "none"

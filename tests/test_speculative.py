"""Speculative decoding (models/inference.speculative_generate):
greedy equivalence with the lockstep decoder across draft qualities —
hostile draft (every token corrected), perturbed draft (partial
acceptance), identical draft (full acceptance + bonus tokens) — plus
the prompt-length-1 edge and stats accounting."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import transformer as tfm

TCFG = tfm.TransformerConfig(
    vocab_size=97, d_model=64, n_layers=3, n_heads=4, d_head=16,
    d_ff=128, max_seq_len=96, dtype=jnp.float32,
    param_dtype=jnp.float32)
DCFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=1, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=96, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def tparams():
    return tfm.TransformerLM(TCFG).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture(scope="module")
def dparams():
    return tfm.TransformerLM(DCFG).init(
        jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture(scope="module")
def reference(tparams):
    run, _ = inf.make_decoder(TCFG, tparams, max_decode_len=96)
    return run


PROMPT = jnp.asarray([[5, 17, 31, 2], [9, 9, 1, 42]], jnp.int32)
N = 24


def _spec(tparams, dcfg, dparams, gamma=4):
    run, _, _ = inf.make_speculative_decoder(
        TCFG, tparams, dcfg, dparams, max_decode_len=96, gamma=gamma)
    return run


def test_hostile_draft_still_exact(tparams, dparams, reference):
    """A draft that almost never agrees: every round falls back to
    the target's correction token — output must still be identical."""
    tok, stats = _spec(tparams, DCFG, dparams)(PROMPT, N)
    ref, _ = reference(PROMPT, N, jax.random.PRNGKey(0))
    assert jnp.array_equal(tok, ref)
    assert tok.shape == (2, PROMPT.shape[1] + N)
    # Worst case: one committed token per round.
    assert int(stats["rounds"]) <= N
    assert int(stats["proposed"]) == int(stats["rounds"]) * 4


def test_identical_draft_full_acceptance(tparams, reference):
    """Draft == target: every proposal validates, rounds collapse to
    ceil(N / (gamma+1)) and the bonus-token path is exercised."""
    tok, stats = _spec(tparams, TCFG, tparams)(PROMPT, N)
    ref, _ = reference(PROMPT, N, jax.random.PRNGKey(0))
    assert jnp.array_equal(tok, ref)
    assert int(stats["accepted"]) == int(stats["proposed"])
    assert int(stats["rounds"]) == -(-N // 5)  # gamma+1 per round


def test_perturbed_draft_partial_acceptance(tparams, reference):
    """A slightly-noised target as draft: agrees often but not
    always — exercises mixed accept/correct rounds exactly."""
    rng = np.random.RandomState(7)
    noisy = jax.tree_util.tree_map(
        lambda p: p + jnp.asarray(
            0.02 * rng.randn(*p.shape), p.dtype), tparams)
    tok, stats = _spec(tparams, TCFG, noisy)(PROMPT, N)
    ref, _ = reference(PROMPT, N, jax.random.PRNGKey(0))
    assert jnp.array_equal(tok, ref)
    acc, prop = int(stats["accepted"]), int(stats["proposed"])
    assert 0 < acc < prop, (acc, prop)


def test_prompt_length_one(tparams, dparams, reference):
    prompt = jnp.asarray([[3], [77]], jnp.int32)
    tok, _ = _spec(tparams, DCFG, dparams)(prompt, 12)
    ref, _ = reference(prompt, 12, jax.random.PRNGKey(0))
    assert jnp.array_equal(tok, ref)


@pytest.mark.parametrize("gamma", [1, 2, 7])
def test_gamma_sweep_exact(tparams, dparams, reference, gamma):
    tok, _ = _spec(tparams, DCFG, dparams, gamma=gamma)(PROMPT, N)
    ref, _ = reference(PROMPT, N, jax.random.PRNGKey(0))
    assert jnp.array_equal(tok, ref)

def test_paged_kv_config_rejected(tparams, dparams):
    import dataclasses
    paged = dataclasses.replace(TCFG, kv_page_size=16)
    with pytest.raises(ValueError) as exc:
        inf.make_speculative_decoder(paged, tparams, DCFG, dparams,
                                     max_decode_len=96)
    assert "kv_page_size" in str(exc.value)

"""End-to-end job execution on the FakePod substrate: the minimum
slice of SURVEY.md section 7 step 3 plus gang scheduling (step 4),
exercised with real subprocesses via runtime: none."""

import json

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def make_env(accel="v5litepod-16", slices=1, slots=1):
    conf = {"pool_specification": {
        "id": "pool1", "substrate": "fake",
        "tpu": {"accelerator_type": accel, "num_slices": slices},
        "task_slots_per_node": slots,
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return store, substrate, pool


def submit(store, pool, jobs_conf):
    jobs = settings_mod.job_settings_list(jobs_conf)
    return jobs_mgr.add_jobs(store, pool, jobs)


@pytest.fixture()
def env():
    store, substrate, pool = make_env()
    yield store, substrate, pool
    substrate.stop_all()


def test_single_task_runs_and_streams_output(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "job1",
        "tasks": [{"command": "echo hello from $SHIPYARD_TASK_ID"}],
    }]})
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "job1", timeout=30)
    assert len(tasks) == 1
    assert tasks[0]["state"] == "completed"
    assert tasks[0]["exit_code"] == 0
    out = jobs_mgr.get_task_output(store, "pool1", "job1", "task-00000")
    assert out.strip() == b"hello from task-00000"


def test_task_env_contract(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jenv",
        "environment_variables": {"MYVAR": "42"},
        "tasks": [{"command":
                   "echo $MYVAR $SHIPYARD_POOL_ID $SHIPYARD_JOB_ID"}],
    }]})
    jobs_mgr.wait_for_tasks(store, "pool1", "jenv", timeout=30)
    out = jobs_mgr.get_task_output(store, "pool1", "jenv", "task-00000")
    assert out.strip() == b"42 pool1 jenv"


def test_failing_task_retries_then_quarantines(env):
    """Retry budget exhausted: the retry supervisor (PR 5) parks the
    task in the terminal `quarantined` state with its post-mortem
    instead of plain `failed` — tests/test_chaos_recovery.py covers
    the bundle contents and the zero-budget legacy path."""
    from batch_shipyard_tpu.state import names
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jfail",
        "tasks": [{"command": "exit 3", "max_task_retries": 2}],
    }]})
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jfail", timeout=30)
    assert tasks[0]["state"] == names.TASK_STATE_QUARANTINED
    assert tasks[0]["exit_code"] == 3
    assert tasks[0]["retries"] == 2
    assert [a["exit_code"] for a in
            tasks[0]["diagnostics"]["attempt_history"]] == [3, 3, 3]


def test_task_dependencies_order(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jdep",
        "tasks": [
            {"id": "a", "command": "echo A"},
            {"id": "b", "command": "echo B", "depends_on": ["a"]},
            {"id": "c", "command": "echo C", "depends_on": ["b"]},
        ],
    }]})
    tasks = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
        store, "pool1", "jdep", timeout=30)}
    assert all(t["state"] == "completed" for t in tasks.values())
    assert tasks["a"]["completed_at"] <= tasks["b"]["started_at"]
    assert tasks["b"]["completed_at"] <= tasks["c"]["started_at"]


def test_dependency_on_failed_task_blocks(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jblock",
        "tasks": [
            {"id": "bad", "command": "exit 1"},
            {"id": "child", "command": "echo never",
             "depends_on": ["bad"]},
        ],
    }]})
    tasks = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
        store, "pool1", "jblock", timeout=30)}
    assert tasks["bad"]["state"] == "failed"
    assert tasks["child"]["state"] == "blocked"


def test_dependency_action_satisfy_runs_child(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jsat",
        "tasks": [
            {"id": "bad", "command": "exit 1",
             "exit_conditions": {"default": {"exit_options": {
                 "dependency_action": "satisfy"}}}},
            {"id": "child", "command": "echo ran",
             "depends_on": ["bad"]},
        ],
    }]})
    tasks = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
        store, "pool1", "jsat", timeout=30)}
    assert tasks["child"]["state"] == "completed"


def test_wall_time_enforcement(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jwall",
        "tasks": [{"command": "sleep 30",
                   "max_wall_time_seconds": 1}],
    }]})
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jwall", timeout=30)
    assert tasks[0]["state"] == "failed"
    assert tasks[0]["timed_out"]


def test_job_prep_runs_once_per_node(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jp",
        "job_preparation": {"command": "echo prep"},
        "tasks": [{"id": f"t{i}", "command": "echo x"}
                  for i in range(6)],
    }]})
    jobs_mgr.wait_for_tasks(store, "pool1", "jp", timeout=30)
    rows = list(store.query_entities(
        names.TABLE_JOBPREP, partition_key=names.task_pk("pool1", "jp")))
    # At most one prep per node, and every prep is done.
    assert 1 <= len(rows) <= 4
    assert all(r["state"] == "done" for r in rows)


def test_auto_complete_and_job_release(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jac", "auto_complete": True,
        "job_preparation": {"command": "echo prep"},
        "job_release": {"command": "echo release"},
        "tasks": [{"command": "echo done"}],
    }]})
    jobs_mgr.wait_for_tasks(store, "pool1", "jac", timeout=30)
    import time
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if jobs_mgr.get_job(store, "pool1", "jac")[
                "state"] == "completed":
            break
        time.sleep(0.1)
    assert jobs_mgr.get_job(store, "pool1", "jac")["state"] == "completed"


def test_parametric_sweep_fanout(env):
    store, substrate, pool = env
    counts = submit(store, pool, {"job_specifications": [{
        "id": "jsweep",
        "tasks": [{
            "command": "echo {0}-{1}",
            "task_factory": {"parametric_sweep": {
                "generator": "product",
                "product": [
                    {"start": 0, "stop": 2, "step": 1},
                    {"values": ["x", "y", "z"]},
                ]}},
        }],
    }]})
    assert counts["jsweep"] == 6
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jsweep", timeout=30)
    outs = set()
    for task in tasks:
        assert task["state"] == "completed"
        outs.add(jobs_mgr.get_task_output(
            store, "pool1", "jsweep", task["_rk"]).strip())
    assert outs == {b"0-x", b"0-y", b"0-z", b"1-x", b"1-y", b"1-z"}


def test_gang_task_rendezvous_and_jax_env():
    store, substrate, pool = make_env()
    try:
        submit(store, pool, {"job_specifications": [{
            "id": "jgang",
            "tasks": [{
                "command": ("echo $JAX_PROCESS_ID/$JAX_NUM_PROCESSES "
                            "$JAX_COORDINATOR_ADDRESS "
                            "$SHIPYARD_HOST_LIST"),
                "multi_instance": {
                    "num_instances": 4,
                    "coordination_command": "echo coord",
                    "jax_distributed": {"enabled": True,
                                        "transport": "ici"},
                },
            }],
        }]})
        tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jgang",
                                        timeout=60)
        assert tasks[0]["state"] == "completed"
        seen = set()
        coords = set()
        for k in range(4):
            out = jobs_mgr.get_task_output(
                store, "pool1", "jgang", "task-00000",
                instance=k).decode().strip()
            rank_part, coord, hosts = out.split(" ")
            seen.add(rank_part)
            coords.add(coord)
            assert len(hosts.split(",")) == 4
        assert seen == {"0/4", "1/4", "2/4", "3/4"}
        assert len(coords) == 1  # everyone agrees on the coordinator
        port = coords.pop().split(":")[1]
        assert port == "8476"
    finally:
        substrate.stop_all()


def test_gang_multislice_megascale_env():
    store, substrate, pool = make_env(accel="v5litepod-8", slices=2)
    try:
        submit(store, pool, {"job_specifications": [{
            "id": "jms",
            "tasks": [{
                "command": ("echo $MEGASCALE_NUM_SLICES "
                            "$MEGASCALE_SLICE_ID $JAX_NUM_PROCESSES"),
                "multi_instance": {
                    "num_instances": 4,
                    "jax_distributed": {"enabled": True,
                                        "transport": "auto"},
                },
            }],
        }]})
        tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jms", timeout=60)
        assert tasks[0]["state"] == "completed"
        slice_ids = set()
        for k in range(4):
            out = jobs_mgr.get_task_output(
                store, "pool1", "jms", "task-00000",
                instance=k).decode().split()
            assert out[0] == "2"
            assert out[2] == "4"
            slice_ids.add(out[1])
        assert slice_ids == {"0", "1"}
    finally:
        substrate.stop_all()


def test_terminate_job(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jterm",
        "tasks": [{"command": "sleep 60"}],
    }]})
    import time
    time.sleep(0.5)
    jobs_mgr.terminate_job(store, "pool1", "jterm")
    job = jobs_mgr.get_job(store, "pool1", "jterm")
    assert job["state"] == "terminated"


def test_job_stats(env):
    store, substrate, pool = env
    submit(store, pool, {"job_specifications": [{
        "id": "jstats",
        "tasks": [{"command": "echo 1"}, {"command": "exit 1"}],
    }]})
    jobs_mgr.wait_for_tasks(store, "pool1", "jstats", timeout=30)
    stats = jobs_mgr.job_stats(store, "pool1")
    assert stats["tasks"] == 2
    assert stats["by_state"]["completed"] == 1
    assert stats["by_state"]["failed"] == 1


def test_orphaned_task_reclaimed_from_dead_node(env):
    """A task assigned to a node that died (stale heartbeat) is reset
    to pending and picked up by a live node on message redelivery."""
    store, substrate, pool = env
    pk = names.task_pk("pool1", "jorph")
    store.insert_entity(names.TABLE_JOBS, "pool1", "jorph",
                        {"state": "active", "spec": {}})
    store.insert_entity(names.TABLE_TASKS, pk, "t0", {
        "state": "running", "node_id": "ghost-node",
        "spec": {"command": "echo reclaimed", "runtime": "none"},
        "retries": 0})
    # ghost node with ancient heartbeat
    store.upsert_entity(names.TABLE_NODES, "pool1", "ghost-node", {
        "state": "running", "heartbeat_at": 0.0})
    store.put_message(names.task_queue("pool1"), json.dumps(
        {"job_id": "jorph", "task_id": "t0"}).encode())
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jorph", timeout=30)
    assert tasks[0]["state"] == "completed"
    assert tasks[0]["node_id"] != "ghost-node"


def test_broken_gang_fails_fast(env):
    """A gang whose member died (stale node heartbeat) is failed
    promptly by the surviving participants instead of hanging until
    the rendezvous timeout (preempted-slice semantics)."""
    store, substrate, pool = env
    pk = names.task_pk("pool1", "jghost")
    store.insert_entity(names.TABLE_JOBS, "pool1", "jghost",
                        {"state": "active", "spec": {}})
    spec = {"command": "echo never", "runtime": "none",
            "multi_instance": {"num_instances": 4,
                               "jax_distributed": {"enabled": True}}}
    store.insert_entity(names.TABLE_TASKS, pk, "g0",
                        {"state": "pending", "spec": spec,
                         "retries": 0})
    # Ghost member already holds instance 0 with a dead node.
    gang_pk = names.gang_pk("pool1", "jghost", "g0")
    store.insert_entity(names.TABLE_GANGS, gang_pk, "i0", {
        "node_id": "ghost-node", "hostname": "ghost",
        "internal_ip": "10.9.9.9", "slice_index": 0,
        "worker_index": 0, "state": "joined"})
    store.insert_entity(names.TABLE_GANGS, gang_pk, "node$ghost-node",
                        {"instance": 0})
    store.upsert_entity(names.TABLE_NODES, "pool1", "ghost-node", {
        "state": "running", "heartbeat_at": 0.0})
    for k in range(4):
        store.put_message(names.task_queue("pool1"), json.dumps(
            {"job_id": "jghost", "task_id": "g0",
             "instance": k}).encode())
    import time as time_mod
    deadline = time_mod.monotonic() + 30
    while time_mod.monotonic() < deadline:
        task = jobs_mgr.get_task(store, "pool1", "jghost", "g0")
        if task.get("state") == "failed":
            break
        time_mod.sleep(0.2)
    assert task["state"] == "failed"
    assert "gang member" in task.get("error", "")


def test_gang_done_member_crash_finalized_by_peer(env):
    """A gang whose last member marked itself done but crashed before
    finalizing is finalized by whichever live node receives the
    redelivered message."""
    store, substrate, pool = env
    pk = names.task_pk("pool1", "jdone")
    store.insert_entity(names.TABLE_JOBS, "pool1", "jdone",
                        {"state": "active", "spec": {}})
    spec = {"command": "echo x", "runtime": "none",
            "multi_instance": {"num_instances": 2,
                               "jax_distributed": {"enabled": False}}}
    store.insert_entity(names.TABLE_TASKS, pk, "g1",
                        {"state": "running", "spec": spec,
                         "retries": 0})
    gang_pk = names.gang_pk("pool1", "jdone", "g1")
    for k, node in ((0, "ghost-a"), (1, "ghost-b")):
        store.insert_entity(names.TABLE_GANGS, gang_pk, f"i{k}", {
            "node_id": node, "hostname": node,
            "internal_ip": "10.0.0.9", "slice_index": 0,
            "worker_index": k, "state": "done", "exit_code": 0})
        store.insert_entity(names.TABLE_GANGS, gang_pk,
                            f"node${node}", {"instance": k})
    # The crashed member's message redelivers:
    store.put_message(names.task_queue("pool1"), json.dumps(
        {"job_id": "jdone", "task_id": "g1", "instance": 1}).encode())
    import time as time_mod
    deadline = time_mod.monotonic() + 30
    while time_mod.monotonic() < deadline:
        task = jobs_mgr.get_task(store, "pool1", "jdone", "g1")
        if task.get("state") == "completed":
            break
        time_mod.sleep(0.2)
    assert task["state"] == "completed"
    assert task["exit_code"] == 0

"""Fused RMSNorm + matmul Pallas kernel: the training-MFU lever for
the transformer's projection matmuls.

Unfused, every block entry costs HBM twice: RMSNorm reads x and writes
the normalized activation, then each projection matmul reads it back
(three times for q/k/v, twice for gate/up). XLA fuses the elementwise
tail of the norm but still materializes the normalized [B*T, d] tensor
between the reduction and the matmuls. This kernel computes the row
rsqrt(mean(x^2)) statistic and the matmul in one VMEM round trip: x is
read once per (m, n) output tile, the normalized rows never touch HBM,
and the matmul accumulates on the MXU in fp32.

The normalization is recomputed per n-tile (VPU work, free next to the
MXU matmul) — the classic flash-attention trade of FLOPs for HBM
bandwidth applied to the norm.

Backward is plain XLA (custom_vjp): the cotangent math is two big
matmuls (dW = n^T g, dn = g W^T) plus the RMSNorm chain rule, all
shapes XLA already schedules well; the win is the forward HBM traffic
(and the [M, d] normalized tensor that no longer needs saving — x is
the only residual).

No reference counterpart: the reference (Azure batch-shipyard) contains
no ML compute; this follows the public fused-norm-projection pattern
(e.g. Megatron-LM's fused layernorm-linear) re-derived for Pallas/TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from batch_shipyard_tpu.ops.quantization import _largest_divisor_block


def rmsnorm_ref(x, scale, eps: float = 1e-6):
    """Reference RMSNorm (fp32 statistics, cast back to x.dtype)."""
    x32 = x.astype(jnp.float32)
    r = jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * r * scale.astype(jnp.float32)).astype(x.dtype)


def _fused_kernel(x_ref, s_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)                 # [bm, K]
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    n = x * r * s_ref[...].astype(jnp.float32)[None, :]
    o_ref[...] = jax.lax.dot_general(
        n.astype(w_ref.dtype), w_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _fused_forward(x, scale, w, eps: float, block_m: int,
                   block_n: int, interpret: bool):
    m, k = x.shape
    n = w.shape[1]
    bm = _largest_divisor_block(m, block_m, align=8)
    bn = _largest_divisor_block(n, block_n)
    grid = (m // bm, n // bn)
    return pl.pallas_call(
        functools.partial(_fused_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k,), lambda i, j: (0,)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        interpret=interpret,
    )(x, scale, w)


def _xla_forward(x, scale, w, eps: float):
    return jnp.dot(rmsnorm_ref(x, scale, eps), w,
                   preferred_element_type=jnp.float32).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def rmsnorm_matmul(x, scale, w, eps: float = 1e-6,
                   block_m: int = 256, block_n: int = 512,
                   impl: Optional[str] = None):
    """y = (rmsnorm(x) * scale) @ w in one kernel.

    x: [M, K] (callers flatten [B, T, K] to [B*T, K]); scale: [K];
    w: [K, N]. Returns [M, N] in x.dtype with fp32 norm statistics and
    fp32 MXU accumulation.

    impl: 'pallas' | 'xla' | None (pallas on TPU, xla elsewhere —
    same dispatch convention as ops/paged_attention.py).
    """
    return _rmsnorm_matmul_fwd(
        x, scale, w, eps, block_m, block_n, impl)[0]


def _dispatch(impl: Optional[str]) -> str:
    if impl is not None:
        return impl
    # Same convention as ops/attention.attention: default_backend()
    # reports "tpu" for the tunnelled chip too.
    return ("pallas" if jax.default_backend() == "tpu" else "xla")


def _rmsnorm_matmul_fwd(x, scale, w, eps, block_m, block_n, impl):
    mode = _dispatch(impl)
    if mode == "pallas":
        y = _fused_forward(x, scale, w, eps, block_m, block_n,
                           interpret=False)
    elif mode == "interpret":
        y = _fused_forward(x, scale, w, eps, block_m, block_n,
                           interpret=True)
    else:
        y = _xla_forward(x, scale, w, eps)
    return y, (x, scale, w)


def _rmsnorm_matmul_bwd(eps, block_m, block_n, impl, res, g):
    x, scale, w = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    r = jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)  # [M, 1]
    xhat = x32 * r                                          # [M, K]
    n = xhat * scale.astype(jnp.float32)
    dw = jnp.dot(n.T, g32,
                 preferred_element_type=jnp.float32)        # [K, N]
    dn = jnp.dot(g32, w.astype(jnp.float32).T,
                 preferred_element_type=jnp.float32)        # [M, K]
    ds = jnp.sum(xhat * dn, axis=0)                         # [K]
    dxhat = dn * scale.astype(jnp.float32)
    dx = r * (dxhat - xhat * jnp.mean(dxhat * xhat, axis=-1,
                                      keepdims=True))
    return (dx.astype(x.dtype), ds.astype(scale.dtype),
            dw.astype(w.dtype))


rmsnorm_matmul.defvjp(_rmsnorm_matmul_fwd, _rmsnorm_matmul_bwd)

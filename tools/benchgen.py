#!/usr/bin/env python3
"""Measured-numbers page generator: bench artifacts -> markdown.

VERDICT r4 next #8: a numbers page that CANNOT rot — it is rendered
from the JSON the bench pipeline actually produced (BENCH_r*.json,
BENCH_LATEST.json, BENCH_DETAILS.json, SILICON_PROOF.json), never
hand-written. tools/silicon_proof.py re-runs this after every
successful bench so docs/26-benchmarks.md always shows the latest
silicon truth, including the honest "accelerator unreachable" state.

Usage: python tools/benchgen.py [--out docs/26-benchmarks.md]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))
# Where the live bench artifacts (BENCH_DETAILS/LATEST, SILICON_PROOF,
# KERNEL_VALIDATION) are read from; silicon_proof passes its --out-dir
# so a non-repo-root run still renders ITS fresh numbers. Round
# history (BENCH_r*.json) always comes from the repo root.
ARTIFACTS = REPO_ROOT


def _load(path: pathlib.Path):
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _fmt(value, digits=1):
    if value is None:
        return "—"
    if isinstance(value, float):
        return f"{value:,.{digits}f}"
    return str(value)


def _round_history(out: list[str]) -> None:
    rows = []
    for path in sorted(glob.glob(str(REPO_ROOT / "BENCH_r*.json"))):
        tag = os.path.basename(path)[6:-5]  # -> r01
        data = _load(pathlib.Path(path)) or {}
        parsed = data.get("parsed") or {}
        rows.append((tag, parsed))
    latest = _load(ARTIFACTS / "BENCH_LATEST.json")
    if latest:
        rows.append(("latest", latest))
    if not rows:
        return
    out.append("## Headline metric by round\n")
    out.append("ResNet-50 training images/sec/chip (bf16, b=256, "
               "synthetic) vs the reference's 16xV100 recipe "
               "(405 img/s per V100 — BASELINE.md).\n")
    out.append("| round | value | vs V100 baseline | note |")
    out.append("|---|---|---|---|")
    for tag, parsed in rows:
        note = parsed.get("error", "")
        out.append(
            f"| {tag} | {_fmt(parsed.get('value'), 1)} "
            f"{parsed.get('unit', '')} | "
            f"{_fmt(parsed.get('vs_baseline'), 2)}x | {note} |")
    out.append("")


def _workload(out: list[str], name: str, data: dict,
              rate_key: str, rate_label: str) -> None:
    if not isinstance(data, dict):
        return
    if "error" in data:
        out.append(f"### {name}\n")
        out.append(f"Not measured: `{data['error']}`\n")
        return
    if data.get(rate_key) is None:
        return  # nothing recorded for this workload
    out.append(f"### {name}\n")
    out.append("| metric | value |")
    out.append("|---|---|")
    out.append(f"| {rate_label} | {_fmt(data.get(rate_key))} |")
    if data.get("step_seconds") is not None:
        out.append(f"| step time | "
                   f"{_fmt(data['step_seconds'] * 1e3)} ms |")
    if data.get("mfu_pct") is not None:
        out.append(f"| **MFU** | {_fmt(data['mfu_pct'])}% of "
                   f"{_fmt(data.get('peak_bf16_tflops_per_chip'))} "
                   f"bf16 TFLOP/s ({data.get('device_kind')}) |")
    if data.get("chips") is not None:
        out.append(f"| chips | {data['chips']} |")
    out.append("")


def _serving(out: list[str], name: str, data: dict) -> None:
    if not isinstance(data, dict):
        return
    if "error" in data:
        out.append(f"### {name}\n")
        out.append(f"Not measured: `{data['error']}`\n")
        return
    if not data.get("ttft_ms"):
        return
    out.append(f"### {name}\n")
    # Percentiles come from merged per-replica fixed-log-bucket
    # histograms (trace/histogram.py) — the same numbers the router
    # and Prometheus histogram_quantile() report for this fleet.
    out.append("| metric | p50 | p90 | p99 |")
    out.append("|---|---|---|---|")
    for key, label in (("ttft_ms", "TTFT (ms)"),
                       ("tpot_ms", "TPOT (ms)"),
                       ("latency_ms", "latency (ms)")):
        pcts = data.get(key, {})
        out.append(f"| {label} | {_fmt(pcts.get('p50'))} | "
                   f"{_fmt(pcts.get('p90', pcts.get('p95')))} | "
                   f"{_fmt(pcts.get('p99'))} |")
    out.append("")
    out.append(f"Completed {data.get('completed')}/"
               f"{data.get('num_requests')} requests at "
               f"{_fmt(data.get('offered_rate_hz'))} req/s offered; "
               f"{_fmt(data.get('tokens_per_second'))} tok/s "
               f"aggregate.")
    router = data.get("router")
    if router:
        out.append(f"Fleet: {router.get('replicas')} replicas, "
                   f"dispatch {router.get('dispatched')} / completed "
                   f"{router.get('completed')} / failed "
                   f"{router.get('failed')} (queue-depth-aware "
                   f"router).")
    spec = data.get("speculative")
    if spec:
        rate = spec.get("acceptance_rate")
        out.append(f"Speculative decoding: gamma={spec.get('gamma')}, "
                   f"{spec.get('accepted')}/{spec.get('proposed')} "
                   f"drafts accepted "
                   f"({_fmt(None if rate is None else 100 * rate)}% "
                   f"acceptance; tokens per target forward = "
                   f"1 + rate x gamma).")
    out.append("")


_CKPT_KEYS = (("sync_blocking_ms_per_save", "sync save blocking"),
              ("async_blocking_ms_per_save",
               "async save blocking (snapshot only)"),
              ("blocking_speedup", "blocking speedup"),
              ("payload_mb", "payload (MB)"),
              ("saves", "saves measured"))


def _checkpoint_overhead(out: list[str], data: dict) -> None:
    """Zero-stall checkpointing section: blocking ms/save sync vs
    async (docs/28-checkpointing.md). Falls back to the silicon-proof
    phase's skeleton metrics so the dry run still renders the full
    shape."""
    if not isinstance(data, dict) or not data:
        proof = _load(ARTIFACTS / "SILICON_PROOF.json") or {}
        phase = next((p for p in proof.get("phases", [])
                      if p.get("phase") == "checkpoint_overhead"),
                     None)
        if phase is None:
            return
        data = phase.get("metrics") or {}
    out.append("### Checkpoint overhead (sync vs async)\n")
    if "error" in data:
        out.append(f"Not measured: `{data['error']}`\n")
        return
    out.append("Blocking time per save on the training loop's "
               "critical path: the sync path pays the full "
               "device→host + serialize + fsync + rename; "
               "`--async-checkpoint` pays only the snapshot and "
               "persists in a background writer "
               "([28-checkpointing.md](28-checkpointing.md)).\n")
    out.append("| metric | value |")
    out.append("|---|---|")
    for key, label in _CKPT_KEYS:
        value = data.get(key)
        unit = " ms" if key.endswith("ms_per_save") and \
            value is not None else ""
        out.append(f"| {label} | {_fmt(value, 2)}{unit} |")
    out.append("")


_COMPILE_WARM_KEYS = (
    ("cold_ms", "cold compile (empty cache, time to first step)"),
    ("warm_ms", "warm compile (seeded cache + AOT)"),
    ("speedup", "warm-start speedup"),
    ("cache_hits", "persistent-cache entries reused"),
    ("aot_first_step_ms", "first step after AOT precompile"),
    ("steady_step_ms", "steady-state step"))


def _compile_warm(out: list[str], data: dict) -> None:
    """Warm-start compilation section: cold vs warm compile wall time
    (docs/29-compile-cache.md). Falls back to the silicon-proof
    phase's skeleton metrics so the dry run still renders the full
    shape."""
    if not isinstance(data, dict) or not data:
        proof = _load(ARTIFACTS / "SILICON_PROOF.json") or {}
        phase = next((p for p in proof.get("phases", [])
                      if p.get("phase") == "compile_warm"), None)
        if phase is None:
            return
        data = phase.get("metrics") or {}
    out.append("### Warm-start compilation (cold vs warm cache)\n")
    if "error" in data:
        out.append(f"Not measured: `{data['error']}`\n")
        return
    out.append("Time to first train step in a fresh process: cold "
               "XLA compile vs a seeded persistent compilation cache "
               "plus `--aot-precompile` "
               "([29-compile-cache.md](29-compile-cache.md)). This "
               "is the per-node, per-restart compile badput that "
               "pool-wide cache seeding removes.\n")
    out.append("| metric | value |")
    out.append("|---|---|")
    for key, label in _COMPILE_WARM_KEYS:
        value = data.get(key)
        unit = (" ms" if key.endswith("_ms") and value is not None
                else "x" if key == "speedup" and value is not None
                else "")
        out.append(f"| {label} | {_fmt(value, 2)}{unit} |")
    out.append("")


_RING_KEYS = (("mode", "mode (remote_dma = multi-chip ICI ring; "
               "virtual = single-chip schedule proof)"),
              ("ring", "ring size"),
              ("chips", "chips"),
              ("numeric_ok", "parity vs lax collectives"),
              ("best_all_gather_gbps", "best ring all-gather (GB/s)"),
              ("best_reduce_scatter_gbps",
               "best ring reduce-scatter (GB/s)"))


def _ring_collectives(out: list[str], data: dict) -> None:
    """Async-DMA ring collective kernels section
    (docs/31-pallas-kernels.md). Falls back to the silicon-proof
    phase's skeleton metrics; when nothing was measured (the relay is
    down), the section says so explicitly — claims are labeled, not
    implied."""
    skeleton_note = None
    if not isinstance(data, dict) or not data:
        proof = _load(ARTIFACTS / "SILICON_PROOF.json") or {}
        phase = next((p for p in proof.get("phases", [])
                      if p.get("phase") == "ring_collectives"), None)
        if phase is None:
            return
        data = phase.get("metrics") or {}
        skeleton_note = phase.get("note")
    out.append("### Ring collectives (async-DMA Pallas kernels)\n")
    if "error" in data:
        out.append(f"Not measured: `{data['error']}`\n")
        return
    out.append("Double-buffered `make_async_remote_copy` ring "
               "all-gather/reduce-scatter: numeric parity against the "
               "XLA lax collectives always; a timed lax baseline only "
               "in `remote_dma` mode (interpret-mode runs are parity "
               "checks, never timings) "
               "([31-pallas-kernels.md](31-pallas-kernels.md)).\n")
    if skeleton_note or data.get("numeric_ok") is None:
        out.append("**accelerator unreachable — dry-run skeleton** "
                   "(no chip has answered since round 2; the values "
                   "below are unmeasured placeholders, not claims).\n")
    out.append("| metric | value |")
    out.append("|---|---|")
    for key, label in _RING_KEYS:
        out.append(f"| {label} | {_fmt(data.get(key), 3)} |")
    out.append("")
    rows = data.get("rows") or []
    if rows:
        out.append("| op | impl | bytes | GB/s |")
        out.append("|---|---|---|---|")
        for row in rows:
            out.append(f"| {row.get('op')} | {row.get('impl')} | "
                       f"{row.get('bytes')} | "
                       f"{_fmt(row.get('algo_bw_gbps'), 3)} |")
        out.append("")


_ORCH_KEYS = ("pool_add_to_ready_seconds", "nodeprep_seconds",
              "image_prefetch_seconds",
              "submit_to_task_complete_seconds")


def _orchestration(out: list[str], data: dict) -> None:
    if not isinstance(data, dict):
        return
    if "error" not in data and not any(
            data.get(k) is not None for k in _ORCH_KEYS):
        return  # nothing recorded (training-only bench run)
    out.append("### Orchestration latency\n")
    if "error" in data:
        out.append(f"Not measured: `{data['error']}`\n")
        return
    out.append(f"Measured on: {data.get('substrate', 'unknown')}\n")
    out.append("| phase | seconds |")
    out.append("|---|---|")
    labels = dict(zip(_ORCH_KEYS, (
        "pool add -> all ready", "nodeprep (max over nodes)",
        "image prefetch (max over nodes)",
        "job submit -> task complete")))
    for key, label in labels.items():
        if data.get(key) is not None:
            out.append(f"| {label} | {_fmt(data[key], 2)} |")
    out.append("")


_SCHED_KEYS = (
    ("num_tasks", "tasks driven end-to-end"),
    ("end_to_end_seconds", "end-to-end wall (s)"),
    ("end_to_end_tasks_per_second", "end-to-end throughput "
                                    "(tasks/s)"),
    ("submit_seconds", "submission leg, expansion included (s)"),
    ("submit_tasks_per_second", "submission throughput (tasks/s)"),
    ("client_submit_seconds", "client-side submit leg (s)"),
    ("run_seconds", "run/drain leg (s)"),
    ("tasks_per_second", "post-submit drain rate (tasks/s)"),
    ("queue_depth_after", "undrained queue messages"))

_SCHED_BREAKDOWN_KEYS = (
    ("expansion_wall_seconds", "server-side expansion wall (s)"),
    ("encode_seconds", "encode leg, overlapped (s)"),
    ("entity_seconds", "entity-insert leg, overlapped (s)"),
    ("enqueue_seconds", "enqueue leg, overlapped (s)"),
    ("chunks", "adaptive chunks"),
    ("queue_shards_final", "task-queue shards after autoscale"))


def _scheduler_scale(out: list[str], data: dict) -> None:
    """10^6-task scheduler proof section. The run is ALWAYS a
    CPU/in-process measurement (the marker convention: label the
    substrate, never imply silicon) — the number proves the
    scheduling path, not an accelerator."""
    if not isinstance(data, dict) or not data:
        return
    out.append("### Scheduler scale (10^6-task end-to-end proof)\n")
    if "error" in data:
        out.append(f"Not measured: `{data['error']}`\n")
        return
    out.append("**CPU fakepod, in-process task mode — an "
               "orchestration measurement, no accelerator involved "
               "or claimed.** Every task runs the real scheduling "
               "path (server-side expansion + streaming bulk "
               "submission ([13-task-factory.md](13-task-factory.md)), "
               "sharded queue fan-out, batched claims, goodput/trace "
               "emission, queue drain); the "
               "task body is a function call, so per-task fork cost "
               "stops dominating "
               "([33-elastic-training.md](33-elastic-training.md)).\n")
    out.append(f"Measured on: {data.get('substrate', 'unknown')}\n")
    out.append("| metric | value |")
    out.append("|---|---|")
    for key, label in _SCHED_KEYS:
        out.append(f"| {label} | {_fmt(data.get(key), 1)} |")
    out.append(f"| server-side expansion | "
               f"{'yes' if data.get('server_side_expansion') else 'no'}"
               f" |")
    breakdown = data.get("submit_breakdown") or {}
    for key, label in _SCHED_BREAKDOWN_KEYS:
        if key in breakdown:
            out.append(f"| {label} | "
                       f"{_fmt(breakdown.get(key), 1)} |")
    completed = data.get("completed")
    out.append(f"| all tasks completed | "
               f"{'yes' if completed else 'NO'} |")
    goodput = data.get("goodput") or {}
    out.append(f"| goodput partition exact | "
               f"{'yes' if goodput.get('partition_exact') else 'NO'}"
               f" |")
    out.append(f"| accounting report over the run (s) | "
               f"{_fmt(goodput.get('report_seconds'), 2)} |")
    out.append("")


_CHAOS_INVARIANTS = (
    ("tasks", "terminal task states"),
    ("orphaned_gang_rows", "orphaned gang rows"),
    ("queue_depth", "undrained queue messages"),
    ("retries", "retries spent healing"),
    ("backoff_seconds", "backoff badput (seconds)"))


def _chaos_drill(out: list[str]) -> None:
    """Self-healing section: the seeded chaos drill's recovery
    invariants (docs/30-fault-tolerance.md). Falls back to the
    silicon-proof phase skeleton so a dry run renders the full
    shape."""
    report = _load(ARTIFACTS / "CHAOS_DRILL_DETAILS.json")
    if report is not None:
        scenarios = report.get("scenarios") or [{}]
        data = scenarios[0]
    else:
        proof = _load(ARTIFACTS / "SILICON_PROOF.json") or {}
        phase = next((p for p in proof.get("phases", [])
                      if p.get("phase") == "chaos_drill"), None)
        if phase is None:
            return
        data = phase.get("metrics") or {}
        data.setdefault("invariants", {})
    out.append("## Self-healing (chaos drill)\n")
    out.append("Seeded fault schedule — wedge, mid-run kill, node "
               "preemption, heartbeat blackout, store faults — "
               "replayed against a fakepod pool "
               "(`python tools/chaos_drill.py`, "
               "[30-fault-tolerance.md](30-fault-tolerance.md)). "
               "Healing means every invariant holds after the "
               "drill.\n")
    if data.get("error"):
        out.append(f"**Status**: `{data['error']}`\n")
        return
    out.append("| invariant | value |")
    out.append("|---|---|")
    out.append(f"| same-seed plan determinism | "
               f"{_fmt(data.get('determinism'), 0)} |")
    out.append(f"| injections applied | "
               f"{_fmt(data.get('injections_applied'), 0)} |")
    invariants = data.get("invariants") or {}
    for key, label in _CHAOS_INVARIANTS:
        value = invariants.get(key)
        if key == "tasks" and isinstance(value, dict):
            value = ", ".join(f"{k}={v}"
                              for k, v in sorted(value.items()))
            out.append(f"| {label} | {value} |")
        else:
            out.append(f"| {label} | {_fmt(value, 2)} |")
    out.append("")


def _fleet_elasticity(out: list[str]) -> None:
    """Fleet-elasticity section: the three ISSUE-12 drill results
    from the committed BENCH_fleet_elasticity.json artifact — seeds,
    invariants checked, pass/fail, and the priced recovery-leg
    seconds. Every 'pass' was ASSERTED inside the drill
    (chaos/drill.py), not summarized after the fact."""
    report = (_load(ARTIFACTS / "BENCH_fleet_elasticity.json")
              or {}).get("fleet_elasticity")
    if report is None:
        return
    out.append("## Fleet elasticity (eviction / resize / "
               "migration drills)\n")
    out.append("Forcible eviction of an uncooperative victim, "
               "multi-host reshard-on-restore across a permanent "
               "host loss, and cross-pool gang migration under "
               "total capacity loss — each pinned by a seeded "
               "deterministic chaos drill "
               "(`shipyard chaos drill --evict|--resize|"
               "--migrate`, "
               "[33-elastic-training.md](33-elastic-training.md)).\n")
    if report.get("cpu_marker"):
        out.append("**CPU marker**: orchestration + recovery "
                   "measurement on the CPU fakepod substrate — no "
                   "accelerator involved or claimed.\n")
    out.append("| drill | seed | invariants checked | pass | "
               "recovery leg | leg seconds | wall (s) |")
    out.append("|---|---|---|---|---|---|---|")
    for name in ("eviction", "host_resize", "migration"):
        entry = (report.get("drills") or {}).get(name) or {}
        checked = entry.get("invariants_checked") or []
        out.append(
            f"| {name} | {entry.get('seed', '-')} | "
            f"{len(checked)} | "
            f"{'yes' if entry.get('passed') else 'NO'} | "
            f"{entry.get('recovery_leg', '-')} | "
            f"{_fmt(entry.get('recovery_leg_seconds'), 3)} | "
            f"{_fmt(entry.get('wall_seconds'), 1)} |")
        if entry.get("error"):
            out.append(f"| | | `{entry['error']}` | | | | |")
    out.append("")


def _control_plane(out: list[str]) -> None:
    """Control-plane partition-tolerance section: the three ISSUE-13
    drill results from the committed BENCH_control_plane.json
    artifact — seeds, invariants checked, pass/fail, and the priced
    recovery-leg seconds. Every 'pass' was ASSERTED inside the drill
    (chaos/drill.py), not summarized after the fact."""
    report = (_load(ARTIFACTS / "BENCH_control_plane.json")
              or {}).get("control_plane")
    if report is None:
        return
    out.append("## Control plane (outage / partition / restart "
               "drills)\n")
    out.append("Store-outage ride-through (critical-op retry + "
               "advisory WAL replay), lease-based sweep leadership "
               "with fencing epochs under a leader partition, and "
               "agent crash-restart adoption of still-running "
               "tasks — each pinned by a seeded deterministic chaos "
               "drill (`shipyard chaos drill "
               "--outage|--partition|--restart`, "
               "[30-fault-tolerance.md](30-fault-tolerance.md)).\n")
    if report.get("cpu_marker"):
        out.append("**CPU marker**: orchestration + recovery "
                   "measurement on the CPU fakepod substrate — no "
                   "accelerator involved or claimed.\n")
    out.append("| drill | seed | invariants checked | pass | "
               "recovery leg | leg seconds | wall (s) |")
    out.append("|---|---|---|---|---|---|---|")
    for name in ("store_outage", "leader_partition",
                 "agent_restart"):
        entry = (report.get("drills") or {}).get(name) or {}
        checked = entry.get("invariants_checked") or []
        out.append(
            f"| {name} | {entry.get('seed', '-')} | "
            f"{len(checked)} | "
            f"{'yes' if entry.get('passed') else 'NO'} | "
            f"{entry.get('recovery_leg', '-')} | "
            f"{_fmt(entry.get('recovery_leg_seconds'), 3)} | "
            f"{_fmt(entry.get('wall_seconds'), 1)} |")
        if entry.get("error"):
            out.append(f"| | | `{entry['error']}` | | | | |")
    out.append("")


def _fleet_sim(out: list[str]) -> None:
    """Fleet-simulation section: the ISSUE-17 policy proof from the
    committed BENCH_fleet_sim.json artifact — every policy bundle's
    goodput partition on each scenario, and the delta vs baseline.
    The policies are the same pure functions the live claim path,
    preemption sweep, and autoscaler import (sched/policy.py — no
    forked copies), priced by the production goodput engine."""
    report = (_load(ARTIFACTS / "BENCH_fleet_sim.json")
              or {}).get("fleet_sim")
    if report is None:
        return
    out.append("## Fleet simulation (policy goodput deltas)\n")
    out.append(
        f"Discrete-event fleet simulator "
        f"([35-fleet-simulator.md](35-fleet-simulator.md)): "
        f"{_fmt(report.get('nodes'))} virtual nodes, "
        f"{_fmt(report.get('tasks'))} tasks per run, seed "
        f"{report.get('seed', '-')}, priced by the production "
        f"goodput engine (`shipyard sim compare`). Deltas are vs "
        f"the `baseline` policy bundle on the same scenario and "
        f"seed; every partition is exact "
        f"(all_partitions_exact="
        f"{report.get('all_partitions_exact')}).\n")
    if report.get("cpu_marker"):
        out.append("**CPU marker**: a discrete-event simulation on "
                   "a virtual clock — no accelerator involved or "
                   "claimed.\n")
    out.append("| scenario | policy | goodput ratio | Δ ratio vs "
               "baseline | Δ badput (s) | Δ queue wait mean (s) | "
               "partition exact | wall (s) |")
    out.append("|---|---|---|---|---|---|---|---|")
    for scenario, section in (report.get("scenarios") or {}).items():
        for policy, row in (section or {}).items():
            goodput = row.get("goodput") or {}
            delta = row.get("delta_vs_baseline") or {}
            badput_delta = delta.get("badput_seconds_delta") or {}
            out.append(
                f"| {scenario} | {policy} | "
                f"{_fmt(goodput.get('goodput_ratio'), 4)} | "
                f"{_fmt(delta.get('goodput_ratio_delta'), 4)} | "
                f"{_fmt(sum(badput_delta.values()), 1) if badput_delta else '—'} | "
                f"{_fmt(row.get('queue_wait_mean_delta'), 2)} | "
                f"{'yes' if row.get('partition_exact') else 'NO'} | "
                f"{_fmt(row.get('bench_wall_seconds'), 1)} |")
    out.append("")


def _serving_slo(out: list[str]) -> None:
    """Prefix-cache/SLO section: the ISSUE-18 A/B proof from the
    committed BENCH_serving_slo.json artifact — the SAME shared-prefix
    diurnal workload (identical seed) through a prefix-cache-on engine
    and a cache-off control, with token-level hit rate, exact TTFT
    deltas, byte-identical greedy outputs, and per-class SLO
    attainment."""
    report = (_load(ARTIFACTS / "BENCH_serving_slo.json")
              or {}).get("serving_slo")
    if report is None:
        return
    out.append("## Serving, cross-request prefix cache + SLO "
               "classes\n")
    out.append(
        f"Shared-prefix diurnal workload "
        f"([36-prefix-caching.md](36-prefix-caching.md)): "
        f"{_fmt(report.get('num_requests'))} requests, "
        f"{_fmt(report.get('shared_prefix_groups'))} prefix groups x "
        f"{_fmt(report.get('shared_prefix_len'))} shared tokens, "
        f"seed {report.get('seed', '-')}, identical arrivals and "
        f"prompts on both arms. Token-level prefix hit rate "
        f"{_fmt(report.get('prefix_hit_rate'), 3)}; greedy outputs "
        f"byte-identical across arms: "
        f"{report.get('outputs_identical')}.\n")
    if report.get("cpu_marker"):
        out.append("**CPU marker**: a relative A/B measurement on "
                   "whatever backend ran it — no accelerator "
                   "figures claimed.\n")
    on = report.get("prefix_cache_on") or {}
    off = report.get("prefix_cache_off") or {}
    out.append("| arm | completed | shed | TTFT mean (ms) | "
               "TTFT p99 (ms) | TPOT mean (ms) |")
    out.append("|---|---|---|---|---|---|")
    for name, arm in (("prefix cache ON", on),
                      ("prefix cache OFF (control)", off)):
        exact = arm.get("ttft_exact_ms") or {}
        out.append(
            f"| {name} | {_fmt(arm.get('completed'))} | "
            f"{_fmt(arm.get('shed'))} | "
            f"{_fmt(arm.get('ttft_mean_ms'), 2)} | "
            f"{_fmt(exact.get('p99'), 2)} | "
            f"{_fmt(arm.get('tpot_mean_ms'), 2)} |")
    out.append("")
    out.append(
        f"TTFT deltas (ON − OFF): mean "
        f"{_fmt(report.get('ttft_mean_delta_ms'), 2)} ms, p99 "
        f"{_fmt(report.get('ttft_p99_delta_ms'), 2)} ms.\n")
    attain = (on.get("slo_attainment") or {})
    if attain:
        out.append("| SLO class | requests | TTFT target (ms) | "
                   "TTFT attainment | TPOT target (ms) | "
                   "TPOT attainment |")
        out.append("|---|---|---|---|---|---|")
        for name in sorted(attain):
            row = attain[name] or {}
            out.append(
                f"| {name} | {_fmt(row.get('requests'))} | "
                f"{_fmt(row.get('ttft_target_ms'))} | "
                f"{_fmt(row.get('ttft_attainment'), 3)} | "
                f"{_fmt(row.get('tpot_target_ms'))} | "
                f"{_fmt(row.get('tpot_attainment'), 3)} |")
        out.append("")


def _serving_resilience(out: list[str]) -> None:
    """Serving fault-tolerance section: the ISSUE-20 drill results
    from the committed BENCH_serving_resilience.json artifact —
    seeds, invariants checked, pass/fail, and the priced
    serving_recovery leg seconds. Every 'pass' was ASSERTED inside
    the drill (chaos/serving_drill.py): zero lost requests,
    exactly-once token delivery, byte-identical greedy streams
    across the fault, exact goodput partition."""
    report = (_load(ARTIFACTS / "BENCH_serving_resilience.json")
              or {}).get("serving_resilience")
    if report is None:
        return
    out.append("## Serving resilience (kill / drain / router "
               "drills)\n")
    out.append("Mid-stream replica kill with sibling resume, "
               "graceful drain on a preempt notice (no new "
               "admissions, in-flight decodes finish), and a router "
               "crash ridden out by client cancel-then-resume — "
               "each pinned by a seeded deterministic chaos drill "
               "(`shipyard chaos drill "
               "--serve-kill|--serve-drain|--serve-router`, "
               "[37-serving-resilience.md](37-serving-resilience"
               ".md)).\n")
    if report.get("cpu_marker"):
        out.append("**CPU marker**: real HTTP replicas + router "
                   "over tiny fp32 CPU engines — no accelerator "
                   "involved or claimed.\n")
    out.append("| drill | seed | invariants checked | pass | "
               "recovery leg | leg seconds | wall (s) |")
    out.append("|---|---|---|---|---|---|---|")
    for name in ("replica_kill", "replica_drain",
                 "router_restart"):
        entry = (report.get("drills") or {}).get(name) or {}
        checked = entry.get("invariants_checked") or []
        out.append(
            f"| {name} | {entry.get('seed', '-')} | "
            f"{len(checked)} | "
            f"{'yes' if entry.get('passed') else 'NO'} | "
            f"{entry.get('recovery_leg', '-')} | "
            f"{_fmt(entry.get('recovery_leg_seconds'), 3)} | "
            f"{_fmt(entry.get('wall_seconds'), 1)} |")
        if entry.get("error"):
            out.append(f"| | | `{entry['error']}` | | | | |")
    out.append("")


def _goodput(out: list[str]) -> None:
    """ML-productivity goodput section: always names goodput_ratio,
    the three decomposition legs, and EVERY badput category (the
    skeleton is the contract — a dry run renders the full shape with
    unmeasured values)."""
    from batch_shipyard_tpu.goodput.accounting import BADPUT_CATEGORIES
    report = _load(ARTIFACTS / "GOODPUT_REPORT.json")
    if report is None:
        # Fall back to the silicon-proof phase's skeleton metrics.
        proof = _load(ARTIFACTS / "SILICON_PROOF.json") or {}
        phase = next((p for p in proof.get("phases", [])
                      if p.get("phase") == "goodput"), None)
        if phase is None:
            return
        report = phase.get("metrics") or {
            "goodput_ratio": phase.get("goodput_ratio"),
            "badput_seconds": phase.get("badput_seconds") or {}}
    out.append("## Goodput decomposition\n")
    out.append("ML Productivity Goodput (arxiv 2502.06982): "
               "`goodput_ratio = availability x resource x program`, "
               "with badput attributed per category "
               "(`shipyard goodput pool`).\n")
    out.append("| metric | value |")
    out.append("|---|---|")
    out.append(f"| goodput_ratio | "
               f"{_fmt(report.get('goodput_ratio'), 3)} |")
    for leg in ("availability_goodput", "resource_goodput",
                "program_goodput"):
        if leg in report:
            out.append(f"| {leg} | {_fmt(report.get(leg), 3)} |")
    badput = report.get("badput_seconds") or {}
    for category in BADPUT_CATEGORIES:
        out.append(f"| badput_seconds{{category=\"{category}\"}} | "
                   f"{_fmt(badput.get(category), 2)} |")
    from batch_shipyard_tpu.goodput.accounting import (
        OVERLAPPED_CATEGORIES)
    overlapped = report.get("overlapped_seconds") or {}
    for category in OVERLAPPED_CATEGORIES:
        out.append(
            f"| overlapped_seconds{{category=\"{category}\"}} "
            f"(not badput) | {_fmt(overlapped.get(category), 2)} |")
    out.append("")


def _silicon_proof(out: list[str]) -> None:
    proof = _load(ARTIFACTS / "SILICON_PROOF.json")
    if not proof:
        return
    out.append("## Silicon proof pipeline (latest run)\n")
    out.append(f"Run finished {proof.get('finished_at')} "
               + ("(dry run)" if proof.get("dry_run") else "")
               + ".\n")
    out.append("| phase | status |")
    out.append("|---|---|")
    for phase in proof.get("phases", []):
        out.append(f"| {phase.get('phase')} | "
                   f"{phase.get('status')} |")
    out.append("")
    marker = _load(ARTIFACTS / "KERNEL_VALIDATION.json")
    if marker:
        out.append("Kernel validation marker "
                   "(gates `impl='auto'` Pallas dispatch):\n")
        out.append("| kernel | on-chip pass |")
        out.append("|---|---|")
        for name, record in sorted(marker.items()):
            ok = (record.get("ok") and
                  record.get("backend") == "tpu")
            out.append(f"| {name} | {'yes' if ok else 'no'} |")
        out.append("")


def render() -> str:
    out: list[str] = []
    out.append("# Measured performance\n")
    out.append("This page is GENERATED by `tools/benchgen.py` from "
               "the bench pipeline's JSON artifacts — do not edit by "
               "hand; re-run the generator (tools/silicon_proof.py "
               "does so after every successful bench).\n")
    _round_history(out)
    details = _load(ARTIFACTS / "BENCH_DETAILS.json") or {}
    # The speculative serving benches run as their OWN silicon-proof
    # phase (bench.py --workloads serving_speculative) with a
    # separate details file; merge them in unless a direct bench run
    # already recorded them.
    spec_details = _load(ARTIFACTS / "SPEC_SERVING_DETAILS.json") or {}
    for key in ("serving_speculative", "serving_speculative_paged"):
        if key not in details and key in spec_details:
            details[key] = spec_details[key]
    # Same for the checkpoint-overhead phase's own details file.
    ckpt_details = _load(ARTIFACTS / "CKPT_OVERHEAD_DETAILS.json") or {}
    if "checkpoint_overhead" not in details and \
            "checkpoint_overhead" in ckpt_details:
        details["checkpoint_overhead"] = (
            ckpt_details["checkpoint_overhead"])
    # And the warm-start compilation phase's.
    cw_details = _load(ARTIFACTS / "COMPILE_WARM_DETAILS.json") or {}
    if "compile_warm" not in details and "compile_warm" in cw_details:
        details["compile_warm"] = cw_details["compile_warm"]
    # And the ring-collectives kernel phase's.
    ring_details = _load(
        ARTIFACTS / "RING_COLLECTIVES_DETAILS.json") or {}
    if "ring_collectives" not in details and \
            "ring_collectives" in ring_details:
        details["ring_collectives"] = (
            ring_details["ring_collectives"])
    # And the 10^5 scheduler-scale phase's committed artifact.
    sched_details = _load(
        ARTIFACTS / "BENCH_scheduler_scale.json") or {}
    if "scheduler_scale" not in details and \
            "scheduler_scale" in sched_details:
        details["scheduler_scale"] = (
            sched_details["scheduler_scale"])
    out.append("## Latest detailed run\n")
    if details.get("error"):
        out.append(f"**Status**: `{details['error']}`\n")
        stale = details.get("last_successful_run_stale")
        if stale:
            out.append("Figures below are the LAST SUCCESSFUL run "
                       "(stale, kept for reference):\n")
            details = {**details, **stale}
    if details.get("platform"):
        out.append(f"Platform: {details['platform']} "
                   f"({', '.join(details.get('devices', []))}); "
                   f"XLA tuning profile: "
                   f"`{details.get('xla_tuning_profile')}`.\n")
    _workload(out, "ResNet-50 training", details.get("resnet50", {}),
              "images_per_sec_per_chip", "images/sec/chip")
    _workload(out, "Transformer training (303M, T=2048)",
              details.get("transformer", {}),
              "tokens_per_sec_per_chip", "tokens/sec/chip")
    _workload(out, "Transformer training, int8 matmuls",
              details.get("transformer_int8", {}),
              "tokens_per_sec_per_chip", "tokens/sec/chip")
    _serving(out, "Serving (single replica, Poisson load)",
             details.get("serving", {}))
    _serving(out, "Serving, int8 paged KV + overcommit",
             details.get("serving_paged_int8", {}))
    _serving(out, "Serving fleet (router over replicas)",
             details.get("serving_fleet", {}))
    _serving(out, "Serving, speculative decoding (dense KV)",
             details.get("serving_speculative", {}))
    _serving(out, "Serving, speculative decoding (paged KV)",
             details.get("serving_speculative_paged", {}))
    _checkpoint_overhead(out, details.get("checkpoint_overhead", {}))
    _compile_warm(out, details.get("compile_warm", {}))
    _ring_collectives(out, details.get("ring_collectives", {}))
    _orchestration(out, details.get("orchestration", {}))
    _scheduler_scale(out, details.get("scheduler_scale", {}))
    _goodput(out)
    _chaos_drill(out)
    _fleet_elasticity(out)
    _control_plane(out)
    _fleet_sim(out)
    _serving_slo(out)
    _serving_resilience(out)
    _silicon_proof(out)
    return "\n".join(out).rstrip() + "\n"


def main(argv=None) -> int:
    global ARTIFACTS
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out",
                        default=str(REPO_ROOT /
                                    "docs/26-benchmarks.md"))
    parser.add_argument("--artifacts-dir", default=str(REPO_ROOT),
                        help="where BENCH_DETAILS/LATEST, "
                        "SILICON_PROOF and KERNEL_VALIDATION live")
    args = parser.parse_args(argv)
    ARTIFACTS = pathlib.Path(args.artifacts_dir)
    content = render()
    with open(args.out, "w", encoding="utf-8") as fh:
        fh.write(content)
    print(f"wrote {args.out} ({len(content.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Job schedules: recurrence-driven job instantiation.

Reference analog: job schedules with the recurrent job manager task
(batch.py:5392+ recurrence -> JobScheduleAddParameter;
cargo/recurrent_job_manager.py regenerating the task collection each
recurrence and optionally terminating the job when tasks complete).

Ours is a storage-mediated scheduler loop: schedule state (next run
number, timestamps) lives in a table row, each recurrence submits a
fresh job ``<job-id>:NNNNN`` with the template's tasks, and an optional
monitor waits for completion and terminates the instance (the
monitor_task_completion knob). Runs in-process (tests), as a CLI
daemon verb, or on a service VM.
"""

from __future__ import annotations

import datetime
import threading
import time
from typing import Optional

from batch_shipyard_tpu.config.settings import JobSettings, PoolSettings
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, NotFoundError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_SCHED_TABLE = names.TABLE_JOBSCHEDULES


def _parse_ts(value: Optional[str]) -> Optional[float]:
    if not value:
        return None
    return datetime.datetime.fromisoformat(
        value.replace("Z", "+00:00")).timestamp()


def instance_id(job_id: str, run_number: int) -> str:
    return f"{job_id}-r{run_number:05d}"


def schedule_state(store: StateStore, pool_id: str,
                   job_id: str) -> dict:
    try:
        return store.get_entity(_SCHED_TABLE, pool_id, job_id)
    except NotFoundError:
        return {"run_number": 0, "last_run_at": None}


def run_due_schedules(store: StateStore, pool: PoolSettings,
                      jobs: list[JobSettings],
                      now: Optional[float] = None) -> list[str]:
    """One evaluation pass: submit an instance for every schedule whose
    interval has elapsed. Returns new job instance ids."""
    now = now if now is not None else time.time()
    launched: list[str] = []
    for job in jobs:
        rec = job.recurrence
        if rec is None:
            continue
        not_before = _parse_ts(rec.do_not_run_until)
        not_after = _parse_ts(rec.do_not_run_after)
        if not_before and now < not_before:
            continue
        if not_after and now > not_after:
            continue
        state = schedule_state(store, pool.id, job.id)
        last = state.get("last_run_at")
        if last is not None and now - last < (
                rec.recurrence_interval_seconds):
            continue
        if rec.run_exclusive and state.get("active_instance"):
            active = state["active_instance"]
            try:
                entity = jobs_mgr.get_job(store, pool.id, active)
                if entity.get("state") == "active":
                    continue  # previous recurrence still running
            except jobs_mgr.JobNotFoundError:
                pass
        run_number = int(state.get("run_number", 0))
        inst = instance_id(job.id, run_number)
        # Claim the recurrence BEFORE submitting: evaluators run
        # concurrently (CLI daemon + pool service VM are both
        # documented run modes, docs/04), and the old blind upsert
        # after add_jobs let two of them read run_number=N and both
        # launch instance N. insert-as-claim covers the first run,
        # etag-guarded merge every later one; losing the race means
        # another evaluator owns this recurrence. If add_jobs then
        # fails, the claimed run is skipped — the next interval fires
        # normally — which beats a double submission.
        claim = {
            "run_number": run_number + 1,
            "last_run_at": now,
            "active_instance": inst,
        }
        try:
            etag = state.get("_etag")
            if etag:
                store.merge_entity(_SCHED_TABLE, pool.id, job.id,
                                   claim, if_match=etag)
            else:
                # Insert-as-claim: EntityExistsError IS the
                # concurrent-evaluator signal; batching would
                # destroy the per-schedule claim semantics.
                store.insert_entity(_SCHED_TABLE, pool.id, job.id,  # shipyard-lint: disable=store-write-in-loop
                                    claim)
        except (EtagMismatchError, EntityExistsError):
            logger.info("schedule %s: recurrence %d claimed by a "
                        "concurrent evaluator; skipping", job.id,
                        run_number)
            continue
        instance_settings = _instantiate(job, inst)
        jobs_mgr.add_jobs(store, pool, [instance_settings])
        launched.append(inst)
        logger.info("schedule %s launched instance %s", job.id, inst)
    return launched


def _instantiate(job: JobSettings, inst_id: str) -> JobSettings:
    import dataclasses
    return dataclasses.replace(
        job, id=inst_id, recurrence=None,
        auto_complete=(job.auto_complete or
                       job.recurrence.monitor_task_completion))


def register_schedules(store: StateStore, pool_id: str,
                       jobs_config: dict) -> list[str]:
    """Persist the recurrence-bearing job templates from a raw jobs
    config into the state store, so a POOL-RESIDENT scheduler (the
    reference runs its recurrent job manager as a job-manager task on
    the pool, cargo/recurrent_job_manager.py:187) can fire them with
    no CLI process alive. Returns the registered job ids."""
    from batch_shipyard_tpu.config import settings as settings_mod
    registered = []
    for raw in jobs_config.get("job_specifications") or []:
        if not raw.get("recurrence"):
            continue
        # Parse NOW so a malformed template fails registration rather
        # than poisoning every pool-service pass later.
        parsed = settings_mod.job_settings_list(
            {"job_specifications": [raw]})[0]
        if parsed.recurrence.recurrence_interval_seconds is None:
            raise ValueError(
                f"schedule {raw['id']}: recurrence.schedule."
                f"recurrence_interval_seconds is required")
        # Template rows are operator-CLI single-writer surface and
        # re-registration REPLACES the spec by design — blind upsert
        # is the intended semantics here, unlike the multi-evaluator
        # schedule-state rows above.
        # shipyard-lint: disable=store-blind-upsert
        store.upsert_entity(
            _SCHED_TABLE, f"{pool_id}#templates", raw["id"],
            {"spec": raw})
        registered.append(raw["id"])
    return registered


def unregister_schedule(store: StateStore, pool_id: str,
                        job_id: str) -> None:
    store.delete_entity(_SCHED_TABLE, f"{pool_id}#templates", job_id)


def stored_schedule_jobs(store: StateStore,
                         pool_id: str) -> list[JobSettings]:
    """Parse the registered templates back into JobSettings (re-read
    every pass so new registrations are picked up live)."""
    from batch_shipyard_tpu.config import settings as settings_mod
    specs = [row["spec"] for row in store.query_entities(
        _SCHED_TABLE, partition_key=f"{pool_id}#templates")]
    if not specs:
        return []
    return settings_mod.job_settings_list(
        {"job_specifications": specs})


def run_pool_schedule_service(store: StateStore, pool: PoolSettings,
                              stop_event: Optional[
                                  threading.Event] = None,
                              poll_interval: float = 1.0) -> int:
    """The pool-resident scheduler loop: like run_schedule_daemon but
    template-driven from the state store instead of a CLI process's
    parsed config. Runs on worker 0 when
    pool_specification.pool_services.schedules is enabled."""
    stop = stop_event or threading.Event()
    total = 0
    while not stop.is_set():
        try:
            jobs = stored_schedule_jobs(store, pool.id)
            if jobs:
                total += len(run_due_schedules(store, pool, jobs))
        except Exception:
            logger.exception("pool schedule service pass failed")
        if stop.wait(poll_interval):
            break
    return total


def run_schedule_daemon(store: StateStore, pool: PoolSettings,
                        jobs: list[JobSettings],
                        stop_event: Optional[threading.Event] = None,
                        poll_interval: float = 1.0,
                        max_recurrences: Optional[int] = None) -> int:
    """Scheduler loop (the recurrent-job-manager daemon). Returns the
    number of instances launched."""
    stop = stop_event or threading.Event()
    total = 0
    while not stop.is_set():
        launched = run_due_schedules(store, pool, jobs)
        total += len(launched)
        if max_recurrences is not None and total >= max_recurrences:
            break
        if stop.wait(poll_interval):
            break
    return total

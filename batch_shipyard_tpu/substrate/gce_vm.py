"""GCE VM lifecycle helper: the ARM/virtual_machine layer analog.

Reference analog: convoy/resource.py (create_virtual_machine,
create_network_interface, the async ARM deployers) — re-designed as a
thin gcloud-driven manager shared by every subsystem that needs a
standalone VM next to the TPU pools: remotefs NFS servers
(remotefs/manager.py), the monitoring VM (monitor/provision.py), and
the slurm controller/login nodes (slurm/provision.py).

All gcloud invocations go through an injectable ``runner`` so every
caller is unit-testable without cloud access (same pattern as
substrate/gcp_tpu.py's _gcloud seam).
"""

from __future__ import annotations

import shutil
import tempfile
from typing import Callable, Optional, Sequence

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

Runner = Callable[..., tuple[int, str, str]]


class GceVmError(RuntimeError):
    pass


class GceVmManager:
    """Create/stop/start/resize/delete GCE VMs and disks."""

    def __init__(self, project: str, zone: Optional[str] = None,
                 network: Optional[str] = None,
                 runner: Optional[Runner] = None):
        if runner is None and shutil.which("gcloud") is None:
            raise GceVmError(
                "gcloud CLI is required for GCE VM provisioning")
        self.project = project
        self.zone = zone
        self.network = network
        self._run = runner or util.subprocess_capture

    # ------------------------------ plumbing ---------------------------

    def _scope(self) -> list[str]:
        args = [f"--project={self.project}"]
        if self.zone:
            args.append(f"--zone={self.zone}")
        return args

    def _gcloud(self, *args: str) -> str:
        rc, out, err = self._run(["gcloud", "compute", *args,
                                  *self._scope()])
        if rc != 0:
            raise GceVmError(
                f"gcloud compute {args[0]} {args[1] if len(args) > 1 else ''} "
                f"failed: {err.strip() or out.strip()}")
        return out

    # ------------------------------- disks -----------------------------

    def create_disk(self, name: str, size_gb: int,
                    disk_type: str = "pd-ssd") -> None:
        self._gcloud("disks", "create", name, f"--size={size_gb}GB",
                     f"--type={disk_type}")

    def delete_disk(self, name: str) -> None:
        self._gcloud("disks", "delete", name, "--quiet")

    def attach_disk(self, vm_name: str, disk_name: str,
                    device_name: str) -> None:
        self._gcloud("instances", "attach-disk", vm_name,
                     f"--disk={disk_name}",
                     f"--device-name={device_name}")

    # -------------------------------- vms ------------------------------

    def create_vm(self, name: str, machine_type: str,
                  startup_script: Optional[str] = None,
                  disks: Sequence[tuple[str, str]] = (),
                  tags: Sequence[str] = (),
                  boot_disk_size_gb: int = 64,
                  public_ip: bool = True) -> str:
        """Create a VM; returns its internal IP.

        disks: (disk_name, device_name) pairs to attach at create.
        public_ip=False creates the VM with no external address
        (monitor/federation/slurm yaml public_ip.enabled: false —
        private-VPC-only service VMs).
        """
        args = ["instances", "create", name,
                f"--machine-type={machine_type}",
                f"--boot-disk-size={boot_disk_size_gb}GB"]
        if not public_ip:
            args.append("--no-address")
        if self.network:
            args.append(f"--network={self.network}")
        if tags:
            args.append(f"--tags={','.join(tags)}")
        for disk_name, device in disks:
            args += ["--disk", f"name={disk_name},"
                     f"device-name={device},mode=rw"]
        script_path = None
        try:
            if startup_script is not None:
                # Startup scripts can embed secrets (db passwords,
                # bundle payloads) — never leave them in /tmp.
                with tempfile.NamedTemporaryFile(
                        "w", suffix=".sh", delete=False) as fh:
                    fh.write(startup_script)
                    script_path = fh.name
                args.append(
                    f"--metadata-from-file=startup-script="
                    f"{script_path}")
            self._gcloud(*args)
        finally:
            if script_path is not None:
                import os
                os.unlink(script_path)
        return self.internal_ip(name)

    def internal_ip(self, name: str) -> str:
        out = self._gcloud(
            "instances", "describe", name,
            "--format=value(networkInterfaces[0].networkIP)")
        return out.strip()

    def vm_status(self, name: str) -> str:
        out = self._gcloud("instances", "describe", name,
                           "--format=value(status)")
        return out.strip()

    def stop_vm(self, name: str) -> None:
        self._gcloud("instances", "stop", name)

    def start_vm(self, name: str) -> None:
        self._gcloud("instances", "start", name)

    def set_machine_type(self, name: str, machine_type: str) -> None:
        """VM must be stopped first (gcloud enforces this)."""
        self._gcloud("instances", "set-machine-type", name,
                     f"--machine-type={machine_type}")

    def delete_vm(self, name: str) -> None:
        self._gcloud("instances", "delete", name, "--quiet")

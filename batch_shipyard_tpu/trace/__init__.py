"""End-to-end distributed tracing + on-demand step profiling.

The goodput subsystem (goodput/) answers *how much* wall clock a pool
lost per category; this package answers *where one specific
submission lost it*: a trace context born at ``jobs add`` is persisted
on every task row, carried through queue messages and gang attempt
partitions, and exported into task processes as ``$SHIPYARD_TRACE_*``
env — so agent-side lifecycle spans (claim, backoff, rendezvous,
launch) and in-process program spans (compile, checkpoint, train step
windows, serving requests) all share one causal chain that
``shipyard trace show|export`` can assemble into a Perfetto-loadable
Chrome trace.

Modules:
  context.py    trace-context identity (trace_id/span_id/parent),
                env contract, task-row persistence helpers
  spans.py      declared span-kind registry + store-backed and
                process-local (JSONL) span recorders, agent-ingested
                post-task exactly like the goodput recorder
  export.py     spans + goodput intervals -> Chrome trace-event JSON
                (one track per node/slot/request)
  histogram.py  fixed log-bucket latency histograms, mergeable across
                replicas/router, backing TTFT/TPOT/step-time
                percentiles and Prometheus ``_bucket`` export
  profiling.py  on-demand ``jax.profiler`` step capture driven by the
                ``jobs profile`` store flag the agent forwards
"""

from batch_shipyard_tpu.trace.context import (  # noqa: F401
    TRACE_FILE_ENV, TRACE_ID_ENV, TRACE_SPAN_ENV, TraceContext)

"""Signed-URL generation (VERDICT r4 next #7, `storage sas` analog,
reference shipyard.py:1327): V4 URLs through the gcs backend's fake
client, clear refusal on local backends, and the CLI verb incl.
prefix mode."""

import json

import pytest
import yaml
from click.testing import CliRunner

from batch_shipyard_tpu.cli.main import cli
from batch_shipyard_tpu.state.base import NotFoundError
from batch_shipyard_tpu.state.localfs import LocalFSStateStore
from batch_shipyard_tpu.state.memory import MemoryStateStore
from tests.fake_gcs import make_fake_gcs_store


@pytest.fixture()
def gcs():
    return make_fake_gcs_store()


def test_signed_get_url_for_existing_object(gcs):
    gcs.put_object("out/result.bin", b"payload")
    url = gcs.generate_signed_url("out/result.bin",
                                  expires_seconds=600)
    assert url.startswith("https://")
    assert "out/result.bin" in url
    assert "X-Goog-Expires=600" in url
    assert "X-Goog-Method=GET" in url


def test_signed_get_missing_object_raises(gcs):
    with pytest.raises(NotFoundError):
        gcs.generate_signed_url("absent.bin")


def test_signed_put_url_does_not_require_existence(gcs):
    url = gcs.generate_signed_url("incoming/up.bin", method="PUT")
    assert "X-Goog-Method=PUT" in url


def test_unsupported_method_rejected(gcs):
    with pytest.raises(ValueError):
        gcs.generate_signed_url("k", method="POST")


@pytest.mark.parametrize("store_cls", [MemoryStateStore])
def test_local_backends_refuse_clearly(store_cls, tmp_path):
    store = store_cls()
    with pytest.raises(NotImplementedError) as exc:
        store.generate_signed_url("k")
    assert "gcs backend" in str(exc.value)


def test_localfs_refuses_clearly(tmp_path):
    store = LocalFSStateStore(str(tmp_path / "s"))
    with pytest.raises(NotImplementedError):
        store.generate_signed_url("k")


@pytest.fixture()
def configdir(tmp_path):
    confs = {
        "credentials": {"credentials": {
            "storage": {"backend": "localfs",
                        "root": str(tmp_path / "store")}}},
        "config": {"global_resources": {"docker_images": []}},
        "pool": {"pool_specification": {
            "id": "p", "substrate": "fake",
            "tpu": {"accelerator_type": "v5litepod-8"}}},
    }
    for name, data in confs.items():
        with open(tmp_path / f"{name}.yaml", "w") as fh:
            yaml.safe_dump(data, fh)
    return str(tmp_path)


def test_cli_sas_on_localfs_errors_cleanly(configdir):
    result = CliRunner().invoke(
        cli, ["--configdir", configdir, "storage", "sas", "some/key"])
    assert result.exit_code != 0
    assert "gcs backend" in result.output


def test_cli_sas_prefix_put_rejected(configdir):
    result = CliRunner().invoke(
        cli, ["--configdir", configdir, "storage", "sas", "p/",
              "--prefix", "--method", "PUT"])
    assert result.exit_code != 0
    assert "GET-only" in result.output


def test_cli_sas_gcs_prefix(configdir, monkeypatch):
    """Prefix mode signs every object under the prefix (GET)."""
    store = make_fake_gcs_store()
    store.put_object("ingress/a.bin", b"a")
    store.put_object("ingress/b.bin", b"b")
    store.put_object("other/c.bin", b"c")
    from batch_shipyard_tpu import fleet as fleet_mod
    monkeypatch.setattr(fleet_mod, "create_statestore",
                        lambda *_a, **_k: store)
    result = CliRunner().invoke(
        cli, ["--configdir", configdir, "--raw", "storage", "sas",
              "ingress/", "--prefix"], catch_exceptions=False)
    assert result.exit_code == 0, result.output
    out = json.loads(result.output)
    assert set(out["urls"]) == {"ingress/a.bin", "ingress/b.bin"}
    assert all(u.startswith("https://") for u in out["urls"].values())

"""Attention kernels: reference, blockwise (memory-efficient), and a
Pallas flash-attention forward for the TPU MXU.

Layout convention throughout: q/k/v are [batch, seq, heads, head_dim]
(bfloat16 on TPU; accumulation in float32).

  - ``mha_reference``: O(T^2) materialized-scores attention, the
    correctness oracle.
  - ``blockwise_mha``: lax.scan over KV blocks with online softmax —
    O(T) memory, fully differentiable (the building block ring
    attention runs per step). This is the XLA-friendly formulation:
    static shapes, no data-dependent control flow.
  - ``flash_attention``: Pallas TPU kernel for the forward pass (grid
    over batch*heads x q-blocks, KV streamed through VMEM); backward
    falls back to the blockwise formulation via custom_vjp, keeping
    training end-to-end differentiable while the hot inference path
    uses the hand kernel.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _causal_mask(q_positions, k_positions):
    """[Tq, Tk] True where attention is allowed (k <= q)."""
    return q_positions[:, None] >= k_positions[None, :]


def mha_reference(q, k, v, causal: bool = True,
                  q_offset: int = 0, kv_offset: int = 0):
    """Plain attention; the numerics oracle for the fast paths."""
    depth = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(depth)
    if causal:
        q_pos = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[1], 1), 0)[:, 0]
        k_pos = kv_offset + jax.lax.broadcasted_iota(
            jnp.int32, (k.shape[1], 1), 0)[:, 0]
        mask = _causal_mask(q_pos, k_pos)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ----------------------- online-softmax accumulation -------------------

def attention_block_update(q, k_blk, v_blk, o, m, l, *, causal: bool,
                           q_offset, kv_offset, scale: float):
    """One online-softmax accumulation step against a KV block.

    q: [B, Tq, H, D]; k_blk/v_blk: [B, Tk, H, D]
    o: [B, Tq, H, D] float32 numerator
    m: [B, H, Tq] running max; l: [B, H, Tq] running denominator.
    q_offset/kv_offset: global positions (ints or traced scalars).
    """
    depth = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[1], 1), 0)[:, 0]
        k_pos = kv_offset + jax.lax.broadcasted_iota(
            jnp.int32, (k_blk.shape[1], 1), 0)[:, 0]
        mask = _causal_mask(q_pos, k_pos)
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # exp with stable max; rows with no valid keys stay at -inf max and
    # contribute nothing (exp(-inf - -inf) handled via where).
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def attention_init(q):
    batch, t_q, heads, depth = q.shape
    o = jnp.zeros((batch, t_q, heads, depth), dtype=jnp.float32)
    m = jnp.full((batch, heads, t_q), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((batch, heads, t_q), dtype=jnp.float32)
    return o, m, l


def attention_finalize(q, o, m, l):
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def blockwise_mha(q, k, v, causal: bool = True, block_size: int = 512,
                  q_offset: int = 0, kv_offset: int = 0):
    """Memory-efficient attention: scan KV blocks with online softmax."""
    batch, t_kv = k.shape[0], k.shape[1]
    block_size = min(block_size, t_kv)
    if t_kv % block_size:
        raise ValueError(
            f"kv length {t_kv} not divisible by block {block_size}")
    num_blocks = t_kv // block_size
    scale = 1.0 / math.sqrt(q.shape[-1])
    k_blocks = k.reshape(batch, num_blocks, block_size, *k.shape[2:])
    v_blocks = v.reshape(batch, num_blocks, block_size, *v.shape[2:])

    # Rematerialize each block update: without this, the scan's
    # backward saves every block's score/probability matrices
    # ([B,H,Tq,block] fp32 per step — gigabytes per layer), defeating
    # the whole point of blockwise attention. With it, the backward
    # recomputes scores per block (the flash-attention property).
    @jax.checkpoint
    def step(carry, blk):
        o, m, l = carry
        k_blk, v_blk, blk_idx = blk
        o, m, l = attention_block_update(
            q, k_blk, v_blk, o, m, l, causal=causal,
            q_offset=q_offset,
            kv_offset=kv_offset + blk_idx * block_size, scale=scale)
        return (o, m, l), None

    carry = attention_init(q)
    (o, m, l), _ = jax.lax.scan(
        step, carry,
        (k_blocks.transpose(1, 0, 2, 3, 4),
         v_blocks.transpose(1, 0, 2, 3, 4),
         jnp.arange(num_blocks)))
    return attention_finalize(q, o, m, l)


# --------------------------- pallas forward ----------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      causal: bool, scale: float, q_block: int):
    """One (batch*head, q-block) program: stream KV blocks via the
    grid-blocked refs and accumulate with online softmax in VMEM."""
    qi = pl.program_id(1)
    q_tile = q_ref[...].astype(jnp.float32)  # [q_block, D]
    t_kv = k_ref.shape[0]
    num_kb = t_kv // block_k

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32)
        v_blk = v_ref[pl.ds(kb * block_k, block_k), :].astype(
            jnp.float32)
        scores = jax.lax.dot_general(
            q_tile, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [qb, kb]
        if causal:
            q_pos = (qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (q_block, block_k), 0))
            k_pos = (kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (q_block, block_k), 1))
            scores = jnp.where(q_pos >= k_pos, scores, _NEG_INF)
        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[:, None])
        l_new = l * correction + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_new = o * correction[:, None] + pv
        return o_new, m_new, l_new

    o = jnp.zeros((q_block, q_ref.shape[-1]), dtype=jnp.float32)
    m = jnp.full((q_block,), _NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((q_block,), dtype=jnp.float32)
    if causal:
        # Only blocks up to (and including) the diagonal contribute.
        upper = jnp.minimum(
            num_kb, (qi + 1) * q_block // block_k + 1)
    else:
        upper = num_kb
    o, m, l = jax.lax.fori_loop(0, upper, body, (o, m, l))
    denom = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (o / denom[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, block_q: int, block_k: int):
    batch, t_q, heads, depth = q.shape
    t_kv = k.shape[1]
    scale = 1.0 / math.sqrt(depth)
    # Collapse batch/heads into the grid's first dimension.
    q_r = q.transpose(0, 2, 1, 3).reshape(batch * heads, t_q, depth)
    k_r = k.transpose(0, 2, 1, 3).reshape(batch * heads, t_kv, depth)
    v_r = v.transpose(0, 2, 1, 3).reshape(batch * heads, t_kv, depth)
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    if t_q % block_q or t_kv % block_k:
        raise ValueError(
            f"flash attention requires seq lengths divisible by block "
            f"sizes: t_q={t_q} block_q={block_q}, t_kv={t_kv} "
            f"block_k={block_k}")
    grid = (batch * heads, t_q // block_q)
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k,
                          causal=causal, scale=scale, q_block=block_q),
        out_shape=jax.ShapeDtypeStruct((batch * heads, t_q, depth),
                                       q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, depth),
                         lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, t_kv, depth), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, t_kv, depth), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, depth),
                               lambda bh, qi: (bh, qi, 0)),
    )(q_r, k_r, v_r)
    return out.reshape(batch, heads, t_q, depth).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 256,
                    block_k: int = 512):
    """Pallas forward; blockwise-recompute backward."""
    return _flash_forward(q, k, v, causal, block_q, block_k)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    return _flash_forward(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_mha(q_, k_, v_, causal=causal,
                                         block_size=block_k),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def attention(q, k, v, causal: bool = True,
              impl: Optional[str] = None, block_size: int = 512):
    """Dispatch: 'flash' (pallas fwd), 'blockwise', or 'reference'.
    Default: flash on TPU (falling back to blockwise for shapes the
    kernel can't tile), blockwise elsewhere."""
    if impl is None:
        impl = ("flash" if jax.default_backend() == "tpu"
                else "blockwise")
        if impl == "flash" and (q.shape[1] % 256 or k.shape[1] % 512):
            impl = "blockwise"
            block_size = math.gcd(k.shape[1], block_size) or k.shape[1]
    if impl == "flash":
        return flash_attention(q, k, v, causal)
    if impl == "blockwise":
        return blockwise_mha(q, k, v, causal, block_size=block_size)
    if impl == "reference":
        return mha_reference(q, k, v, causal)
    raise ValueError(f"unknown attention impl {impl!r}")

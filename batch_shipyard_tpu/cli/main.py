"""shipyard-tpu CLI: the click command tree.

Reference analog: shipyard.py (3136 LoC click tree: pool/jobs/data/
storage/diag/monitor/fed/slurm groups, shipyard.py:1001-3136). Groups
mirror the reference so a Batch Shipyard user finds the same verbs:

  shipyard-tpu pool   add | list | del | resize | nodes | stats | ssh |
                      images update | autoscale ...
  shipyard-tpu jobs   add | list | term | del | stats | tasks list
  shipyard-tpu data   stream | ingress
  shipyard-tpu diag   perf
  shipyard-tpu storage clear
  shipyard-tpu monitor / fed / slurm (aux clusters)
"""

from __future__ import annotations

import sys

import click

from batch_shipyard_tpu import fleet
from batch_shipyard_tpu.version import __version__


@click.group(context_settings={"help_option_names": ["-h", "--help"]})
@click.version_option(version=__version__)
@click.option("--configdir", envvar="SHIPYARD_CONFIGDIR", default=None,
              help="Directory holding credentials/config/pool/jobs yaml")
@click.option("--credentials", "credentials_path", default=None,
              help="Path to credentials yaml")
@click.option("--config", "config_path", default=None,
              help="Path to global config yaml")
@click.option("--pool", "pool_path", default=None,
              help="Path to pool yaml")
@click.option("--jobs", "jobs_path", default=None,
              help="Path to jobs yaml")
@click.option("--raw", is_flag=True, default=False,
              help="JSON output for scripting")
@click.pass_context
def cli(click_ctx, configdir, credentials_path, config_path, pool_path,
        jobs_path, raw):
    files = {}
    if credentials_path:
        files["credentials"] = credentials_path
    if config_path:
        files["config"] = config_path
    if pool_path:
        files["pool"] = pool_path
    if jobs_path:
        files["jobs"] = jobs_path
    click_ctx.obj = {
        "configdir": configdir, "files": files, "raw": raw, "ctx": None}


def _ctx(click_ctx) -> fleet.Context:
    if click_ctx.obj["ctx"] is None:
        click_ctx.obj["ctx"] = fleet.load_context(
            click_ctx.obj["configdir"], click_ctx.obj["files"])
    return click_ctx.obj["ctx"]


# ------------------------------- pool ----------------------------------

@cli.group()
def pool():
    """Pool lifecycle (TPU pod slices / VM groups)."""


@pool.command("add")
@click.option("--no-wait", is_flag=True, default=False)
@click.pass_context
def pool_add(click_ctx, no_wait):
    """Provision the pool from pool.yaml."""
    fleet.action_pool_add(_ctx(click_ctx), wait=not no_wait)


@pool.command("list")
@click.pass_context
def pool_list(click_ctx):
    fleet.action_pool_list(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@pool.command("del")
@click.option("--pool-id", default=None)
@click.option("-y", "--yes", is_flag=True, default=False)
@click.pass_context
def pool_del(click_ctx, pool_id, yes):
    ctx = _ctx(click_ctx)
    target = pool_id or ctx.pool.id
    if not yes and not click.confirm(
            f"Delete pool {target} and all its jobs/tasks?"):
        raise click.Abort()
    fleet.action_pool_del(ctx, pool_id)


@pool.command("resize")
@click.argument("num_slices", type=int)
@click.pass_context
def pool_resize(click_ctx, num_slices):
    fleet.action_pool_resize(_ctx(click_ctx), num_slices)


@pool.command("stats")
@click.pass_context
def pool_stats(click_ctx):
    fleet.action_pool_stats(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@pool.group()
def nodes():
    """Node operations."""


@nodes.command("list")
@click.pass_context
def pool_nodes_list(click_ctx):
    fleet.action_pool_nodes_list(_ctx(click_ctx),
                                 raw=click_ctx.obj["raw"])


@pool.command("ssh")
@click.argument("node_id")
@click.pass_context
def pool_ssh(click_ctx, node_id):
    fleet.action_pool_ssh(_ctx(click_ctx), node_id)


@pool.group()
def images():
    """Container image management on pool nodes."""


@images.command("update")
@click.argument("image")
@click.option("--kind", default="docker",
              type=click.Choice(["docker", "singularity"]))
@click.pass_context
def pool_images_update(click_ctx, image, kind):
    fleet.action_pool_images_update(_ctx(click_ctx), image, kind)


@pool.group()
def autoscale():
    """Pool autoscale management."""


@autoscale.command("enable")
@click.pass_context
def pool_autoscale_enable(click_ctx):
    from batch_shipyard_tpu.pool import autoscale as as_mod
    as_mod.enable_autoscale(_ctx(click_ctx).store, _ctx(click_ctx).pool)


@autoscale.command("disable")
@click.pass_context
def pool_autoscale_disable(click_ctx):
    from batch_shipyard_tpu.pool import autoscale as as_mod
    as_mod.disable_autoscale(_ctx(click_ctx).store, _ctx(click_ctx).pool)


@autoscale.command("evaluate")
@click.pass_context
def pool_autoscale_evaluate(click_ctx):
    from batch_shipyard_tpu.pool import autoscale as as_mod
    ctx = _ctx(click_ctx)
    decision = as_mod.evaluate(ctx.store, ctx.pool)
    fleet._emit(decision, click_ctx.obj["raw"])


@autoscale.command("tick")
@click.option("--daemon", is_flag=True, default=False,
              help="Loop at autoscale.evaluation_interval_seconds")
@click.option("--interval", type=float, default=None,
              help="Override evaluation interval seconds")
@click.pass_context
def pool_autoscale_tick(click_ctx, daemon, interval):
    """Evaluate AND apply the autoscale decision (the hosted
    evaluator's job in the reference)."""
    from batch_shipyard_tpu.pool import autoscale as as_mod
    ctx = _ctx(click_ctx)
    if daemon:
        as_mod.run_daemon(ctx.store, ctx.substrate(), ctx.pool,
                          interval=interval)
    else:
        decision = as_mod.autoscale_tick(ctx.store, ctx.substrate(),
                                         ctx.pool)
        fleet._emit(decision, click_ctx.obj["raw"])


# ------------------------------- jobs ----------------------------------

@cli.group()
def jobs():
    """Job and task submission."""


@jobs.command("add")
@click.option("--tail", default=None,
              help="Stream this file of the last task after submit")
@click.pass_context
def jobs_add(click_ctx, tail):
    fleet.action_jobs_add(_ctx(click_ctx), tail=tail)


@jobs.command("list")
@click.pass_context
def jobs_list(click_ctx):
    fleet.action_jobs_list(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@jobs.command("term")
@click.option("--job-id", default=None)
@click.pass_context
def jobs_term(click_ctx, job_id):
    fleet.action_jobs_term(_ctx(click_ctx), job_id)


@jobs.command("del")
@click.option("--job-id", default=None)
@click.pass_context
def jobs_del(click_ctx, job_id):
    fleet.action_jobs_del(_ctx(click_ctx), job_id)


@jobs.command("stats")
@click.option("--job-id", default=None)
@click.pass_context
def jobs_stats(click_ctx, job_id):
    fleet.action_jobs_stats(_ctx(click_ctx), job_id,
                            raw=click_ctx.obj["raw"])


@jobs.command("disable")
@click.option("--job-id", required=True)
@click.pass_context
def jobs_disable(click_ctx, job_id):
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    jobs_mgr.disable_job(ctx.store, ctx.pool.id, job_id)


@jobs.command("enable")
@click.option("--job-id", required=True)
@click.pass_context
def jobs_enable(click_ctx, job_id):
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    jobs_mgr.enable_job(ctx.store, ctx.pool.id, job_id)


@jobs.command("migrate")
@click.option("--job-id", required=True)
@click.option("--dst-pool-id", required=True)
@click.pass_context
def jobs_migrate(click_ctx, job_id, dst_pool_id):
    """Move a job's pending tasks to another pool."""
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    moved = jobs_mgr.migrate_job(ctx.store, ctx.pool.id, job_id,
                                 dst_pool_id)
    click.echo(f"migrated {moved} tasks of {job_id} to {dst_pool_id}")


@jobs.command("cmi")
@click.pass_context
def jobs_cmi(click_ctx):
    """Clean up orphaned multi-instance containers on all nodes."""
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    count = jobs_mgr.cleanup_mi_containers(ctx.store, ctx.pool.id)
    click.echo(f"cleanup fanned out to {count} nodes")


@jobs.command("schedule")
@click.option("--once", is_flag=True, default=False,
              help="Evaluate due schedules once and exit")
@click.option("--poll-interval", type=float, default=5.0)
@click.pass_context
def jobs_schedule(click_ctx, once, poll_interval):
    """Run the recurrence scheduler for jobs with a recurrence block."""
    from batch_shipyard_tpu.jobs import schedules
    ctx = _ctx(click_ctx)
    if once:
        launched = schedules.run_due_schedules(ctx.store, ctx.pool,
                                               ctx.jobs)
        click.echo(f"launched: {launched}")
    else:
        schedules.run_schedule_daemon(ctx.store, ctx.pool, ctx.jobs,
                                      poll_interval=poll_interval)


@jobs.group()
def tasks():
    """Task operations."""


@tasks.command("list")
@click.argument("job_id")
@click.pass_context
def jobs_tasks_list(click_ctx, job_id):
    fleet.action_jobs_tasks_list(_ctx(click_ctx), job_id,
                                 raw=click_ctx.obj["raw"])


# ------------------------------- data ----------------------------------

@cli.group()
def data():
    """Data movement and task file access."""


@data.command("stream")
@click.argument("job_id")
@click.argument("task_id")
@click.option("--filename", default="stdout.txt")
@click.pass_context
def data_stream(click_ctx, job_id, task_id, filename):
    fleet.action_data_stream(_ctx(click_ctx), job_id, task_id, filename)


@data.command("ingress")
@click.pass_context
def data_ingress(click_ctx):
    from batch_shipyard_tpu.data import movement
    ctx = _ctx(click_ctx)
    movement.ingress_data(ctx.store, ctx.global_settings,
                          pool_id=ctx.pool.id if "pool" in
                          ctx.configs else None)


# ------------------------------- diag ----------------------------------

@cli.group()
def diag():
    """Diagnostics."""


@diag.command("perf")
@click.pass_context
def diag_perf(click_ctx):
    fleet.action_perf_events(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@diag.command("gantt")
@click.option("--output", default=None,
              help="PNG output path (requires matplotlib)")
@click.pass_context
def diag_gantt(click_ctx, output):
    """Render the pool's perf-event timeline."""
    from batch_shipyard_tpu.graph import perf_graph
    ctx = _ctx(click_ctx)
    click.echo(perf_graph.graph_data(ctx.store, ctx.pool.id, output))


# ------------------------------ storage --------------------------------

@cli.group()
def storage():
    """State store management."""


@storage.command("clear")
@click.option("-y", "--yes", is_flag=True, default=False)
@click.pass_context
def storage_clear(click_ctx, yes):
    """Clear ALL framework state (containers/tables/queues analog)."""
    ctx = _ctx(click_ctx)
    if not yes and not click.confirm(
            "Clear ALL state in the configured store?"):
        raise click.Abort()
    ctx.store.clear()


def main():
    return cli(prog_name="shipyard-tpu")


if __name__ == "__main__":
    sys.exit(main())

"""Simulator-determinism rules.

The fleet simulator's contract is byte-identical reports for the same
(seed, trace, policy) — tests/test_fleet_sim.py asserts it, and the
goodput-delta methodology (docs/35-fleet-simulator.md) depends on it:
a policy comparison is only evidence when the ONLY difference between
two runs is the policy. One stray wall-clock read anywhere in
``batch_shipyard_tpu/sim/`` breaks that silently — reports still look
plausible, they just stop replaying.
"""

from __future__ import annotations

import ast
from typing import Optional

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, rule)

SIM_PREFIX = "batch_shipyard_tpu/sim/"
# The one module allowed to even think about time sources: virtual
# time lives here (it starts at 0.0 and advances only by popping the
# event heap, so in practice it needs no wall clock either).
CLOCK_MODULE = SIM_PREFIX + "clock.py"

_BANNED_TIME_ATTRS = {"time", "monotonic", "perf_counter",
                      "monotonic_ns", "perf_counter_ns", "time_ns"}
_BANNED_DATETIME_ATTRS = {"now", "utcnow", "today"}


def _wall_clock_call(node: ast.Call) -> Optional[str]:
    """'time.monotonic' / 'datetime.now' / 'datetime.datetime.now'
    when the call reads a wall clock; None otherwise."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    if isinstance(base, ast.Name):
        if base.id == "time" and func.attr in _BANNED_TIME_ATTRS:
            return f"time.{func.attr}"
        if base.id == "datetime" and \
                func.attr in _BANNED_DATETIME_ATTRS:
            return f"datetime.{func.attr}"
    # datetime.datetime.now() / datetime.date.today()
    if isinstance(base, ast.Attribute) and \
            isinstance(base.value, ast.Name) and \
            base.value.id == "datetime" and \
            func.attr in _BANNED_DATETIME_ATTRS:
        return f"datetime.{base.attr}.{func.attr}"
    return None


@rule("sim-wall-clock", family="sim")
def check_sim_wall_clock(ctx: AnalysisContext) -> list[Finding]:
    """A wall-clock read (``time.time``/``time.monotonic``/
    ``time.perf_counter``/``datetime.now`` and friends) anywhere in
    ``batch_shipyard_tpu/sim/`` outside the clock module: the
    simulator's virtual clock (sim/clock.py) is the package's ONLY
    time source, and a single wall-clock read makes two runs of the
    same (seed, trace, policy) produce different reports — the
    byte-identical determinism contract the policy-delta methodology
    rests on.

    Provenance: the live agent's heartbeat/goodput plumbing is built
    on ``time.time()`` everywhere, so any code lifted from it into a
    sim adapter carries a wall-clock read by default — this rule is
    what makes that an error instead of a latent flake."""
    findings = []
    for src in ctx.python_files:
        if not src.rel.startswith(SIM_PREFIX):
            continue
        if src.rel == CLOCK_MODULE:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            banned = _wall_clock_call(node)
            if banned:
                findings.append(Finding(
                    rule="sim-wall-clock", path=src.rel,
                    line=node.lineno,
                    message=(f"{banned}() in the simulator package; "
                             f"sim code must take time from the "
                             f"virtual clock (sim/clock.py) — a "
                             f"wall-clock read breaks byte-identical "
                             f"replay of (seed, trace, policy)")))
    return findings

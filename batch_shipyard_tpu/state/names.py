"""Canonical state-store naming: tables, queues, object key prefixes.

Reference analog: the _STORAGE_CONTAINERS registry (convoy/storage.py:68)
that names every blob container/table/queue. Centralized so clients,
daemons, and node agents agree on the schema.
"""

from __future__ import annotations

import zlib

# Tables (partition key scheme in comments)
TABLE_POOLS = "pools"          # pk="pools",           rk=pool_id
TABLE_NODES = "nodes"          # pk=pool_id,           rk=node_id
TABLE_JOBS = "jobs"            # pk=pool_id,           rk=job_id
TABLE_TASKS = "tasks"          # pk=f"{pool}${job}",   rk=task_id
TABLE_GANGS = "gangs"          # pk=f"{pool}${job}${task}", rk=f"i{k}"
TABLE_JOBPREP = "jobprep"      # pk=f"{pool}${job}",   rk=node_id
TABLE_PERF = "perf"            # pk=f"{pool}",         rk=f"{ts}${uniq}"
TABLE_GOODPUT = "goodput"      # pk=pool_id,           rk=f"{ts}${uniq}"
TABLE_TRACE = "trace"          # pk=pool_id,           rk=f"{ts}${uniq}"
TABLE_IMAGES = "images"        # pk=pool_id,           rk=image hash
TABLE_JOBSCHEDULES = "jobschedules"  # pk=pool_id (templates:
#                                      f"{pool}#templates"), rk=job_id
TABLE_MONITOR = "monitor"      # pk="monitor",         rk=resource id
TABLE_FEDERATIONS = "federations"  # pk="fed",         rk=federation_id
TABLE_FEDJOBS = "fedjobs"      # pk=federation_id,     rk=job id
TABLE_SLURM = "slurm"          # pk=cluster_id,        rk=host/partition
TABLE_REMOTEFS = "remotefs"    # pk="remotefs",        rk=cluster_id
TABLE_REMOTEFS_NODES = "remotefs_nodes"  # pk=cluster_id, rk=node name
TABLE_EXPANSIONS = "expansions"  # pk=pool_id,         rk=job_id


# Entity state vocabularies. Every "state" literal written to a task
# or node entity must come from these tuples (enforced by an AST scan
# in tests/test_names_consistency.py) — a typo'd state string would
# otherwise silently dodge every terminal-state check in the fleet.
#
# "quarantined" is the poison-task terminal state: the retry
# supervisor parks a task there after its retry budget is exhausted,
# with a diagnostics bundle on the entity (agent/node_agent.py).
TASK_STATE_QUARANTINED = "quarantined"
# "preempted" is the cooperative-preemption waiting state: the task
# drained to a step boundary, committed a checkpoint, and exited with
# the distinct preempted status (agent/preemption.EXIT_PREEMPTED).
# NON-terminal and claimable like "pending" — the requeue consumed no
# retry budget, and the next claim restores from the forced commit.
TASK_STATE_PREEMPTED = "preempted"
# "evicted" is the FORCIBLE sibling of "preempted": the victim never
# honored its preempt notice within preempt_grace_seconds, so the
# escalation path hard-killed it (no drain, no forced commit). Still
# externally caused — claimable, full retry budget, neutral node
# health — but the rerun resumes from the last COMMITTED checkpoint
# BEFORE the notice, and the wait is priced as the distinct
# "eviction" badput leg.
TASK_STATE_EVICTED = "evicted"
TASK_STATES = ("pending", "assigned", "running", "completed",
               "failed", "blocked", TASK_STATE_QUARANTINED,
               TASK_STATE_PREEMPTED, TASK_STATE_EVICTED)
TERMINAL_TASK_STATES = ("completed", "failed", "blocked",
                        TASK_STATE_QUARANTINED)
# Task states a node may claim for execution: "preempted"/"evicted"
# are requeued-waiting states, not failures — the claim path treats
# them exactly like "pending".
CLAIMABLE_TASK_STATES = ("pending", TASK_STATE_PREEMPTED,
                         TASK_STATE_EVICTED)
NODE_STATES = ("creating", "starting", "idle", "running", "offline",
               "unusable", "start_task_failed", "suspended",
               "preempted")
# Auxiliary coordination states (jobprep fan-out rows, gang member
# rows, job lifecycle, remotefs/slurm cluster lifecycle) — same
# registry, same AST enforcement.
AUX_STATES = ("joined", "done", "active", "disabled", "terminated",
              "completed", "resizing", "ready", "allocation_failed",
              "deleted", "defined", "provisioned", "expanding")

# Server-side task-factory expansion rows (TABLE_EXPANSIONS): one row
# per `jobs add --server-expand` job holding the raw generator spec;
# the leader-gated pool expander (jobs/expansion.py) walks it through
# pending -> expanding -> completed/failed, etag-fencing a resumable
# cursor so a crashed expander's successor re-derives the factory
# deterministically and continues where the chunk commits stopped.
EXPANSION_STATES = ("pending", "expanding", "completed", "failed")
#   cursor — count of tasks already materialized (rows + messages
#            committed); the deterministic factory replays past it
#   stats  — submit-leg breakdown stamped at completion:
#            {expanded, expand_seconds, entity_seconds,
#             enqueue_seconds, encode_seconds}
EXPANSION_COL_CURSOR = "cursor"
EXPANSION_COL_STATS = "stats"

# Node-entity health columns (written by the node agent's health
# scorer, read by claim exclusion + heimdall gauges).
NODE_COL_HEALTH = "health"
NODE_COL_QUARANTINED = "quarantined"

# Task-entity preemption columns (single-sourced: stamped by the
# preempt sweep / chaos node_preempt_notice injector, delivered by the
# agent heartbeat loop, cleared by the preempted requeue):
#   preempt_request — {"requested_at", "reason", "by_job_id",
#                      "by_task_id"} while a preempt is pending
#   preempted_at    — epoch of the last preempted exit (the recovery
#                     interval's start; cleared at next claim)
#   preempt_count   — lifetime preemptions survived (never consumes
#                     the retry budget)
#   gang_size       — elastic gang override: the CURRENT attempt's
#                     effective size when resized below the spec's
#                     num_instances (absent = spec size)
TASK_COL_PREEMPT_REQUEST = "preempt_request"
TASK_COL_PREEMPTED_AT = "preempted_at"
TASK_COL_PREEMPT_COUNT = "preempt_count"
TASK_COL_GANG_SIZE = "gang_size"
# Forcible-eviction columns (the escalation ladder's bookkeeping):
#   evicted_at  — epoch of the last hard-killed (evicted) exit; the
#                 eviction-recovery interval's start, cleared at the
#                 next claim (the preempted_at pattern)
#   evict_count — lifetime forcible evictions survived (never
#                 consumes the retry budget; namespaces the gang
#                 rendezvous attempt like preempt_count)
TASK_COL_EVICTED_AT = "evicted_at"
TASK_COL_EVICT_COUNT = "evict_count"
# Scheduling hints the agent mirrors from the workload's hints file
# (agent/progress.py record_sched_hints) on each heartbeat:
#   {"step", "ckpt_step", "step_seconds", "cache_identity"} — the
# inputs the shared victim-cost policy (sched/policy.py
# victim_cost_from_row) prices preemption rework from. Advisory: a
# task that never writes hints costs 0.0 and tie-breaks on
# (priority, task_id) exactly as before.
TASK_COL_SCHED_HINTS = "sched_hints"


def task_pk(pool_id: str, job_id: str) -> str:
    return f"{pool_id}${job_id}"


def gang_pk(pool_id: str, job_id: str, task_id: str,
            attempt: int = 0) -> str:
    """Gang rendezvous partition. ``attempt`` (the task's retry count)
    namespaces each recovery attempt: a zombie member of a recovered
    gang finishing late merges into the OLD attempt's (deleted)
    partition and gets NotFoundError, instead of corrupting the fresh
    rendezvous that reuses its instance index. Attempt 0 keeps the
    historical name so existing pools are unchanged on disk."""
    base = f"{pool_id}${job_id}${task_id}"
    return base if attempt <= 0 else f"{base}#g{attempt}"


# Queues
#
# Priority bands: job.priority maps onto separate queue families that
# agents drain strictly in band order (hi before normal before lo), so
# a high-priority job overtakes a 10k-task sweep backlog the way Azure
# Batch's job priority does for the reference (jobs.yaml priority,
# -1000..1000). Band "" (normal, priority 0) keeps the historical
# queue names so existing pools are unchanged on disk.
PRIORITY_BANDS = ("hi", "", "lo")


def priority_band(priority: int) -> str:
    if priority > 0:
        return "hi"
    if priority < 0:
        return "lo"
    return ""


def task_queue(pool_id: str, shard: int = 0, band: str = "") -> str:
    """Task queue name for one shard+band. Shard 0 of the normal band
    keeps the unsharded name, so pools with task_queue_shards=1 (the
    default) are unchanged on disk."""
    suffix = f"-{band}" if band else ""
    if shard == 0:
        return f"taskq-{pool_id}{suffix}"
    return f"taskq-{pool_id}{suffix}-{shard}"


def task_queues(pool_id: str, shards: int) -> list[str]:
    """Every task queue of a pool, all bands — the set over which
    backlog lengths (autoscale, federation facts) are summed."""
    return [task_queue(pool_id, k, band)
            for band in PRIORITY_BANDS
            for k in range(max(shards, 1))]


def task_queues_by_band(pool_id: str, shards: int) -> list[list[str]]:
    """Queues grouped by band in strict drain order (hi, normal, lo):
    agents exhaust earlier bands before popping later ones."""
    return [[task_queue(pool_id, k, band)
             for k in range(max(shards, 1))]
            for band in PRIORITY_BANDS]


def task_queue_for(pool_id: str, task_id: str, shards: int,
                   priority: int = 0) -> str:
    """Deterministic shard for a task: every producer (submit,
    migrate, retry requeue) routes a task's messages to the same
    shard (reference analog: the 100-task TaskAddCollection fan-in,
    batch.py:4313 — re-designed as queue fan-OUT so 10^4-task pools
    don't serialize on one queue)."""
    band = priority_band(priority)
    if shards <= 1:
        return task_queue(pool_id, 0, band)
    return task_queue(pool_id, zlib.crc32(task_id.encode()) % shards,
                      band)


def control_queue(pool_id: str, node_id: str) -> str:
    """Per-node control messages (job release, shutdown, reboot)."""
    return f"ctrlq-{pool_id}-{node_id}"


def federation_queue(federation_id: str) -> str:
    return f"fedq-{federation_id}"


def control_reply_key(pool_id: str, node_id: str, nonce: str) -> str:
    """Object key where a node agent parks the reply to a
    request/reply control verb (nodes ps/zap/prune)."""
    return f"ctrlreply/{pool_id}/{node_id}/{nonce}.json"


# Object key prefixes
def resource_file_key(pool_id: str, filename: str) -> str:
    return f"resourcefiles/{pool_id}/{filename}"


def task_output_key(pool_id: str, job_id: str, task_id: str,
                    filename: str) -> str:
    return f"taskdata/{pool_id}/{job_id}/{task_id}/{filename}"


def node_log_key(pool_id: str, node_id: str, filename: str) -> str:
    return f"nodelogs/{pool_id}/{node_id}/{filename}"


def global_resource_lock_key(pool_id: str, resource_hash: str,
                             slot: int) -> str:
    """Cascade concurrency-gate lock names (reference: hash.{0..N} lock
    blobs, storage.py:1946)."""
    return f"grlocks/{pool_id}/{resource_hash}.{slot}"


def federation_job_blob_key(federation_id: str, job_id: str,
                            unique: str) -> str:
    return f"fedjobs/{federation_id}/{job_id}/{unique}"


# Leader leases (state/leases.py): one named lease per leader-gated
# loop — the gang janitor, the preempt sweep, the federation elastic
# evaluator — plus a per-lease epoch object whose generation is the
# monotonic fencing epoch stamped into every sweep write. ``scope``
# is the pool id for agent sweeps, "fed-<federation_id>" for the
# federation evaluator.
def leader_lease_key(scope: str, role: str) -> str:
    return f"leader/{scope}/{role}"


def leader_epoch_key(scope: str, role: str) -> str:
    return f"leader/{scope}/{role}.epoch"


# Node-entity column: the local store-outage WAL backlog
# (state/resilient.py), published on every heartbeat so heimdall can
# export shipyard_journal_backlog_entries per node.
NODE_COL_JOURNAL_BACKLOG = "journal_backlog"


# Pool-wide compile-cache seeding (compilecache/seeding.py): one tar
# artifact per cache identity, a latest.json pointer read before
# download, and a lease so exactly one node uploads per identity.
def compile_cache_key(pool_id: str, identity: str) -> str:
    return f"compilecache/{pool_id}/{identity}.tar"


def compile_cache_latest_key(pool_id: str) -> str:
    return f"compilecache/{pool_id}/latest.json"


def compile_cache_lease_key(pool_id: str, identity: str) -> str:
    return f"compilecache/{pool_id}/{identity}.lock"

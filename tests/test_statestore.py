"""Contract tests over both state store implementations.

Every distributed protocol in the framework (cascade lease gate,
federation queues, gang rendezvous) sits on these semantics, so they
are tested as a contract across backends.
"""

import concurrent.futures
import time

import pytest

from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, LeaseLostError, NotFoundError,
    PreconditionFailedError)
from batch_shipyard_tpu.state.localfs import LocalFSStateStore
from batch_shipyard_tpu.state.memory import MemoryStateStore


@pytest.fixture(params=["memory", "localfs", "gcs"])
def store(request, tmp_path):
    if request.param == "memory":
        yield MemoryStateStore()
    elif request.param == "gcs":
        # The REAL GCSStateStore logic over an in-memory fake of the
        # google.cloud.storage API (generation preconditions etc.).
        from tests.fake_gcs import make_fake_gcs_store
        yield make_fake_gcs_store()
    else:
        yield LocalFSStateStore(str(tmp_path / "store"))


def test_object_roundtrip(store):
    gen = store.put_object("a/b.txt", b"hello")
    assert store.get_object("a/b.txt") == b"hello"
    meta = store.get_object_meta("a/b.txt")
    assert meta.size == 5
    assert meta.generation == gen
    assert store.list_objects("a/") == ["a/b.txt"]
    store.delete_object("a/b.txt")
    assert not store.object_exists("a/b.txt")
    with pytest.raises(NotFoundError):
        store.get_object("a/b.txt")


def test_object_create_only_precondition(store):
    store.put_object("x", b"1", if_generation_match=0)
    with pytest.raises(PreconditionFailedError):
        store.put_object("x", b"2", if_generation_match=0)


def test_object_matched_overwrite(store):
    gen = store.put_object("x", b"1")
    store.put_object("x", b"2", if_generation_match=gen)
    with pytest.raises(PreconditionFailedError):
        store.put_object("x", b"3", if_generation_match=gen)
    assert store.get_object("x") == b"2"


def test_lease_mutual_exclusion(store):
    h1 = store.acquire_lease("lock1", 30.0, "owner-a")
    assert h1 is not None
    assert store.acquire_lease("lock1", 30.0, "owner-b") is None
    store.release_lease(h1)
    h2 = store.acquire_lease("lock1", 30.0, "owner-b")
    assert h2 is not None and h2.owner == "owner-b"


def test_lease_expiry_steal(store):
    h1 = store.acquire_lease("lock2", 0.05, "a")
    assert h1 is not None
    time.sleep(0.1)
    h2 = store.acquire_lease("lock2", 30.0, "b")
    assert h2 is not None
    with pytest.raises(LeaseLostError):
        store.renew_lease(h1, 30.0)


def test_lease_renew(store):
    h = store.acquire_lease("lock3", 0.2, "a")
    h = store.renew_lease(h, 30.0)
    time.sleep(0.25)
    # renewed past original expiry -> still held
    assert store.acquire_lease("lock3", 30.0, "b") is None
    store.release_lease(h)


def test_lease_contention_single_winner(store):
    winners = []

    def contend(idx):
        handle = store.acquire_lease("hot", 30.0, f"w{idx}")
        if handle is not None:
            winners.append(idx)

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        list(pool.map(contend, range(8)))
    assert len(winners) == 1


def test_entity_crud(store):
    etag = store.insert_entity("t", "pk", "rk", {"a": 1})
    with pytest.raises(EntityExistsError):
        store.insert_entity("t", "pk", "rk", {"a": 2})
    ent = store.get_entity("t", "pk", "rk")
    assert ent["a"] == 1 and ent["_etag"] == etag
    etag2 = store.merge_entity("t", "pk", "rk", {"b": 2}, if_match=etag)
    with pytest.raises(EtagMismatchError):
        store.merge_entity("t", "pk", "rk", {"c": 3}, if_match=etag)
    ent = store.get_entity("t", "pk", "rk")
    assert ent["a"] == 1 and ent["b"] == 2 and ent["_etag"] == etag2
    store.delete_entity("t", "pk", "rk", if_match=etag2)
    with pytest.raises(NotFoundError):
        store.get_entity("t", "pk", "rk")


def test_entity_query(store):
    store.insert_entity("t", "p1", "a", {"v": 1})
    store.insert_entity("t", "p1", "ab", {"v": 2})
    store.insert_entity("t", "p2", "a", {"v": 3})
    assert len(list(store.query_entities("t"))) == 3
    assert len(list(store.query_entities("t", partition_key="p1"))) == 2
    rows = list(store.query_entities("t", partition_key="p1",
                                     row_key_prefix="ab"))
    assert len(rows) == 1 and rows[0]["v"] == 2


def test_entity_claim_race(store):
    """Optimistic-concurrency claim: only one thread wins the etag swap
    (the task-assignment primitive for the node agent)."""
    store.insert_entity("tasks", "job", "t1", {"state": "pending"})
    wins = []

    def claim(idx):
        ent = store.get_entity("tasks", "job", "t1")
        if ent["state"] != "pending":
            return
        try:
            store.merge_entity("tasks", "job", "t1",
                               {"state": "assigned", "node": idx},
                               if_match=ent["_etag"])
            wins.append(idx)
        except EtagMismatchError:
            pass

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        list(pool.map(claim, range(8)))
    assert len(wins) == 1
    assert store.get_entity("tasks", "job", "t1")["node"] == wins[0]


def test_queue_visibility_and_redelivery(store):
    store.put_message("q", b"m1")
    msgs = store.get_messages("q", visibility_timeout=0.05)
    assert len(msgs) == 1 and msgs[0].payload == b"m1"
    # invisible while claimed
    assert store.get_messages("q") == []
    time.sleep(0.1)
    # redelivered after visibility timeout, dequeue_count increments
    msgs2 = store.get_messages("q", visibility_timeout=30.0)
    assert len(msgs2) == 1 and msgs2[0].dequeue_count == 2
    store.delete_message(msgs2[0])
    assert store.queue_length("q") == 0
    # stale receipt cannot delete
    with pytest.raises(NotFoundError):
        store.delete_message(msgs[0])


def test_queue_delay_and_update(store):
    store.put_message("q2", b"later", delay_seconds=0.1)
    assert store.get_messages("q2") == []
    time.sleep(0.15)
    msgs = store.get_messages("q2", visibility_timeout=0.05)
    assert len(msgs) == 1
    store.update_message(msgs[0], visibility_timeout=30.0)
    time.sleep(0.1)
    assert store.get_messages("q2") == []  # visibility was extended


def test_queue_multiple_consumers_no_double_claim(store):
    for idx in range(20):
        store.put_message("mq", f"m{idx}".encode())
    claimed = []

    def consume(_):
        for msg in store.get_messages("mq", max_messages=5,
                                      visibility_timeout=30.0):
            claimed.append(msg.payload)

    with concurrent.futures.ThreadPoolExecutor(4) as pool:
        list(pool.map(consume, range(4)))
    assert len(claimed) == len(set(claimed)) == 20


def test_clear(store):
    store.put_object("o", b"x")
    store.insert_entity("t", "p", "r", {})
    store.put_message("q", b"m")
    store.clear()
    assert store.list_objects() == []
    assert list(store.query_entities("t")) == []
    assert store.queue_length("q") == 0


def test_batch_put_messages_and_insert_entities(store):
    ids = store.put_messages("bq", [f"m{i}".encode()
                                    for i in range(25)])
    assert len(ids) == len(set(ids)) == 25
    assert store.queue_length("bq") == 25
    got = {m.payload for m in store.get_messages(
        "bq", max_messages=25, visibility_timeout=30.0)}
    assert got == {f"m{i}".encode() for i in range(25)}
    etags = store.insert_entities("bt", [
        ("p", f"r{i}", {"v": i}) for i in range(10)])
    assert len(etags) == 10
    assert len(list(store.query_entities("bt"))) == 10
    with pytest.raises(EntityExistsError):
        store.insert_entities("bt", [("p", "new", {}),
                                     ("p", "r3", {})])


def test_object_streaming_contract(store):
    """put_object_stream/get_object_stream round-trip a >100MB object
    chunk-by-chunk (VERDICT r1 #6: the blobxfer-streaming analog) —
    the producer never materializes the payload."""
    import hashlib

    chunk = bytes(range(256)) * (32 * 1024)  # 8 MiB
    n_chunks = 14                            # 112 MiB total
    h_in = hashlib.sha256()

    def produce():
        for _ in range(n_chunks):
            h_in.update(chunk)
            yield chunk

    gen = store.put_object_stream("big/obj.bin", produce())
    meta = store.get_object_meta("big/obj.bin")
    assert meta.size == len(chunk) * n_chunks
    assert meta.generation == gen
    h_out = hashlib.sha256()
    sizes = []
    for piece in store.get_object_stream("big/obj.bin"):
        h_out.update(piece)
        sizes.append(len(piece))
    assert h_out.hexdigest() == h_in.hexdigest()
    # Streamed read really is chunked, not one whole-buffer yield.
    assert len(sizes) > 1
    store.delete_object("big/obj.bin")


def test_object_stream_precondition_and_missing(store):
    store.put_object("s1", b"v1")
    with pytest.raises(PreconditionFailedError):
        store.put_object_stream("s1", iter([b"v2"]),
                                if_generation_match=0)
    with pytest.raises(NotFoundError):
        list(store.get_object_stream("nope"))

"""Load generator for the serving front end.

Measures what continuous-batching engines are judged by: TTFT and
TPOT percentiles under concurrent load, plus aggregate tokens/sec —
the serving benchmark the reference's recipes-as-acceptance strategy
(SURVEY.md section 4) implies but never had an ML engine to apply to.
stdlib-only: urllib for transport, threads for in-flight requests,
random.Random(seed) for reproducible arrivals.

Two arrival processes: steady Poisson (``arrival="poisson"``) and a
diurnal replay (``arrival="diurnal"``) that reuses the fleet
simulator's sinusoidal thinning construction
(sim/traces.diurnal_arrivals) — the same day/night curve, scaled to
real seconds, deterministic per seed. Workloads can share prompt
prefixes across request groups (``shared_prefix_groups``) to exercise
the engine's cross-request prefix cache and the router's
prefix-affinity routing, and tag requests with SLO classes to report
per-class attainment alongside the percentile tables.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence, Union

from batch_shipyard_tpu.sim import traces as sim_traces

from batch_shipyard_tpu.trace.histogram import LatencyHistogram
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def _exact_percentile(values: list, q: float) -> float:
    """Nearest-rank percentile over the raw values (no binning)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    k = max(0, math.ceil(q / 100.0 * len(ordered)) - 1)
    return ordered[k]


def _post_generate(base_url: str, payload: dict,
                   timeout: float) -> dict:
    req = urllib.request.Request(
        f"{base_url}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def run_load(base_url: Union[str, Sequence[str]],
             num_requests: int,
             rate_hz: float = 8.0,
             prompt_len: tuple[int, int] = (4, 32),
             max_new_tokens: tuple[int, int] = (8, 32),
             vocab_size: int = 97,
             seed: int = 0,
             eos_id: Optional[int] = None,
             request_timeout: float = 300.0,
             arrival: str = "poisson",
             day_seconds: float = 60.0,
             trough_rate_hz: Optional[float] = None,
             shared_prefix_groups: int = 0,
             shared_prefix_len: int = 0,
             slo_classes: Optional[dict] = None) -> dict:
    """Fire ``num_requests`` and return the latency report:
    TTFT/TPOT/latency p50/p90/p99 computed from MERGED per-replica
    fixed-log-bucket histograms (trace/histogram.py — the same
    aggregation rule the router and heimdall use, so bench numbers
    and fleet dashboards agree), tokens/sec, and the raw mergeable
    histograms.

    ``arrival="poisson"`` spaces requests at ``rate_hz``;
    ``arrival="diurnal"`` replays the fleet simulator's sinusoidal
    curve (peak ``rate_hz``, trough ``trough_rate_hz`` or rate_hz/4,
    one virtual day = ``day_seconds``). With ``shared_prefix_groups``
    > 0, each request prepends one of that many fixed
    ``shared_prefix_len``-token prefixes (chosen per-request by the
    seeded rng) and carries a matching ``prefix_key`` — the shape the
    prefix cache and affinity routing exist for. ``slo_classes`` maps
    class name -> {"ttft_ms", "tpot_ms"} targets (None = untargeted);
    requests then cycle through the classes and the report adds
    per-class attainment. 503-shed requests are counted separately
    from transport failures.

    ``base_url`` may be a single URL or a list of replica URLs (a
    serving fleet — one server task per pool node); requests then
    round-robin across replicas and the report adds a per-replica
    completion breakdown."""
    urls = ([base_url] if isinstance(base_url, str)
            else list(base_url))
    rng = random.Random(seed)
    prefixes = [[rng.randrange(vocab_size)
                 for _ in range(shared_prefix_len)]
                for _ in range(shared_prefix_groups)]
    class_names = sorted(slo_classes) if slo_classes else []
    if arrival == "diurnal":
        trough = (trough_rate_hz if trough_rate_hz is not None
                  else rate_hz / 4.0)
        times = sim_traces.diurnal_arrivals(
            seed, num_requests, day_seconds, rate_hz, trough)
        gaps = [times[k + 1] - times[k]
                for k in range(num_requests - 1)]
    elif arrival == "poisson":
        gaps = [rng.expovariate(rate_hz)
                for _ in range(num_requests - 1)]
    else:
        raise ValueError(f"unknown arrival process: {arrival!r}")
    results: list[Optional[dict]] = [None] * num_requests
    errors: list[Optional[str]] = [None] * num_requests
    sheds: list[Optional[str]] = [None] * num_requests
    threads = []

    def _one(k: int, url: str, payload: dict) -> None:
        try:
            result = _post_generate(url, payload, request_timeout)
            result["_replica"] = url
            results[k] = result
        except urllib.error.HTTPError as exc:
            try:
                body = json.loads(exc.read())
            except Exception:
                body = {}
            if exc.code == 503 and body.get("shed"):
                sheds[k] = payload.get("slo_class", "standard")
            else:
                errors[k] = f"HTTP {exc.code}: " \
                            f"{body.get('error', '')}"
        except (urllib.error.URLError, OSError, TimeoutError) as exc:
            errors[k] = str(exc)

    started = time.perf_counter()
    for k in range(num_requests):
        plen = rng.randint(*prompt_len)
        prompt = [rng.randrange(vocab_size) for _ in range(plen)]
        payload = {
            "request_id": f"load-{seed}-{k}",
            "max_new_tokens": rng.randint(*max_new_tokens),
        }
        if prefixes:
            g = rng.randrange(len(prefixes))
            prompt = prefixes[g] + prompt
            payload["prefix_key"] = f"load-{seed}-g{g}"
        payload["prompt"] = prompt
        if class_names:
            payload["slo_class"] = class_names[k % len(class_names)]
        if eos_id is not None:
            payload["eos_id"] = eos_id
        thread = threading.Thread(
            target=_one, args=(k, urls[k % len(urls)], payload),
            daemon=True)
        thread.start()
        threads.append(thread)
        if k < num_requests - 1:
            time.sleep(gaps[k])
    for thread in threads:
        thread.join(request_timeout)
    elapsed = time.perf_counter() - started
    done = [r for r in results if r is not None]
    failed = [e for e in errors if e is not None]
    shed = [s for s in sheds if s is not None]
    tokens = sum(r["num_tokens"] for r in done)
    # One histogram per (metric, replica), merged for the report:
    # this is the exact aggregation a fleet of independent replicas
    # supports (percentiles of pooled bucket counts), as opposed to
    # averaging per-replica percentiles or reporting means.
    per_replica: dict[str, dict[str, LatencyHistogram]] = {
        metric: {url: LatencyHistogram() for url in urls}
        for metric in ("ttft_ms", "tpot_ms", "latency_ms")}
    for r in done:
        for metric in ("ttft_ms", "tpot_ms", "latency_ms"):
            per_replica[metric][r["_replica"]].observe(r[metric])
    merged = {metric: LatencyHistogram.merged(hists.values())
              for metric, hists in per_replica.items()}
    report = {
        "num_requests": num_requests,
        "completed": len(done),
        "failed": len(failed),
        "shed": len(shed),
        "arrival": arrival,
        "offered_rate_hz": rate_hz,
        "elapsed_seconds": elapsed,
        "requests_per_second": len(done) / elapsed if elapsed else 0.0,
        "tokens_per_second": tokens / elapsed if elapsed else 0.0,
        "generated_tokens": tokens,
        "ttft_ms": merged["ttft_ms"].percentiles((50, 90, 99)),
        "tpot_ms": merged["tpot_ms"].percentiles((50, 90, 99)),
        # Exact mean/percentiles from the raw observations (the
        # log-bucket histograms quantize to bucket edges; A/B deltas
        # like BENCH_serving_slo need unbinned values so a real
        # improvement can't vanish into a shared bucket).
        "ttft_mean_ms": (sum(r["ttft_ms"] for r in done) / len(done)
                         if done else 0.0),
        "tpot_mean_ms": (sum(r["tpot_ms"] for r in done) / len(done)
                         if done else 0.0),
        "ttft_exact_ms": {
            f"p{q}": _exact_percentile(
                [r["ttft_ms"] for r in done], q)
            for q in (50, 99)},
        "tpot_exact_ms": {
            f"p{q}": _exact_percentile(
                [r["tpot_ms"] for r in done], q)
            for q in (50, 99)},
        "latency_ms": merged["latency_ms"].percentiles((50, 90, 99)),
        "ttft_hist": merged["ttft_ms"].to_dict(),
        "tpot_hist": merged["tpot_ms"].to_dict(),
    }
    if slo_classes:
        # Per-class SLO attainment: of the completed requests in each
        # class, the fraction whose TTFT/TPOT landed inside the
        # class's target (a None target always attains). Sheds are
        # charged to the class that lost them.
        per_class: dict[str, dict] = {
            name: {"requests": 0, "completed": 0, "shed": 0,
                   "ttft_ok": 0, "tpot_ok": 0}
            for name in class_names}
        for s in shed:
            if s in per_class:
                per_class[s]["requests"] += 1
                per_class[s]["shed"] += 1
        for r in done:
            name = r.get("slo_class", "standard")
            row = per_class.setdefault(
                name, {"requests": 0, "completed": 0, "shed": 0,
                       "ttft_ok": 0, "tpot_ok": 0})
            row["requests"] += 1
            row["completed"] += 1
            targets = slo_classes.get(name) or {}
            for metric, key in (("ttft_ms", "ttft_ok"),
                                ("tpot_ms", "tpot_ok")):
                target = targets.get(metric)
                if target is None or r[metric] <= target:
                    row[key] += 1
        for name, row in per_class.items():
            n = row["completed"]
            targets = slo_classes.get(name) or {}
            row["ttft_target_ms"] = targets.get("ttft_ms")
            row["tpot_target_ms"] = targets.get("tpot_ms")
            row["ttft_attainment"] = row["ttft_ok"] / n if n else None
            row["tpot_attainment"] = row["tpot_ok"] / n if n else None
        report["slo_attainment"] = per_class
    if prefixes:
        report["shared_prefix_groups"] = shared_prefix_groups
        report["shared_prefix_len"] = shared_prefix_len
    # Digest of every completed request's exact token ids: two runs
    # at the same seed against byte-identical engines must agree —
    # the bench's prefix-cache-on-vs-off equivalence check.
    digest = hashlib.sha256()
    for r in sorted(done, key=lambda r: r["request_id"]):
        digest.update(f"{r['request_id']}:{r['tokens']};".encode())
    report["outputs_sha256"] = digest.hexdigest()
    if len(urls) > 1:
        by_replica: dict[str, int] = {}
        for r in done:
            by_replica[r["_replica"]] = by_replica.get(
                r["_replica"], 0) + 1
        report["replicas"] = len(urls)
        report["completed_by_replica"] = by_replica
    if failed:
        report["errors"] = failed[:8]
    return report


def main() -> int:
    """Standalone benchmark CLI against running server(s):

        python -m batch_shipyard_tpu.models.loadgen \\
            http://node0:8900 http://node1:8900 \\
            --num 128 --rate 32 --report fleet_report.json
    """
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("urls", nargs="+",
                        help="Serving front end base URL(s)")
    parser.add_argument("--num", type=int, default=64)
    parser.add_argument("--rate", type=float, default=8.0)
    parser.add_argument("--prompt-len", type=int, nargs=2,
                        default=(4, 32), metavar=("MIN", "MAX"))
    parser.add_argument("--gen-tokens", type=int, nargs=2,
                        default=(8, 32), metavar=("MIN", "MAX"))
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--arrival", choices=("poisson", "diurnal"),
                        default="poisson",
                        help="Arrival process; diurnal replays the "
                             "fleet simulator's day/night curve")
    parser.add_argument("--day-seconds", type=float, default=60.0,
                        help="Virtual-day length for --arrival "
                             "diurnal")
    parser.add_argument("--trough-rate", type=float, default=None,
                        help="Diurnal trough rate (default rate/4)")
    parser.add_argument("--shared-prefix-groups", type=int, default=0,
                        help="Number of shared prompt-prefix groups "
                             "(0 = fully random prompts)")
    parser.add_argument("--shared-prefix-len", type=int, default=0,
                        help="Tokens in each shared prefix")
    parser.add_argument("--slo", default=None,
                        help="JSON: class name -> "
                             '{"ttft_ms": .., "tpot_ms": ..}')
    parser.add_argument("--report", default=None,
                        help="Also write the JSON report here")
    args = parser.parse_args()
    report = run_load(
        args.urls, args.num, rate_hz=args.rate,
        prompt_len=tuple(args.prompt_len),
        max_new_tokens=tuple(args.gen_tokens),
        vocab_size=args.vocab, seed=args.seed,
        arrival=args.arrival, day_seconds=args.day_seconds,
        trough_rate_hz=args.trough_rate,
        shared_prefix_groups=args.shared_prefix_groups,
        shared_prefix_len=args.shared_prefix_len,
        slo_classes=json.loads(args.slo) if args.slo else None)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
    print(json.dumps(report))
    return 1 if report["failed"] else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

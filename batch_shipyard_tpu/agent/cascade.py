"""Cascade: pool-wide container-image replication with lease gating.

Reference analog: cascade/cascade.py — the on-node image replicator
whose pool-wide concurrency gate is blob leases over per-resource lock
blobs ``hash.{0..N}`` (_direct_download_resources_async cascade.py:574,
60 s lease + renew at :628). Re-designed:

  - global resources (images) live in TABLE_IMAGES per pool, written by
    ``pool add`` (storage.populate_global_resource_blobs analog,
    storage.py:476);
  - an agent wanting image X acquires one of
    ``grlocks/<pool>/<hash>.{0..K-1}`` leases (K =
    concurrent_source_downloads) before pulling, renewing on a
    background thread while the pull runs — bounding simultaneous
    registry load across the whole pool exactly like the reference;
  - pull happens via docker/singularity CLI with registry fallback;
    perf events record pull start/end per image.

On nodes without docker (tests, bare TPU VMs running runtime:none
tasks) pulls are skipped but the gate/accounting logic still runs, so
the protocol is fully unit-testable (SURVEY.md section 4).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import threading
import time
from typing import Optional

from batch_shipyard_tpu.agent import perf
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.utils import secrets
from batch_shipyard_tpu.state.base import (
    EntityExistsError, NotFoundError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

LEASE_SECONDS = 60.0
RENEW_INTERVAL = 15.0


def populate_global_resources(store: StateStore, pool_id: str,
                              docker_images: list[str],
                              singularity_images: list[str] = (),
                              concurrent_downloads: int = 10,
                              registries: list = ()) -> None:
    """Write the pool's image manifest (pool add path). ``registries``
    (config.settings.DockerRegistry) ride the same manifest as
    kind="registry" rows so every node logs in before its first pull
    (reference scripts/registry_login.sh via the nodeprep flag
    contract). Passwords are stored as their secret:// refs, resolved
    on node — never plaintext in the state store."""
    def _upsert_preserving_blob(key: str, row: dict) -> None:
        # A preloaded tarball (preload_image_tarball) may already have
        # attached a source_blob to this image; re-populating the
        # manifest must not sever it.
        try:
            old = store.get_entity(names.TABLE_IMAGES, pool_id, key)
            if old.get("source_blob"):
                row = {**row, "source_blob": old["source_blob"]}
        except NotFoundError:
            pass
        store.upsert_entity(names.TABLE_IMAGES, pool_id, key, row)

    for image in docker_images:
        key = util.hash_string(f"docker:{image}")[:24]
        _upsert_preserving_blob(key, {
            "kind": "docker", "image": image,
            "concurrent_downloads": concurrent_downloads})
    for image in singularity_images:
        key = util.hash_string(f"singularity:{image}")[:24]
        _upsert_preserving_blob(key, {
            "kind": "singularity", "image": image,
            "concurrent_downloads": concurrent_downloads})
    for reg in registries or ():
        if reg.password and not secrets.is_secret_id(reg.password):
            # The documented contract is that plaintext never lands in
            # the state store; a raw password here would persist in
            # the images table readable by every node.
            logger.warning(
                "docker registry %s password is NOT a secret:// ref; "
                "it will be stored in the shared state store in "
                "PLAINTEXT — use secret://env/... or "
                "secret://gcp-sm/... instead", reg.server)
        key = util.hash_string(f"registry:{reg.server}")[:24]
        store.upsert_entity(names.TABLE_IMAGES, pool_id, key, {
            "kind": "registry", "server": reg.server,
            "username": reg.username, "password": reg.password,
            "auth": reg.auth})


def preload_image_tarball(store: StateStore, pool_id: str, image: str,
                          chunks, kind: str = "docker") -> str:
    """Upload an image tarball (e.g. `docker save` output chunks) to
    the object store and bind it to the pool's image manifest row —
    the reference cascade's DIRECT DOWNLOAD mode
    (cascade/cascade.py:574 _direct_download_resources_async: images
    ride Azure Storage instead of a registry). Nodes then stream the
    tarball from the state store (lease-gated like registry pulls) and
    `docker load` it, which also serves air-gapped pools with no
    registry egress. Returns the object key."""
    key = util.hash_string(f"{kind}:{image}")[:24]
    blob_key = f"cascade/{pool_id}/{key}.tar"
    store.put_object_stream(blob_key, chunks)
    try:
        store.merge_entity(names.TABLE_IMAGES, pool_id, key,
                           {"source_blob": blob_key})
    except NotFoundError:
        store.upsert_entity(names.TABLE_IMAGES, pool_id, key, {
            "kind": kind, "image": image,
            "concurrent_downloads": 10, "source_blob": blob_key})
    return blob_key


def registry_manifest(store: StateStore, pool_id: str) -> list[dict]:
    """The pool's registry-credential rows."""
    return [row for row in store.query_entities(
        names.TABLE_IMAGES, partition_key=pool_id)
        if row.get("kind") == "registry"]


def global_resources_loaded(store: StateStore, pool_id: str,
                            node_id: str) -> bool:
    """Has this node recorded completion of all its image pulls?"""
    wanted = {row["_rk"] for row in store.query_entities(
        names.TABLE_IMAGES, partition_key=pool_id)
        if row.get("kind") != "registry"}
    if not wanted:
        return True
    try:
        row = store.get_entity(names.TABLE_IMAGES + "done", pool_id,
                               node_id)
    except NotFoundError:
        return False
    return wanted <= set(row.get("loaded", []))


class CascadeImageProvisioner:
    """Per-node image puller with the pool-wide lease gate."""

    def __init__(self, store: StateStore, fallback_registry:
                 Optional[str] = None, pull_timeout: float = 1800.0,
                 puller: Optional[object] = None,
                 login_runner: Optional[object] = None,
                 secrets_file: Optional[str] = None) -> None:
        self.store = store
        self.fallback_registry = fallback_registry
        self.pull_timeout = pull_timeout
        self._puller = puller  # test hook: callable(kind, image) -> int
        # test hook: callable(argv: list[str], stdin: str|None) -> int
        self._login_runner = login_runner
        self._secrets_file = secrets_file or os.environ.get(
            "SHIPYARD_SECRETS_FILE")
        self._loaded: set[str] = set()
        self._logged_in: set[str] = set()
        self._lock = threading.Lock()

    # -- registry auth --------------------------------------------------

    def login_registries(self, pool_id: str) -> None:
        """Authenticate to every registry in the pool manifest before
        pulls (reference scripts/registry_login.sh:1-99 — docker login
        per configured registry; Artifact Registry rows instead run
        ``gcloud auth configure-docker``). secret:// passwords resolve
        HERE, on node, via utils/secrets. Idempotent per server."""
        from batch_shipyard_tpu.utils import secrets as secrets_mod
        for row in registry_manifest(self.store, pool_id):
            server = row.get("server") or ""
            with self._lock:
                if server in self._logged_in:
                    continue
            if row.get("auth") == "gcloud":
                argv = ["gcloud", "auth", "configure-docker", server,
                        "--quiet"]
                rc = self._run_login(argv, None)
            else:
                password = row.get("password") or ""
                if secrets_mod.is_secret_id(password):
                    password = secrets_mod.resolve_secret(
                        password, secrets_file=self._secrets_file)
                argv = ["docker", "login", server,
                        "--username", row.get("username") or "",
                        "--password-stdin"]
                rc = self._run_login(argv, password)
            if rc != 0:
                raise RuntimeError(
                    f"registry login to {server!r} failed rc={rc}")
            with self._lock:
                self._logged_in.add(server)

    def _run_login(self, argv: list, stdin_data) -> int:
        if self._login_runner is not None:
            return self._login_runner(argv, stdin_data)
        if shutil.which(argv[0]) is None:
            logger.info("%s unavailable; skipping registry login",
                        argv[0])
            return 0
        proc = subprocess.run(
            argv, input=(stdin_data.encode() if stdin_data else None),
            timeout=120, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE)
        if proc.returncode != 0:
            logger.error("registry login failed: %s",
                         proc.stderr.decode(errors="replace").strip())
        return proc.returncode

    # -- entry points ---------------------------------------------------

    def distribute_global_resources(self, agent) -> None:
        """Pull every image in the pool manifest (nodeprep path;
        reference cascade.py:724 distribute_global_resources)."""
        pool_id = agent.identity.pool_id
        self.login_registries(pool_id)
        rows = [row for row in self.store.query_entities(
            names.TABLE_IMAGES, partition_key=pool_id)
            if row.get("kind") != "registry"]
        for row in rows:
            self._fetch(agent, row["_rk"], row["kind"], row["image"],
                        int(row.get("concurrent_downloads", 10)),
                        source_blob=row.get("source_blob"))
        perf.emit(self.store, pool_id, agent.identity.node_id, "cascade",
                  "global_resources_loaded")

    def __call__(self, agent, images: list[str],
                 kind: str = "docker") -> None:
        """Agent hook: ensure specific images before running a task.
        The key must match populate_global_resources' kind-qualified
        hash so the pool-wide lease gate is actually shared."""
        pool_id = agent.identity.pool_id
        self.login_registries(pool_id)
        for image in images:
            key = util.hash_string(f"{kind}:{image}")[:24]
            try:
                row = self.store.get_entity(
                    names.TABLE_IMAGES, pool_id, key)
            except NotFoundError:
                row = {"kind": kind, "image": image,
                       "concurrent_downloads": 10}
            self._fetch(agent, key, row["kind"], row.get("image", image),
                        int(row.get("concurrent_downloads", 10)),
                        source_blob=row.get("source_blob"))

    # -- internals ------------------------------------------------------

    def _fetch(self, agent, resource_hash: str, kind: str, image: str,
               concurrent: int, source_blob: Optional[str] = None,
               ) -> None:
        with self._lock:
            if resource_hash in self._loaded:
                return
        pool_id = agent.identity.pool_id
        node_id = agent.identity.node_id
        handle = None
        # Acquire one of the K lock slots (reference hash.{0..N} blobs).
        while handle is None:
            for slot in range(max(1, concurrent)):
                lease_key = names.global_resource_lock_key(
                    pool_id, resource_hash, slot)
                handle = self.store.acquire_lease(
                    lease_key, LEASE_SECONDS, node_id)
                if handle is not None:
                    break
            if handle is None:
                if getattr(agent, "stop_event", None) is not None and \
                        agent.stop_event.is_set():
                    return
                time.sleep(0.1)
        stop_renew = threading.Event()

        def _renew():
            nonlocal handle
            while not stop_renew.wait(RENEW_INTERVAL):
                try:
                    handle = self.store.renew_lease(handle, LEASE_SECONDS)
                except Exception:
                    logger.warning("cascade lease renew lost for %s",
                                   image)
                    return

        renewer = threading.Thread(target=_renew, daemon=True)
        renewer.start()
        try:
            perf.emit(self.store, pool_id, node_id, "cascade",
                      f"pull.start:{image}")
            rc = self._pull(kind, image, source_blob=source_blob)
            perf.emit(self.store, pool_id, node_id, "cascade",
                      f"pull.end:{image}", message=str(rc))
            if rc == 0:
                with self._lock:
                    self._loaded.add(resource_hash)
                self._record_loaded(pool_id, node_id)
        finally:
            stop_renew.set()
            renewer.join(timeout=1.0)
            try:
                self.store.release_lease(handle)
            except Exception:
                pass

    def _pull(self, kind: str, image: str,
              source_blob: Optional[str] = None) -> int:
        if self._puller is not None:
            return self._puller(kind, image)
        if source_blob:
            return self._direct_download(kind, image, source_blob)
        if kind == "docker":
            if shutil.which("docker") is None:
                logger.info("docker unavailable; skipping pull of %s",
                            image)
                return 0
            rc = subprocess.call(["docker", "pull", image],
                                 timeout=self.pull_timeout)
            if rc != 0 and self.fallback_registry:
                fallback = f"{self.fallback_registry}/{image}"
                rc = subprocess.call(["docker", "pull", fallback],
                                     timeout=self.pull_timeout)
                if rc == 0:
                    rc = subprocess.call(
                        ["docker", "tag", fallback, image])
            return rc
        if kind == "singularity":
            if shutil.which("singularity") is None:
                logger.info("singularity unavailable; skipping %s", image)
                return 0
            return subprocess.call(
                ["singularity", "pull", "--force", f"docker://{image}"],
                timeout=self.pull_timeout)
        raise ValueError(f"unknown image kind {kind!r}")

    def _direct_download(self, kind: str, image: str,
                         source_blob: str) -> int:
        """Stream a preloaded image tarball from the object store to
        the node's cache (the reference's direct-download mode), then
        `docker load` it when docker is present. Without docker the
        tarball still lands on disk — real bytes over the real store
        path, which is also what the bench measures."""
        import tempfile
        if not getattr(self, "_cache_dir", None):
            self._cache_dir = tempfile.mkdtemp(
                prefix="shipyard-image-cache-")
        path = os.path.join(self._cache_dir,
                            os.path.basename(source_blob))
        tmp = path + ".part"
        total = 0
        with open(tmp, "wb") as fh:
            for chunk in self.store.get_object_stream(source_blob):
                fh.write(chunk)
                total += len(chunk)
        os.replace(tmp, path)
        logger.info("direct-downloaded %s (%d bytes) from %s",
                    image, total, source_blob)
        if kind == "docker" and shutil.which("docker"):
            return subprocess.call(["docker", "load", "-i", path],
                                   timeout=self.pull_timeout)
        if kind == "singularity" and shutil.which("singularity"):
            # A saved OCI tarball loads as a sif build source.
            return subprocess.call(
                ["singularity", "build", "--force",
                 path + ".sif", f"docker-archive://{path}"],
                timeout=self.pull_timeout)
        return 0

    def _record_loaded(self, pool_id: str, node_id: str) -> None:
        with self._lock:
            loaded = sorted(self._loaded)
        table = names.TABLE_IMAGES + "done"
        try:
            self.store.insert_entity(table, pool_id, node_id,
                                     {"loaded": loaded})
        except EntityExistsError:
            self.store.merge_entity(table, pool_id, node_id,
                                    {"loaded": loaded})

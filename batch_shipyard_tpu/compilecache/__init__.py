"""Warm-start compilation: persistent compile cache, AOT precompile,
and pool-wide cache seeding.

Compile time is a first-class badput category in the ML Productivity
Goodput decomposition (goodput/accounting.py, arxiv 2502.06982), and
on real pods it is minutes per task multiplied by pool width and
restart count. This package removes it three ways:

  * **manager** — configure JAX's persistent XLA compilation cache
    (``jax_compilation_cache_dir`` + entry-size/compile-time knobs),
    compute a stable *cache identity key* (jax/jaxlib versions, device
    kind, topology, model-config digest), and measure hit/miss/
    saved-seconds by diffing cache-dir contents around a compile so
    goodput can report ``compile_saved_seconds`` honestly.
  * **aot** — opt-in ``--aot-precompile``: ``jit(...).lower(...)
    .compile()`` the train step / serving prefill+decode functions
    against ``jax.ShapeDtypeStruct`` abstract inputs, so compilation
    overlaps data-pipeline startup instead of blocking the first step.
  * **seeding** — the node agent exports the cache dir as a tar
    artifact to the state store after a task (lease-guarded, one
    uploader) and seeds it before the next — first node compiles, the
    other N-1 and every restart hit warm (the image-prefetch pattern,
    agent/cascade.py).

Surfacing: ``shipyard pool cache stats|seed|prune`` (cli/main.py),
the ``compile_warm`` bench phase (bench.py), and
``goodput_compile_saved_seconds`` gauges (monitor/heimdall.py). See
docs/29-compile-cache.md.
"""

from batch_shipyard_tpu.compilecache import aot  # noqa: F401
from batch_shipyard_tpu.compilecache import seeding  # noqa: F401
from batch_shipyard_tpu.compilecache.manager import (  # noqa: F401
    CACHE_DIR_ENV, CompileCacheManager, add_compile_cache_args,
    config_digest, current, enable, enable_from_args, identity_key,
    tracked)

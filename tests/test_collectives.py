"""Collective microbench sanity on the virtual CPU mesh (the mpiBench
recipe analog must run anywhere)."""

import jax
import jax.numpy as jnp

from batch_shipyard_tpu.ops import collectives
from batch_shipyard_tpu.parallel import mesh as mesh_mod


def test_collective_bench_runs_all_ops():
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    rows = collectives.run_collective_bench(
        mesh, axis="dp", sizes_bytes=(1 << 12,), dtype=jnp.float32)
    ops = {r["op"] for r in rows}
    assert ops == {"psum", "all_gather", "ppermute", "reduce_scatter"}
    for row in rows:
        assert row["seconds"] > 0
        assert row["algo_bw_gbps"] > 0


def test_collective_correctness():
    """The timed functions must also be *correct* collectives."""
    import numpy as np
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    x = jnp.arange(8 * 128, dtype=jnp.float32)
    psum_fn = collectives._collective_fn(mesh, "dp", "psum")
    out = psum_fn(x)
    # Each shard contributes its slice; psum over 8 shards of the
    # sharded input returns sum of shards, replicated.
    expected = np.asarray(x).reshape(8, 128).sum(axis=0)
    np.testing.assert_allclose(np.asarray(out), expected)


def test_hierarchical_all_to_all_matches_transpose():
    """Two-phase (ICI then DCN) all-to-all delivers exactly the
    (src <-> dst) transpose a flat all-to-all would, on a factored
    2 x 4 expert mesh."""
    import numpy as np
    from batch_shipyard_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n_out, n_in, d = 2, 4, 8
    devices = np.array(jax.devices()[:n_out * n_in]).reshape(
        n_out, n_in)
    mesh = Mesh(devices, ("ep_out", "ep_in"))
    rng = np.random.RandomState(0)
    # X[src_o, src_i, dst_o, dst_i, :] = the block (src -> dst).
    x_global = jnp.asarray(
        rng.randn(n_out, n_in, n_out, n_in, d), jnp.float32)

    def body(x_block):
        # per-device block [1, 1, n_out, n_in, d] -> dest-indexed.
        y = collectives.hierarchical_all_to_all(
            x_block[0, 0], "ep_out", "ep_in")
        return y[None, None]

    fn = shard_map(
        body, mesh=mesh,
        in_specs=P("ep_out", "ep_in", None, None, None),
        out_specs=P("ep_out", "ep_in", None, None, None),
        check_vma=False)
    got = np.asarray(fn(x_global))
    # Device (o, i) must end with Y[s_o, s_i] = X[s_o, s_i, o, i].
    want = np.asarray(x_global).transpose(2, 3, 0, 1, 4)
    np.testing.assert_allclose(got, want)


def test_hierarchical_all_to_all_roundtrip():
    """Applying the exchange twice returns the original blocks (the
    transpose is an involution) — the combine path of MoE dispatch."""
    import numpy as np
    from batch_shipyard_tpu.utils.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    n_out, n_in, d = 2, 4, 4
    devices = np.array(jax.devices()[:n_out * n_in]).reshape(
        n_out, n_in)
    mesh = Mesh(devices, ("ep_out", "ep_in"))
    rng = np.random.RandomState(1)
    x_global = jnp.asarray(
        rng.randn(n_out, n_in, n_out, n_in, d), jnp.float32)

    def body(x_block):
        y = collectives.hierarchical_all_to_all(
            x_block[0, 0], "ep_out", "ep_in")
        z = collectives.hierarchical_all_to_all(y, "ep_out", "ep_in")
        return z[None, None]

    fn = shard_map(
        body, mesh=mesh,
        in_specs=P("ep_out", "ep_in", None, None, None),
        out_specs=P("ep_out", "ep_in", None, None, None),
        check_vma=False)
    np.testing.assert_allclose(np.asarray(fn(x_global)),
                               np.asarray(x_global))

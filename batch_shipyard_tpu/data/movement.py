"""Data movement: ingress/egress between local paths, the object store,
and pool nodes.

Reference analog: convoy/data.py — ingress_data(:981) dispatching to
blobxfer (azure_storage) or scp/rsync (_singlenode_transfer :492 /
_multinode_transfer :567 with round-robin size-balanced file sharding
and optional byte-offset splits), plus task-level process_input_data
(:219) and process_output_data (:447).

TPU-native mapping:
  - azure_storage/blobxfer  -> the state store's object space (GCS in
    production) via put/get_object (whole-file transfers; objects are
    read fully into memory — streaming is a future store API change),
    with include/exclude globs;
  - shared-fs scp/rsync     -> same ssh-based sharded transfer,
    synthesized as command lines (testable dry-run; executed via
    subprocess when live);
  - task input_data/output_data -> handled by the node agent around
    task execution using statestore keys (kind: statestore) or local
    paths.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import os
from typing import Optional

from batch_shipyard_tpu.config.settings import GlobalSettings
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError, StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


# --------------------------- object ingress ----------------------------

def _iter_files(source: str, include: Optional[list[str]] = None,
                exclude: Optional[list[str]] = None):
    if os.path.isfile(source):
        yield source, os.path.basename(source)
        return
    for root, _dirs, files in os.walk(source):
        for name in files:
            path = os.path.join(root, name)
            rel = os.path.relpath(path, source)
            if include and not any(
                    fnmatch.fnmatch(rel, pat) for pat in include):
                continue
            if exclude and any(
                    fnmatch.fnmatch(rel, pat) for pat in exclude):
                continue
            yield path, rel


def ingress_to_storage(store: StateStore, source: str, dest_prefix: str,
                       include: Optional[list[str]] = None,
                       exclude: Optional[list[str]] = None) -> int:
    """Upload local file(s) into the object space. Returns file count."""
    count = 0
    for path, rel in _iter_files(source, include, exclude):
        key = f"{dest_prefix.rstrip('/')}/{rel}".lstrip("/")
        with open(path, "rb") as fh:
            store.put_object(key, fh.read())
        count += 1
    logger.info("ingressed %d files from %s to %s", count, source,
                dest_prefix)
    return count


def _prefix_children(store: StateStore, prefix: str) -> list[str]:
    """Keys strictly under prefix treated as a directory (never keys
    that merely share a string prefix, e.g. 'v10' under 'v1')."""
    base = prefix.rstrip("/")
    return [k for k in store.list_objects(base)
            if k == base or k.startswith(base + "/")]


def egress_from_storage(store: StateStore, prefix: str,
                        dest_dir: str) -> int:
    """Download an object-prefix tree into a local directory."""
    count = 0
    base = prefix.rstrip("/")
    for key in _prefix_children(store, base):
        rel = key[len(base):].lstrip("/")
        if not rel:
            rel = os.path.basename(base)
        path = os.path.join(dest_dir, rel)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(store.get_object(key))
        count += 1
    return count


def ingress_data(store: StateStore, global_conf: GlobalSettings,
                 pool_id: Optional[str] = None) -> int:
    """Process global_resources.files ingress specs (data ingress verb,
    fleet.py:4496 analog)."""
    total = 0
    for spec in global_conf.files:
        source = spec.get("source", {})
        dest = spec.get("destination", {})
        if "storage" in dest or "prefix" in dest:
            prefix = (dest.get("storage", {}).get("prefix")
                      or dest.get("prefix", "ingress"))
            total += ingress_to_storage(
                store, source.get("path", "."), prefix,
                include=source.get("include"),
                exclude=source.get("exclude"))
        elif "shared_data_volume" in dest or "relative_destination_path" \
                in dest:
            raise NotImplementedError(
                "direct-to-node ingress requires a live pool; use "
                "plan_multinode_transfer + run_transfers")
    return total


# ------------------------ node (ssh) transfers -------------------------

@dataclasses.dataclass(frozen=True)
class TransferCommand:
    node_id: str
    argv: tuple[str, ...]
    files: tuple[str, ...]
    total_bytes: int


def plan_multinode_transfer(
        files: list[tuple[str, int]], nodes: list[tuple[str, str, int]],
        dest_path: str, method: str = "scp",
        ssh_username: str = "shipyard",
        ssh_private_key: Optional[str] = None,
        host_key_checking: str = "accept-new",
        ) -> list[TransferCommand]:
    """Shard files across nodes round-robin balanced by size and emit
    per-node transfer command lines (reference _multinode_transfer
    data.py:567: largest-first onto least-loaded node).

    files: [(local_path, size)]; nodes: [(node_id, ip, port)].
    host_key_checking: OpenSSH StrictHostKeyChecking value. The
    'accept-new' default is trust-on-first-use; pass 'no' for
    throwaway/re-provisioned nodes whose IPs get recycled with fresh
    host keys (the reference's unconditional behavior).
    """
    if method not in ("scp", "rsync"):
        raise ValueError(f"unknown transfer method {method!r}")
    if not nodes:
        raise ValueError("no nodes to transfer to")
    loads: list[int] = [0] * len(nodes)
    shards: list[list[str]] = [[] for _ in nodes]
    for path, size in sorted(files, key=lambda fs: -fs[1]):
        idx = loads.index(min(loads))
        shards[idx].append(path)
        loads[idx] += size
    out: list[TransferCommand] = []
    for (node_id, ip, port), shard, load in zip(nodes, shards, loads):
        if not shard:
            continue
        key_args = (("-i", ssh_private_key) if ssh_private_key else ())
        hk = (("-o", f"StrictHostKeyChecking={host_key_checking}") +
              (("-o", "UserKnownHostsFile=/dev/null")
               if host_key_checking == "no" else ()))
        if method == "scp":
            argv = ("scp", *hk,
                    "-P", str(port), *key_args, "-p", *shard,
                    f"{ssh_username}@{ip}:{dest_path}")
        else:
            ssh_cmd = " ".join((
                "ssh", *hk,
                *key_args, "-p", str(port)))
            argv = ("rsync", "-az", "-e", ssh_cmd, *shard,
                    f"{ssh_username}@{ip}:{dest_path}")
        out.append(TransferCommand(
            node_id=node_id, argv=argv, files=tuple(shard),
            total_bytes=load))
    return out


def run_transfers(commands: list[TransferCommand],
                  max_parallel: int = 4) -> list[int]:
    """Execute planned transfers with bounded parallelism."""
    results: list[int] = []
    for batch in util.chunked(commands, max_parallel):
        procs = [util.subprocess_nowait(list(c.argv)) for c in batch]
        results.extend(util.subprocess_wait_all(procs))
    return results


# ---------------------- task-level input/output ------------------------

def stage_task_inputs(store: StateStore, input_data: list[dict],
                      task_dir: str) -> None:
    """Materialize input_data specs into the task dir before execution
    (process_input_data analog, data.py:219)."""
    for spec in input_data:
        kind = spec.get("kind", "statestore")
        if kind == "task_output":
            # Pull another task's uploaded outputs (the reference's
            # cargo/task_file_mover.py input_data:azure_batch path,
            # trivially storage-mediated here).
            key = names.task_output_key(
                spec["pool_id"], spec["job_id"], spec["task_id"],
                spec.get("filename", "outputs"))
            spec = {"kind": "statestore", "key": key,
                    "file_path": spec.get("file_path",
                                          spec["task_id"])}
            kind = "statestore"
        if kind == "statestore":
            key = spec["key"]
            rel = spec.get("file_path") or key.rsplit("/", 1)[-1]
            dest = os.path.join(task_dir, rel)
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            try:
                data = store.get_object(key)
            except NotFoundError:
                # Prefix fetch: key may name a directory-like prefix.
                base = key.rstrip("/")
                sub = _prefix_children(store, base)
                if not sub:
                    raise
                for skey in sub:
                    srel = skey[len(base):].lstrip("/")
                    spath = os.path.join(dest, srel)
                    os.makedirs(os.path.dirname(spath) or ".",
                                exist_ok=True)
                    with open(spath, "wb") as fh:
                        fh.write(store.get_object(skey))
                continue
            with open(dest, "wb") as fh:
                fh.write(data)
        elif kind == "local":
            continue  # already on the node filesystem
        else:
            raise ValueError(f"unknown input_data kind {kind!r}")


def collect_task_outputs(store: StateStore, output_data: list[dict],
                         task_dir: str, pool_id: str, job_id: str,
                         task_id: str,
                         exclude_rels: Optional[set[str]] = None) -> int:
    """Upload output_data globs after execution (process_output_data
    analog, data.py:447). exclude_rels: relative paths staged as
    inputs, which must not be re-uploaded as outputs. Returns count."""
    count = 0
    exclude_rels = exclude_rels or set()
    for spec in output_data:
        pattern = spec.get("include")
        prefix = spec.get("prefix") or names.task_output_key(
            pool_id, job_id, task_id, "outputs")
        for root, _dirs, files in os.walk(task_dir):
            for name in files:
                path = os.path.join(root, name)
                rel = os.path.relpath(path, task_dir)
                if rel in ("stdout.txt", "stderr.txt"):
                    continue
                if rel in exclude_rels:
                    continue
                # fnmatch has no '**' semantics: treat missing/match-all
                # patterns explicitly, else match rel then basename.
                if pattern not in (None, "*", "**/*") and not (
                        fnmatch.fnmatch(rel, pattern) or
                        fnmatch.fnmatch(name, pattern)):
                    continue
                with open(path, "rb") as fh:
                    store.put_object(f"{prefix}/{rel}", fh.read())
                count += 1
    return count


def staged_input_rels(store: StateStore,
                      input_data: list[dict]) -> set[str]:
    """Relative paths that stage_task_inputs materializes, for output
    exclusion."""
    rels: set[str] = set()
    for spec in input_data:
        kind = spec.get("kind", "statestore")
        if kind == "task_output":
            key = names.task_output_key(
                spec["pool_id"], spec["job_id"], spec["task_id"],
                spec.get("filename", "outputs"))
            rel = spec.get("file_path", spec["task_id"])
        elif kind == "statestore":
            key = spec["key"]
            rel = spec.get("file_path") or key.rsplit("/", 1)[-1]
        else:
            continue
        if store.object_exists(key):
            rels.add(rel)
        else:
            base = key.rstrip("/")
            for skey in _prefix_children(store, base):
                srel = skey[len(base):].lstrip("/")
                rels.add(os.path.join(rel, srel) if srel else rel)
    return rels

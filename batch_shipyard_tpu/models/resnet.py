"""ResNet-50 in Flax: the BASELINE.md headline workload
(TensorFlow-Distributed recipe's ResNet-50/ImageNet, re-built
TPU-first).

TPU-first choices: bfloat16 convs/matmuls (MXU), float32 batch-norm
statistics, NHWC layout (XLA TPU's native conv layout), and a
fuse-friendly residual structure (XLA fuses the BN+ReLU chains into
the conv epilogues).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), (self.strides, self.strides),
                    padding=[(1, 1), (1, 1)], use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="bn2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv3")(y)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, scale_init=nn.initializers.zeros,
                         name="bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), (self.strides, self.strides),
                use_bias=False, dtype=self.dtype, name="proj_conv")(x)
            residual = nn.BatchNorm(
                use_running_average=not train, momentum=0.9,
                dtype=self.dtype, name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        """images: [B, H, W, 3] -> logits [B, num_classes]."""
        cfg = self.config
        x = images.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), (2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=cfg.dtype, name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=cfg.dtype, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        for stage, num_blocks in enumerate(cfg.stage_sizes):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    cfg.width * (2 ** stage), strides, cfg.dtype,
                    name=f"stage{stage}_block{block}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                          name="classifier")(x)
        return logits


def resnet50(num_classes: int = 1000,
             dtype: Any = jnp.bfloat16) -> ResNet:
    return ResNet(ResNetConfig(num_classes=num_classes, dtype=dtype))


def cross_entropy_loss(logits, labels):
    logprobs = nn.log_softmax(logits.astype(jnp.float32))
    onehot = jnp.eye(logits.shape[-1], dtype=jnp.float32)[labels]
    return -jnp.mean(jnp.sum(onehot * logprobs, axis=-1))

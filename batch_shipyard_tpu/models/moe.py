"""Mixture-of-Experts layer with expert parallelism.

GShard-style top-1 routing with capacity limits, expressed as dense
one-hot dispatch/combine einsums — the XLA-native formulation: the
dispatch tensor contraction becomes an all-to-all over the ``ep`` mesh
axis when the expert dimension of the parameters is sharded
P('ep', ...), with no manual collectives.

Pieces:
  - Router: softmax gate, top-1 expert per token, position-in-expert
    via a cumulative sum, tokens beyond capacity dropped (their
    contribution is the residual path).
  - Dispatch: one-hot [tokens, experts, capacity] einsum packs token
    activations into per-expert buffers.
  - Experts: batched SwiGLU MLPs, parameters [E, ...] (ep-sharded).
  - Combine: the same tensor weighted by gate probabilities unpacks
    expert outputs back to token order.

Auxiliary load-balancing loss per GShard/Switch: mean(fraction of
tokens per expert * mean gate prob per expert) * num_experts^2.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    d_model: int = 512
    d_ff: int = 1408
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    router_noise: float = 0.0
    num_selected: int = 1    # 1 = Switch-style, 2 = GShard top-2
    # "tokens": tokens pick experts (top-1/top-k above, needs the
    # load-balancing aux loss). "expert_choice": experts pick their
    # top-C tokens (Zhou et al. 2022) — perfectly load-balanced by
    # construction, no aux loss.
    routing: str = "tokens"


def top1_routing(logits, capacity: int):
    """logits: [G, E] (G = flattened tokens). Returns
    (dispatch [G, E, C] bool-ish, combine [G, E, C] float,
    aux_loss scalar)."""
    groups, num_experts = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_index = jnp.argmax(probs, axis=-1)            # [G]
    expert_mask = jax.nn.one_hot(expert_index, num_experts,
                                 dtype=jnp.float32)      # [G, E]
    # Position of each token within its chosen expert's buffer.
    position_in_expert = (jnp.cumsum(expert_mask, axis=0) *
                          expert_mask) - expert_mask      # [G, E]
    keep = position_in_expert < capacity
    expert_mask = expert_mask * keep
    gate = jnp.sum(probs * expert_mask, axis=-1)          # [G]
    pos = jnp.sum(position_in_expert * expert_mask,
                  axis=-1).astype(jnp.int32)              # [G]
    pos_onehot = jax.nn.one_hot(pos, capacity,
                                dtype=jnp.float32)        # [G, C]
    dispatch = expert_mask[:, :, None] * pos_onehot[:, None, :]
    combine = dispatch * gate[:, None, None]
    # Load-balancing auxiliary loss (Switch Transformer eq. 4).
    density = jnp.mean(expert_mask, axis=0)               # [E]
    density_proxy = jnp.mean(probs, axis=0)               # [E]
    aux = jnp.sum(density * density_proxy) * (num_experts ** 2) / (
        num_experts)
    return dispatch, combine, aux


def topk_routing(logits, capacity: int, num_selected: int = 2):
    """GShard-style top-k routing. logits: [G, E]. Returns (dispatch
    [G, E, C], combine [G, E, C], aux). First choices get buffer
    priority; second choices fill remaining capacity; gates of the
    selected experts are renormalized per token."""
    groups, num_experts = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, num_selected)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)
    dispatch = jnp.zeros((groups, num_experts, capacity),
                         dtype=jnp.float32)
    combine = jnp.zeros_like(dispatch)
    used = jnp.zeros((num_experts,), dtype=jnp.float32)
    first_mask = None
    for choice in range(num_selected):
        mask = jax.nn.one_hot(expert_idx[:, choice], num_experts,
                              dtype=jnp.float32)
        if first_mask is None:
            first_mask = mask
        position = (jnp.cumsum(mask, axis=0) - 1.0 +
                    used[None, :]) * mask
        keep = (position < capacity) & (mask > 0)
        mask = mask * keep
        pos = jnp.sum(position * mask, axis=-1).astype(jnp.int32)
        pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
        sel = mask[:, :, None] * pos_onehot[:, None, :]
        dispatch = dispatch + sel
        combine = combine + sel * gate_vals[:, choice][:, None, None]
        used = used + jnp.sum(mask, axis=0)
    density = jnp.mean(first_mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


def expert_choice_routing(logits, capacity: int):
    """Expert-choice routing (Zhou et al. 2022): each EXPERT selects
    its top-C tokens by affinity, the transpose of token-choice.
    Load is perfectly balanced by construction (every expert processes
    exactly C tokens), so there is no auxiliary loss (returns 0.0);
    a token may be picked by several experts (outputs sum) or by none
    (the residual path carries it).

    logits: [G, E]. Returns (dispatch [G, E, C], combine [G, E, C],
    aux=0.0).
    """
    groups, num_experts = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    # Per-expert token affinities: [E, G]; each expert takes top-C.
    gate_vals, token_idx = jax.lax.top_k(probs.T, capacity)  # [E, C]
    dispatch = jax.nn.one_hot(
        token_idx, groups, dtype=jnp.float32)                # [E, C, G]
    dispatch = dispatch.transpose(2, 0, 1)                   # [G, E, C]
    combine = dispatch * gate_vals[None, :, :]
    return dispatch, combine, jnp.float32(0.0)


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: top-1 routed SwiGLU experts."""

    config: MoEConfig

    @nn.compact
    def __call__(self, x):
        """x: [B, T, D] -> ([B, T, D], aux_loss)."""
        cfg = self.config
        if cfg.routing not in ("tokens", "expert_choice"):
            raise ValueError(
                f"unknown MoE routing {cfg.routing!r} "
                f"(expected 'tokens' or 'expert_choice')")
        batch, t_len, d_model = x.shape
        groups = batch * t_len
        capacity = max(1, int(cfg.capacity_factor * groups /
                              cfg.num_experts))
        router = nn.Dense(cfg.num_experts, use_bias=False,
                          dtype=jnp.float32,
                          param_dtype=cfg.param_dtype, name="router")
        flat = x.reshape(groups, d_model)
        logits = router(flat.astype(jnp.float32))
        if cfg.router_noise > 0.0:
            noise = jax.random.uniform(
                self.make_rng("router"), logits.shape,
                minval=1.0 - cfg.router_noise,
                maxval=1.0 + cfg.router_noise)
            logits = logits * noise
        if cfg.routing == "expert_choice":
            dispatch, combine, aux = expert_choice_routing(logits,
                                                           capacity)
        elif cfg.num_selected > 1:
            dispatch, combine, aux = topk_routing(
                logits, capacity, cfg.num_selected)
        else:
            dispatch, combine, aux = top1_routing(logits, capacity)
        # Expert parameters: leading E dim is the ep-sharded axis.
        w_gate = self.param(
            "w_gate", nn.initializers.lecun_normal(),
            (cfg.num_experts, d_model, cfg.d_ff), cfg.param_dtype)
        w_up = self.param(
            "w_up", nn.initializers.lecun_normal(),
            (cfg.num_experts, d_model, cfg.d_ff), cfg.param_dtype)
        w_down = self.param(
            "w_down", nn.initializers.lecun_normal(),
            (cfg.num_experts, cfg.d_ff, d_model), cfg.param_dtype)
        # Dispatch tokens into per-expert buffers: [E, C, D]. With
        # dispatch replicated and experts ep-sharded, XLA lowers the
        # downstream per-expert compute to an all-to-all exchange.
        expert_in = jnp.einsum(
            "gec,gd->ecd", dispatch.astype(cfg.dtype),
            flat.astype(cfg.dtype))
        gate_act = jnp.einsum("ecd,edf->ecf", expert_in,
                              w_gate.astype(cfg.dtype))
        up_act = jnp.einsum("ecd,edf->ecf", expert_in,
                            w_up.astype(cfg.dtype))
        expert_out = jnp.einsum(
            "ecf,efd->ecd", nn.silu(gate_act) * up_act,
            w_down.astype(cfg.dtype))
        out = jnp.einsum("gec,ecd->gd", combine.astype(cfg.dtype),
                         expert_out)
        return out.reshape(batch, t_len, d_model), aux.astype(
            jnp.float32)


def moe_ep_apply_shard(flat, router_kernel, w_gate, w_up, w_down,
                       capacity: int, outer_axis: Optional[str],
                       inner_axis: str, routing: str = "top1",
                       num_selected: int = 2,
                       dtype=jnp.bfloat16):
    """Explicit expert-parallel MoE body for shard_map, with the
    cross-slice exchange on ops/collectives.hierarchical_all_to_all
    (ROADMAP 'wire it into a shard_map MoE dispatch variant').

    The flax MoEMLP leaves the exchange to XLA's sharding propagation
    — correct, but on a multi-slice mesh a flat all-to-all over the
    combined ep axis sends n_inner^2 small DCN messages per slice
    pair. This body routes locally, packs destination-indexed
    buffers, and exchanges them hierarchically (ICI phase inside the
    slice, then ONE aggregated DCN message per slice pair), runs the
    local expert shard, and reverses the exchange — the MoE dispatch
    pattern for experts spanning slices.

    Per-device arguments (call inside shard_map):
      flat          [G_local, D]    this device's tokens
      router_kernel [D, E]          replicated
      w_gate/w_up   [E_local, D, F] local expert shard
      w_down        [E_local, F, D] local expert shard
    Expert e's global id is (outer * n_inner + inner) * E_local + el
    — i.e. leading-dim sharding of [E, ...] weights over the factored
    (outer_axis, inner_axis) mesh axes, which is exactly what
    in_specs=P((outer, inner), ...) hands each device.

    outer_axis=None runs the SINGLE-AXIS case (experts sharded over
    one mesh axis — the common single-slice ep layout): the exchange
    degenerates to one plain all_to_all over inner_axis.

    Returns ([G_local, D] combined output, aux loss averaged over the
    ep group). Token routing/capacity is PER DEVICE GROUP (each
    device's G_local tokens route independently) — same semantics as
    running the dense MoEMLP on each group.
    """
    from batch_shipyard_tpu.ops import collectives

    n_out = 1 if outer_axis is None else jax.lax.psum(1, outer_axis)
    n_in = jax.lax.psum(1, inner_axis)
    n_ep = n_out * n_in

    def exchange(x):
        """Destination-indexed [n_out, n_in, ...] -> source-indexed
        (an involution): hierarchical over (outer, inner), or one
        plain all_to_all when there is no outer axis."""
        if outer_axis is None:
            return jax.lax.all_to_all(x, inner_axis, split_axis=1,
                                      concat_axis=1)
        return collectives.hierarchical_all_to_all(
            x, outer_axis, inner_axis)
    e_local, d_model = w_gate.shape[0], w_gate.shape[1]
    num_experts = e_local * n_ep

    logits = flat.astype(jnp.float32) @ router_kernel.astype(
        jnp.float32)
    if routing == "expert_choice":
        dispatch, combine, aux = expert_choice_routing(logits,
                                                       capacity)
    elif routing == "topk":
        dispatch, combine, aux = topk_routing(logits, capacity,
                                              num_selected)
    else:
        dispatch, combine, aux = top1_routing(logits, capacity)
    # Pack per-expert send buffers [E, C, D], then view the expert
    # dim as destination coordinates [n_out, n_in, E_local, C, D].
    expert_in = jnp.einsum("gec,gd->ecd", dispatch.astype(dtype),
                           flat.astype(dtype))
    x = expert_in.reshape(n_out, n_in, e_local, capacity, d_model)
    # ICI-then-DCN exchange: arrives source-indexed (a[o, i] = the
    # buffer device (o, i) sent to MY experts).
    a = exchange(x)
    # Batch all sources through the local expert shard.
    a = a.reshape(n_ep, e_local, capacity, d_model)
    a = a.transpose(1, 0, 2, 3).reshape(e_local, n_ep * capacity,
                                        d_model)
    gate_act = jnp.einsum("end,edf->enf", a, w_gate.astype(dtype))
    up_act = jnp.einsum("end,edf->enf", a, w_up.astype(dtype))
    out = jnp.einsum("enf,efd->end", nn.silu(gate_act) * up_act,
                     w_down.astype(dtype))
    # Reverse exchange: the same hierarchical a2a returns each
    # processed buffer to its origin device (the exchange is an
    # involution on the [n_out, n_in] block layout).
    out = out.reshape(e_local, n_ep, capacity, d_model)
    out = out.transpose(1, 0, 2, 3).reshape(n_out, n_in, e_local,
                                            capacity, d_model)
    r = exchange(out)
    r = r.reshape(num_experts, capacity, d_model)
    y = jnp.einsum("gec,ecd->gd", combine.astype(dtype), r)
    aux = jax.lax.pmean(aux, inner_axis)
    if outer_axis is not None:
        aux = jax.lax.pmean(aux, outer_axis)
    return y, aux.astype(jnp.float32)


def moe_ep_stage(flat, router_kernel, w_gate, w_up, w_down,
                 capacity: int, inner_axis: str,
                 outer_axis: Optional[str] = None,
                 routing: str = "top1", num_selected: int = 2,
                 dtype=jnp.bfloat16):
    """Expert-parallel MoE for a shard_map STAGE whose activations are
    REPLICATED across the ep axis — the pipeline-parallel composition
    (dp x pp x ep): pipeline stages shard over pp, activations stream
    through replicated across ep, and this stage splits the tokens by
    ep rank, runs the explicit dispatch (moe_ep_apply_shard) on the
    local shard, and all_gathers the outputs back into the replicated
    stream. Everything is unconditional collectives, so it is legal
    inside the (non-interleaved) 1F1B tick like tp is.

    CONTRACT: differentiate INSIDE the shard_map body (the pipeline
    does — manual vjp per tick), where the cotangent arriving at the
    region output is replicated-full by construction. Taking
    jax.grad ACROSS the shard_map boundary instead hits shard_map's
    replicated-output transpose (cotangent split across members) and
    undercounts expert-shard grads.

    The whole split->dispatch->gather region carries a custom VJP:
    with replicated in/out cotangents, naive autodiff would overcount
    the gather's transpose by the ep size and leave the replicated
    router's (and the sliced input's) per-rank PARTIAL grads
    un-summed. The backward here takes each rank's slice of the full
    cotangent through the local pullback, then assembles dx from the
    rank-disjoint scatters and psums the replicated router grad —
    the Megatron f/g discipline applied to a replicated stream.

    flat: [G, D] REPLICATED across ep (G divisible by the ep size).
    Weights: local expert shards [E_local, ...] (ep-sharded specs).
    Returns ([G, D] replicated, aux scalar).
    """
    axes = ([inner_axis] if outer_axis is None
            else [inner_axis, outer_axis])

    def _psum_all(v):
        for ax in axes:
            v = jax.lax.psum(v, ax)
        return v

    n_out = 1 if outer_axis is None else jax.lax.psum(1, outer_axis)
    n_in = jax.lax.psum(1, inner_axis)
    n_ep = n_out * n_in

    def _my():
        # axis_index is TRACED: recompute inside every custom_vjp
        # stage (fwd and bwd trace separately under jax.grad; a
        # closed-over tracer from one would leak into the other).
        my_in = jax.lax.axis_index(inner_axis)
        if outer_axis is None:
            return my_in
        return jax.lax.axis_index(outer_axis) * n_in + my_in

    g_total, _d = flat.shape
    if g_total % n_ep:
        raise ValueError(
            f"moe_ep_stage: {g_total} tokens not divisible by the "
            f"ep size {n_ep}")
    g_local = g_total // n_ep
    flat_shape_dtype = jax.ShapeDtypeStruct(flat.shape, flat.dtype)

    def local(mine, router, wg, wu, wd):
        return moe_ep_apply_shard(
            mine, router, wg, wu, wd, capacity=capacity,
            outer_axis=outer_axis, inner_axis=inner_axis,
            routing=routing, num_selected=num_selected, dtype=dtype)

    def _gather(y_local):
        y = jax.lax.all_gather(y_local, inner_axis, axis=0,
                               tiled=True)
        if outer_axis is not None:
            y = jax.lax.all_gather(y, outer_axis, axis=0, tiled=True)
        return y

    @jax.custom_vjp
    def region(flat, router, wg, wu, wd):
        mine = jax.lax.dynamic_slice_in_dim(
            flat, _my() * g_local, g_local, axis=0)
        y_local, aux = local(mine, router, wg, wu, wd)
        return _gather(y_local), aux

    def region_fwd(flat, router, wg, wu, wd):
        mine = jax.lax.dynamic_slice_in_dim(
            flat, _my() * g_local, g_local, axis=0)
        (y_local, aux), pullback = jax.vjp(local, mine, router, wg,
                                           wu, wd)
        return (_gather(y_local), aux), pullback

    def region_bwd(pullback, cot):
        dy, daux = cot
        # Full (replicated) dy: every rank pulls ITS token slice back
        # through the local region. daux is also replicated-full, but
        # the pullback routes it through the pmean's psum transpose
        # AND region_bwd psums the router partials below — divide by
        # the ep size so the aux gradient is counted exactly once
        # (empirically n_ep-times overcounted otherwise).
        my = _my()
        dy_local = jax.lax.dynamic_slice_in_dim(
            dy, my * g_local, g_local, axis=0)
        dmine, drouter, dwg, dwu, dwd = pullback(
            (dy_local, daux / n_ep))
        # Rank-disjoint scatters assemble the replicated dx; the
        # replicated router grad is the sum of per-rank partials.
        dflat = jnp.zeros(flat_shape_dtype.shape,
                          flat_shape_dtype.dtype)
        dflat = jax.lax.dynamic_update_slice_in_dim(
            dflat, dmine.astype(dflat.dtype), my * g_local, axis=0)
        return (_psum_all(dflat), _psum_all(drouter), dwg, dwu, dwd)

    region.defvjp(region_fwd, region_bwd)
    return region(flat, router_kernel, w_gate, w_up, w_down)


def moe_param_specs():
    """PartitionSpec patterns for MoE params (merged into the
    transformer rules): experts over ep, expert-internal dims over
    tp/fsdp."""
    from jax.sharding import PartitionSpec as P
    return [
        (r".*moe/router/kernel$", P(None, None)),
        (r".*moe/(w_gate|w_up)$", P("ep", "fsdp", "tp")),
        (r".*moe/w_down$", P("ep", "tp", "fsdp")),
    ]

"""Serving-tier fault tolerance (37-serving-resilience.md): the
drain ladder on the front end, mid-stream resume with the
exactly-once token contract, front-door hardening (429 cap, resume
exemption), the router prober's failure threshold + backoff, the
/v1/requests progress probe, shed-vs-drain interplay, and the three
seeded serving chaos drills end to end."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from batch_shipyard_tpu.chaos.serving_drill import _throttle
from batch_shipyard_tpu.models import serving
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.models.server import ServingFrontEnd

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    return model.init(jax.random.PRNGKey(7),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _front(params, step_delay=0.0, **kwargs):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    if step_delay:
        _throttle(engine, step_delay)
    return ServingFrontEnd(engine, port=0, **kwargs).start()


def _post_raw(url, payload, path="/v1/generate"):
    """POST without raising: (status, body-json, headers)."""
    req = urllib.request.Request(
        f"{url}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read()), dict(
                resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read()), dict(exc.headers)


def _get_raw(url, path):
    try:
        with urllib.request.urlopen(f"{url}{path}",
                                    timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


class _Stream(threading.Thread):
    """Background NDJSON streaming client: token lines, then the
    final object (a result, or an error marker)."""

    def __init__(self, url, spec):
        super().__init__(daemon=True)
        self.spec = dict(spec, stream=True)
        self.url = url
        self.tokens = []
        self.indexes = []
        self.final = None
        self.start()

    def run(self):
        req = urllib.request.Request(
            f"{self.url}/v1/generate",
            data=json.dumps(self.spec).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=120) as resp:
            for line in resp:
                event = json.loads(line)
                if "index" in event:
                    self.tokens.append(event["token"])
                    self.indexes.append(event["index"])
                else:
                    self.final = event

    def await_tokens(self, n, timeout=30.0):
        deadline = time.monotonic() + timeout
        while len(self.tokens) < n:
            assert time.monotonic() < deadline, \
                f"no {n} tokens within {timeout}s"
            time.sleep(0.02)


# ---------------------------- drain ladder -----------------------------

def test_drain_refuses_admissions_and_healthz_reports(params):
    front = _front(params)
    try:
        front.drain(grace_s=5.0, reason="test")
        assert front.draining
        status, body, headers = _post_raw(
            front.url, {"prompt": [1, 2], "max_new_tokens": 2})
        assert status == 503 and body.get("draining") is True
        assert "Retry-After" in headers
        status, body = _get_raw(front.url, "/healthz")
        assert status == 503 and body.get("draining") is True
        assert front.drain_rejections >= 1
        # Idempotent: a second notice must not reset the deadline.
        deadline = front._drain_deadline
        front.drain(grace_s=99.0, reason="again")
        assert front._drain_deadline == deadline
    finally:
        front.shutdown()


def test_drain_abandons_actives_and_evicts_queued(params):
    front = _front(params, step_delay=0.05, drain_grace_s=0.2)
    try:
        # Two actives occupy both slots; the third waits in line.
        actives = [_Stream(front.url,
                           {"request_id": f"drain-a{i}",
                            "prompt": [3 + i, 7], "max_new_tokens": 50})
                   for i in range(2)]
        for s in actives:
            s.await_tokens(2)
        queued = _Stream(front.url, {"request_id": "drain-q",
                                     "prompt": [9, 4],
                                     "max_new_tokens": 50})
        deadline = time.monotonic() + 30
        while True:
            status, body = _get_raw(front.url,
                                    "/v1/requests/drain-q")
            if status == 200 and body["phase"] == "queued":
                break
            assert time.monotonic() < deadline, \
                "third request never reached the wait line"
            time.sleep(0.02)
        front.drain(reason="test")
        for s in actives + [queued]:
            s.join(timeout=30)
            assert not s.is_alive()
        # 50 tokens x 50ms/step cannot finish inside the 0.2s grace:
        # actives were abandoned mid-decode with the draining marker
        # (the router's signal to resume on a sibling).
        for s in actives:
            assert s.final is not None
            assert s.final.get("draining") is True
            assert 0 < len(s.tokens) < 50
        # The queued request never decoded: evicted immediately.
        assert queued.final is not None
        assert queued.final.get("draining") is True
        assert queued.tokens == []
    finally:
        front.shutdown()


def test_arm_preempt_drain_fires_on_notice(params, tmp_path):
    from batch_shipyard_tpu.agent import preemption
    notice = str(tmp_path / "preempt.json")
    front = _front(params)
    try:
        assert front.arm_preempt_drain(path=notice, grace_s=1.0,
                                       poll_interval=0.02)
        assert not front.draining
        preemption.write_request(notice, reason="test notice")
        deadline = time.monotonic() + 10
        while not front.draining:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        assert "test notice" in front._drain_reason
    finally:
        front.shutdown()


# ----------------- mid-stream resume / exactly-once --------------------

def test_resume_reprefill_is_byte_identical(params):
    prompt, n = [5, 17, 31, 2], 8
    front = _front(params)
    try:
        _status, ref, _ = _post_raw(
            front.url, {"prompt": prompt, "max_new_tokens": n})
    finally:
        front.shutdown()
    # A sibling re-prefills prompt + the tokens the dead replica
    # already emitted; global indexes continue where they left off
    # and the assembled stream equals the unbroken reference.
    sibling = _front(params)
    try:
        resume = ref["tokens"][:3]
        client = _Stream(sibling.url,
                         {"request_id": ref["request_id"],
                          "prompt": prompt, "max_new_tokens": n,
                          "resume_tokens": resume})
        client.join(timeout=60)
        assert client.final is not None
        assert "error" not in client.final
        assert client.indexes == list(range(3, n))
        assert resume + client.tokens == ref["tokens"]
        assert client.final["tokens"] == ref["tokens"]
    finally:
        sibling.shutdown()


def test_resume_rejected_while_in_flight_then_replays(params):
    """The racing-resume regression: a resume for an id still
    decoding must be refused (400, not a second decode), and a
    resume after completion must replay the cached result without
    touching the engine — exactly one decode ever happens."""
    front = _front(params, step_delay=0.05)
    try:
        spec = {"request_id": "race-1", "prompt": [8, 3],
                "max_new_tokens": 20}
        live = _Stream(front.url, spec)
        live.await_tokens(2)
        status, body, _ = _post_raw(
            front.url, dict(spec, resume_tokens=live.tokens[:1]))
        assert status == 400 and "in flight" in body["error"]
        live.join(timeout=60)
        assert live.final is not None and "error" not in live.final
        assert front.stats()["completed_requests"] == 1
        # Completed: two racing resumes both replay the SAME cached
        # tokens (the _recent_results lookup wins before the
        # in-flight admission under one lock), decode count frozen.
        results = []

        def _resume():
            results.append(_post_raw(
                front.url, dict(spec,
                                resume_tokens=live.final["tokens"][:4])))

        racers = [threading.Thread(target=_resume) for _ in range(2)]
        for t in racers:
            t.start()
        for t in racers:
            t.join(timeout=60)
        assert len(results) == 2
        for status, body, _ in results:
            assert status == 200
            assert body["tokens"] == live.final["tokens"]
        assert front.stats()["completed_requests"] == 1
        # A fresh id: two racing resume admissions — exactly one
        # wins the in-flight slot and decodes; the loser is refused,
        # never a second concurrent decode of the same stream.
        fresh = {"request_id": "race-2", "prompt": [4, 12],
                 "max_new_tokens": 24, "resume_tokens": [19, 3]}
        results.clear()
        racers = [threading.Thread(
            target=lambda: results.append(
                _post_raw(front.url, fresh))) for _ in range(2)]
        for t in racers:
            t.start()
        for t in racers:
            t.join(timeout=60)
        codes = sorted(r[0] for r in results)
        assert codes == [200, 400], codes
        loser = next(r for r in results if r[0] == 400)
        assert "in flight" in loser[1]["error"]
        assert front.stats()["completed_requests"] == 2
    finally:
        front.shutdown()


# ------------------------- shed-vs-drain interplay ---------------------

def test_shed_suspended_while_draining_and_resumed_exempt(params):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64,
                                       slo_shed_grace_ms=1.0)
    shed_ids = []
    engine.on_shed = lambda rid, why: shed_ids.append(rid)
    expired = serving.Request("shed-me", [1, 2], 8,
                              ttft_target_ms=0.01)
    resumed = serving.Request("resumed", [1, 2], 8,
                              ttft_target_ms=0.01)
    engine.submit(expired)
    engine.submit(resumed, resumed=[5])
    far_future = time.monotonic() + 60.0
    # Draining owns the queue: nothing is shed out from under the
    # router's failover, however blown the deadlines are.
    engine.draining = True
    engine._shed_expired(far_future)
    assert engine.slo_sheds == 0 and not shed_ids
    # Not draining: the expired fresh request sheds, but the resumed
    # entry is exempt (its first token already shipped — shedding it
    # would discard delivered work).
    engine.draining = False
    engine._shed_expired(far_future)
    assert shed_ids == ["shed-me"]
    assert [e.request.request_id for e in engine._queue] == ["resumed"]


# ------------------------- front-door hardening ------------------------

def test_max_inflight_429_and_resume_exempt(params):
    front = _front(params, step_delay=0.05, max_inflight=1)
    try:
        live = _Stream(front.url, {"request_id": "cap-live",
                                   "prompt": [2, 9],
                                   "max_new_tokens": 30})
        live.await_tokens(1)
        status, body, _ = _post_raw(
            front.url, {"request_id": "cap-extra", "prompt": [4],
                        "max_new_tokens": 2})
        assert status == 429 and "cap" in body["error"]
        # A recovery resume must not bounce off the cap it is
        # trying to drain.
        status, body, _ = _post_raw(
            front.url, {"request_id": "cap-resume", "prompt": [6, 1],
                        "max_new_tokens": 4, "resume_tokens": [11]})
        assert status == 200 and len(body["tokens"]) == 4
        live.join(timeout=60)
    finally:
        front.shutdown()


def test_request_status_reports_phase_and_progress(params):
    front = _front(params, step_delay=0.05)
    try:
        live = _Stream(front.url, {"request_id": "probe-1",
                                   "prompt": [7, 2],
                                   "max_new_tokens": 20})
        live.await_tokens(2)
        status, body = _get_raw(front.url, "/v1/requests/probe-1")
        assert status == 200
        assert body["phase"] == "decode"
        assert body["emitted_tokens"] >= 2
        live.join(timeout=60)
        status, _body = _get_raw(front.url, "/v1/requests/probe-1")
        assert status == 404
    finally:
        front.shutdown()


# ------------------------- router prober backoff -----------------------

def test_prober_failure_threshold_backoff_and_metric(params):
    from batch_shipyard_tpu.models.router import ServingRouter
    fronts = [_front(params) for _ in range(2)]
    router = None
    try:
        router = ServingRouter([f.url for f in fronts],
                               health_interval=0.05,
                               probe_timeout=1.0,
                               probe_failure_threshold=2).start()
        victim = fronts[1]
        victim.kill()
        replica = router._replicas[1]
        deadline = time.monotonic() + 20
        while replica.consecutive_failures <= 2:
            assert time.monotonic() < deadline, \
                "prober never crossed the failure threshold"
            router._probe(replica)
            time.sleep(0.01)
        assert not replica.healthy
        # healthy->unhealthy is counted ONCE per transition, not per
        # failed probe.
        assert replica.unhealthy_total == 1
        # Past the threshold the re-probe cadence backs off
        # exponentially (capped); a healthy replica keeps the base
        # cadence.
        assert router._probe_delay(replica) > 0.05
        assert router._probe_delay(router._replicas[0]) == 0.05
        metrics = urllib.request.urlopen(
            f"{router.url}/metrics", timeout=10).read().decode()
        assert "shipyard_router_replica_unhealthy_total" in metrics
        assert 'unhealthy_total{replica="%s"} 1' % victim.url \
            in metrics
    finally:
        if router is not None:
            router.shutdown()
        fronts[0].shutdown()


# ----------------------------- the drills ------------------------------

def test_replica_drain_drill_end_to_end():
    from batch_shipyard_tpu.chaos import serving_drill
    report = serving_drill.run_replica_drain_drill(seed=1)
    assert report["invariants"]["ok"]
    assert report["invariants"]["recoveries"] >= 1
    assert report["goodput"]["badput_seconds"]["serving_recovery"] > 0


def test_router_restart_drill_end_to_end():
    from batch_shipyard_tpu.chaos import serving_drill
    report = serving_drill.run_router_restart_drill(seed=1)
    assert report["invariants"]["ok"]
    assert report["invariants"]["resumed_clients"] >= 1


@pytest.mark.slow
def test_replica_kill_drill_end_to_end():
    from batch_shipyard_tpu.chaos import serving_drill
    report = serving_drill.run_replica_kill_drill(seed=1)
    assert report["invariants"]["ok"]
    assert report["invariants"]["recoveries"] >= 1

"""Serving fleet router (VERDICT r4 next #6): queue-depth-aware
dispatch across replica front ends, health-check rotation, failover,
sticky cancel, streaming passthrough, and loadgen-through-router."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from batch_shipyard_tpu.models import loadgen, serving
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.models.router import ServingRouter
from batch_shipyard_tpu.models.server import ServingFrontEnd

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    return model.init(jax.random.PRNGKey(7),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _front(params):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    return ServingFrontEnd(engine, port=0).start()


@pytest.fixture()
def fleet(params):
    fronts = [_front(params), _front(params)]
    router = ServingRouter([f.url for f in fronts],
                           health_interval=0.2).start()
    yield router, fronts
    router.shutdown()
    for f in fronts:
        try:
            f.shutdown()
        except Exception:
            pass


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        f"{url}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(url, path):
    with urllib.request.urlopen(f"{url}{path}", timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_router_dispatches_and_balances(fleet):
    router, fronts = fleet
    seen = set()
    for k in range(4):
        out = _post(router.url, {"prompt": [1 + k, 2, 3],
                                 "max_new_tokens": 3})
        assert out["num_tokens"] == 3
        seen.add(out["_replica"])
    # Sequential idle-fleet requests alternate via the dispatched
    # tie-break: both replicas must have served.
    assert seen == {f.url for f in fronts}
    status, stats = _get(router.url, "/v1/stats")
    assert status == 200
    assert stats["completed"] == 4
    assert stats["healthy_replicas"] == 2
    assert all(s["completed"] >= 1 for s in stats["per_replica"])


def test_router_prefers_less_loaded_replica(fleet):
    router, _fronts = fleet
    # Occupy one replica with a long generation; concurrent short
    # requests must land on the other.
    long_done = {}

    def _long():
        long_done["r"] = _post(router.url, {
            "request_id": "long-run", "prompt": [9, 9, 9],
            "max_new_tokens": 40})

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    # Wait until the router has the long run in flight.
    deadline = time.monotonic() + 20
    busy_url = None
    while time.monotonic() < deadline and busy_url is None:
        for snap in router.replicas():
            if snap["inflight"] > 0:
                busy_url = snap["url"]
        time.sleep(0.01)
    assert busy_url is not None
    short = _post(router.url, {"prompt": [4, 5], "max_new_tokens": 2})
    assert short["_replica"] != busy_url
    t.join(120)
    assert long_done["r"]["num_tokens"] == 40


def test_router_health_failover_and_503(fleet):
    router, fronts = fleet
    fronts[1].shutdown()
    # Next probe cycle marks it unhealthy.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and router.healthy_count() != 1:
        time.sleep(0.05)
    assert router.healthy_count() == 1
    status, health = _get(router.url, "/healthz")
    assert status == 200 and health["healthy_replicas"] == 1
    # All traffic now goes to the survivor.
    for _ in range(3):
        out = _post(router.url, {"prompt": [1, 2],
                                 "max_new_tokens": 2})
        assert out["_replica"] == fronts[0].url
    fronts[0].shutdown()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and router.healthy_count():
        time.sleep(0.05)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(router.url, {"prompt": [1], "max_new_tokens": 1})
    assert exc.value.code == 503


def test_router_dispatch_failover_marks_unhealthy(fleet, params):
    """A replica that dies between probes: the dispatch itself fails
    over and flags it."""
    router, fronts = fleet
    victim = fronts[1]
    victim.shutdown()  # dies silently; probe hasn't run yet
    with router._lock:
        for r in router._replicas:
            r.healthy = True  # simulate stale healthy state
    for _ in range(4):
        out = _post(router.url, {"prompt": [3, 1],
                                 "max_new_tokens": 2})
        assert out["_replica"] == fronts[0].url
    snaps = {s["url"]: s for s in router.replicas()}
    assert snaps[victim.url]["healthy"] is False


def test_router_sticky_cancel(fleet):
    router, _fronts = fleet
    result = {}

    def _long():
        try:
            result["r"] = _post(router.url, {
                "request_id": "cancel-me", "prompt": [7, 7],
                "max_new_tokens": 60})
        except urllib.error.HTTPError as exc:
            result["code"] = exc.code
            result["body"] = json.loads(exc.read())

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            "cancel-me" not in router._owner:
        time.sleep(0.01)
    req = urllib.request.Request(
        f"{router.url}/v1/requests/cancel-me", method="DELETE")
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 202
    t.join(60)
    # The replica completes the waiter with 409 cancelled.
    assert result.get("code") == 409
    assert "cancelled" in result["body"]["error"]


def test_router_broadcast_cancel_finds_unknown_owner(fleet):
    """A request the router never dispatched (server-assigned or
    submitted directly to a replica): broadcast probes replicas —
    non-owners 404, the owner 202s."""
    router, fronts = fleet
    result = {}

    def _long():
        try:
            result["r"] = _post(fronts[1].url, {
                "request_id": "direct-long", "prompt": [8, 8],
                "max_new_tokens": 60})
        except urllib.error.HTTPError as exc:
            result["code"] = exc.code

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            not fronts[1].knows("direct-long"):
        time.sleep(0.01)
    assert "direct-long" not in router._owner
    code, payload = router.cancel("direct-long")
    assert code == 202, payload
    t.join(60)
    assert result.get("code") == 409
    # A fully unknown id 404s everywhere.
    code, payload = router.cancel("never-existed")
    assert code == 404


def test_router_rejects_duplicate_inflight_request_id(fleet):
    """A retry of a live id must not land on the OTHER replica and
    decode twice — the router gates ids fleet-wide (the per-replica
    front end can only see its own)."""
    router, _fronts = fleet
    result = {}

    def _long():
        result["r"] = _post(router.url, {
            "request_id": "dup-id", "prompt": [6, 6],
            "max_new_tokens": 50})

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            "dup-id" not in router._owner:
        time.sleep(0.01)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(router.url, {"request_id": "dup-id", "prompt": [1],
                           "max_new_tokens": 1})
    assert exc.value.code == 400
    assert "in flight" in json.loads(exc.value.read())["error"]
    t.join(120)
    assert result["r"]["num_tokens"] == 50
    # After completion the id is reusable.
    out = _post(router.url, {"request_id": "dup-id", "prompt": [2],
                             "max_new_tokens": 1})
    assert out["num_tokens"] == 1


def test_router_timeout_orphans_and_reconciles(params):
    """A dispatch that outlives request_timeout: 504 to the caller,
    NO re-dispatch (the run may still be live), the id stays gated
    until the health loop sees the replica forget it."""
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    # Deterministic slowness: every engine step pays a fixed delay,
    # so a 50-token decode is guaranteed to outlive the 2s timeout.
    orig_step = engine.step
    engine.step = lambda: (time.sleep(0.1), orig_step())[1]
    fronts = [ServingFrontEnd(engine, port=0).start()]
    router = ServingRouter([fronts[0].url], health_interval=0.2,
                           request_timeout=2.0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(router.url, {"request_id": "slow", "prompt": [3, 3],
                               "max_new_tokens": 50})
        assert exc.value.code == 504
        # Still owned: a retry is refused while the run may be live.
        assert "slow" in router._owner
        with pytest.raises(urllib.error.HTTPError) as exc2:
            _post(router.url, {"request_id": "slow", "prompt": [1],
                               "max_new_tokens": 1})
        assert exc2.value.code == 400
        # Once the replica finishes (or we cancel) and forgets the
        # id, reconciliation releases it.
        fronts[0].cancel("slow")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                "slow" in router._owner:
            time.sleep(0.05)
        assert "slow" not in router._owner
        out = _post(router.url, {"request_id": "slow", "prompt": [2],
                                 "max_new_tokens": 1})
        assert out["num_tokens"] == 1
    finally:
        router.shutdown()
        fronts[0].shutdown()


def test_router_streaming_passthrough(fleet):
    router, _fronts = fleet
    req = urllib.request.Request(
        f"{router.url}/v1/generate",
        data=json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 4,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(line) for line in resp if line.strip()]
    tokens = [ln for ln in lines if "token" in ln]
    finals = [ln for ln in lines if "tokens" in ln]
    assert len(tokens) == 4
    assert len(finals) == 1 and finals[0]["num_tokens"] == 4


def test_loadgen_through_router(fleet):
    router, _fronts = fleet
    report = loadgen.run_load(router.url, num_requests=8,
                              rate_hz=50.0, prompt_len=(2, 6),
                              max_new_tokens=(2, 5), vocab_size=97,
                              seed=3)
    assert report["completed"] == 8
    assert report["failed"] == 0
    assert report["generated_tokens"] > 0
    status, stats = _get(router.url, "/v1/stats")
    assert stats["completed"] >= 8

def test_prometheus_metrics_endpoints(fleet):
    """Front end and router expose Prometheus text metrics the
    monitoring stack can scrape (docs/09-monitoring.md)."""
    router, fronts = fleet
    _post(router.url, {"prompt": [4, 2], "max_new_tokens": 3})

    def scrape(url):
        with urllib.request.urlopen(f"{url}/metrics",
                                    timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/plain")
            return resp.read().decode()

    front_text = scrape(fronts[0].url)
    assert "shipyard_serving_completed_requests_total" in front_text
    assert 'shipyard_serving_ttft_ms{quantile="0.50"}' in front_text
    router_text = scrape(router.url)
    assert "shipyard_router_healthy_replicas 2" in router_text
    assert "shipyard_router_dispatched_total 1" in router_text
    assert ('shipyard_router_replica_healthy{replica="'
            + fronts[0].url + '"} 1') in router_text
    # Every line is NAME{labels} VALUE or NAME VALUE (parseable).
    for line in router_text.strip().splitlines():
        name, value = line.rsplit(" ", 1)
        float(value)

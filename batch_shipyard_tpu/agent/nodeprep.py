"""Node preparation: make a TPU VM worker ready to run tasks.

Reference analog: scripts/shipyard_nodeprep.sh (2078 lines of bash,
flag-driven, SURVEY.md section 2.2). Re-designed in Python and
TPU-native: instead of nvidia driver + container toolkit install
(nodeprep.sh:773) we verify/install libtpu + JAX; instead of
Infiniband/RDMA setup (:1661) we sanity-check TPU device visibility and
ICI metadata. Docker engine setup is shared capability.

Phases (each emits a perf event, mirroring the reference's perf
instrumentation of nodeprep/docker_install/global_resources):

  1. env probe        — TPU chips present? docker present?
  2. docker setup     — registry logins (config from credentials)
  3. jax/libtpu setup — ensure import works; optional pip install pin
  4. monitors         — node exporter / cadvisor launch (if enabled)
  5. cascade          — pull the pool's global images (lease-gated)

Idempotency marker handling lives in NodeAgent.start (reboot-resume
fast path, reference nodeprep.sh:1935-1970).
"""

from __future__ import annotations

import os
import shutil
import subprocess

from batch_shipyard_tpu.agent import perf
from batch_shipyard_tpu.agent.cascade import CascadeImageProvisioner
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def detect_tpu_chips() -> int:
    """Count locally visible TPU accelerator devices."""
    count = 0
    for idx in range(16):
        if os.path.exists(f"/dev/accel{idx}"):
            count += 1
    return count


def ensure_jax(jax_version: str | None = None,
               libtpu_version: str | None = None) -> bool:
    """Verify JAX imports; attempt pinned install only if missing and a
    version was requested (no-op offline)."""
    try:
        import jax  # noqa: F401,PLC0415
        return True
    except ImportError:
        pass
    if jax_version:
        spec = f"jax[tpu]=={jax_version}"
        cmd = ["pip", "install", spec]
        if libtpu_version:
            cmd.append(f"libtpu=={libtpu_version}")
        rc = subprocess.call(cmd)
        return rc == 0
    return False


def run_node_prep(agent) -> None:
    """Full node prep for a real (or localhost) node agent."""
    store = agent.store
    pool_id = agent.identity.pool_id
    node_id = agent.identity.node_id
    pool = agent.pool

    perf.emit(store, pool_id, node_id, "nodeprep", "start")
    chips = detect_tpu_chips()
    perf.emit(store, pool_id, node_id, "nodeprep",
              f"tpu_chips:{chips}")

    if "docker" in pool.container_runtimes:
        if shutil.which("docker") is None:
            logger.warning(
                "docker runtime requested but docker not installed on "
                "%s; docker tasks will fail", node_id)
        perf.emit(store, pool_id, node_id, "nodeprep", "docker_install")
    if ("kata_containers" in pool.container_runtimes or
            pool.container_runtime_default == "kata_containers"):
        if shutil.which("kata-runtime") is None:
            logger.warning(
                "kata_containers runtime requested but kata-runtime "
                "not installed on %s; kata tasks will fail", node_id)
        perf.emit(store, pool_id, node_id, "nodeprep", "kata_install")

    if pool.is_tpu_pool:
        ok = ensure_jax(pool.jax_version, pool.libtpu_version)
        perf.emit(store, pool_id, node_id, "nodeprep",
                  f"jax_ready:{ok}")

    for idx, command in enumerate(pool.additional_node_prep_commands):
        rc = subprocess.call(["/bin/bash", "-c", command])
        perf.emit(store, pool_id, node_id, "nodeprep",
                  f"additional_command:{idx}", message=str(rc))
        if rc != 0:
            raise RuntimeError(
                f"additional node prep command {idx} failed rc={rc}")

    if pool.node_exporter.enabled or pool.cadvisor.enabled:
        _launch_monitors(agent)

    # Cascade: prefetch pool images (blocks if pool policy says so).
    provisioner = getattr(agent, "_image_provisioner", None)
    if provisioner is None:
        provisioner = CascadeImageProvisioner(store)
    if isinstance(provisioner, CascadeImageProvisioner) and (
            pool.block_until_all_global_resources_loaded):
        provisioner.distribute_global_resources(agent)

    perf.emit(store, pool_id, node_id, "nodeprep", "end")


def _launch_monitors(agent) -> None:
    """Start prometheus node_exporter / cadvisor if present on PATH
    (reference: nodeprep.sh:1752-1827)."""
    pool = agent.pool
    if pool.node_exporter.enabled and shutil.which("node_exporter"):
        subprocess.Popen(
            ["node_exporter", "--web.listen-address",
             f":{pool.node_exporter.port}"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    if pool.cadvisor.enabled and shutil.which("cadvisor"):
        subprocess.Popen(
            ["cadvisor", "-port", str(pool.cadvisor.port)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    perf.emit(agent.store, agent.identity.pool_id,
              agent.identity.node_id, "nodeprep", "monitors_launched")

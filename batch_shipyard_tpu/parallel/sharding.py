"""Parameter/activation sharding rules: how models map onto the mesh.

The scaling-book recipe: pick a mesh (parallel/mesh.py), annotate
shardings (this module), let XLA insert the collectives. Rules are
path-pattern based so the model code stays sharding-agnostic.

Transformer (Megatron-style tensor parallel over 'tp', optional fsdp
over 'fsdp'):
  - q/k/v/gate/up projections: columns over tp  -> P(fsdp?, 'tp')
  - o/down projections:        rows over tp     -> P('tp', fsdp?)
  - embedding:                 vocab over tp    -> P('tp', fsdp?)
  - norms/scales: replicated
Activations: batch over (dp, fsdp), sequence over sp.

ResNet: pure data parallel (convs don't tensor-parallelize profitably
at this scale) — all params replicated, batch over every mesh axis.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_TRANSFORMER_RULES: list[tuple[str, P]] = [
    (r".*(q_proj|k_proj|v_proj|gate_proj|up_proj)/kernel$",
     P("fsdp", "tp")),
    # Fused-norm path (models/transformer.py fused_norm): the merged
    # qkv / gate-up projections are column-sharded like their unfused
    # counterparts.
    (r".*(qkv_kernel|gate_up_kernel)$", P("fsdp", "tp")),
    (r".*(o_proj|down_proj)/kernel$", P("tp", "fsdp")),
    (r".*embed/embedding$", P("tp", "fsdp")),
    # MoE: experts over ep, expert-internal dims over fsdp/tp.
    (r".*moe/router/kernel$", P()),
    (r".*moe/(w_gate|w_up)$", P("ep", "fsdp", "tp")),
    (r".*moe/w_down$", P("ep", "tp", "fsdp")),
    (r".*(scale|bias)$", P()),
]


def _path_str(path) -> str:
    parts = []
    for key in path:
        if hasattr(key, "key"):
            parts.append(str(key.key))
        elif hasattr(key, "idx"):
            parts.append(str(key.idx))
        else:
            parts.append(str(key))
    return "/".join(parts)


def transformer_param_specs(params) -> Any:
    """PartitionSpec pytree for TransformerLM params."""
    def rule(path, leaf):
        path_s = _path_str(path)
        for pattern, spec in _TRANSFORMER_RULES:
            if re.match(pattern, path_s):
                return spec
        return P()
    return jax.tree_util.tree_map_with_path(rule, params)


def replicated_specs(params) -> Any:
    return jax.tree_util.tree_map(lambda _: P(), params)


def to_shardings(mesh: Mesh, spec_tree) -> Any:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), spec_tree,
        is_leaf=lambda s: isinstance(s, P))


def place(mesh: Mesh, tree, spec_tree):
    """Device-put a pytree according to a spec tree."""
    shardings = to_shardings(mesh, spec_tree)
    return jax.device_put(tree, shardings)


# ------------------------- reshard on restore ---------------------------

def place_like(template, tree):
    """Re-lay-out ``tree``'s leaves onto ``template``'s shardings and
    dtypes (host round trip: works for ANY source layout, including
    plain numpy and int8-quantized leaves — the dtype is preserved
    bit-for-bit, never promoted through float). On a multi-host mesh
    the leaf is assembled per-shard (make_array_from_callback), so
    each process materializes ONLY its addressable shards on device —
    device_put of a full array against a sharding spanning
    non-addressable devices is not a thing."""
    import numpy as np

    def _place(t, v):
        if not hasattr(t, "sharding") or not hasattr(v, "shape"):
            return v
        arr = np.asarray(v)
        if tuple(arr.shape) != tuple(t.shape):
            raise ValueError(
                f"reshard-on-restore shape mismatch: checkpoint leaf "
                f"{arr.shape} vs template {t.shape} — global shapes "
                f"are mesh-independent, so this checkpoint belongs "
                f"to a different model config")
        if arr.dtype != t.dtype:
            arr = arr.astype(t.dtype)
        sharding = t.sharding
        if getattr(sharding, "is_fully_addressable", True):
            return jax.device_put(arr, sharding)
        return jax.make_array_from_callback(
            tuple(arr.shape), sharding, lambda idx: arr[idx])

    return jax.tree_util.tree_map(_place, template, tree)


def host_restore_plan(params_template, opt_state_template=None,
                      devices=None):
    """Per-host restore plan: for each sharded leaf of the templates,
    the unique global index slices the given device set needs —
    ``devices=None`` means THIS process's addressable devices (the
    real multi-host case); an explicit device subset simulates one
    virtual host of an M-host mesh on a single-process CPU pod (how
    the plan is exercised in tests without silicon).

    Returns ``{"leaves": [...], "read_fraction": float}`` where each
    leaf entry carries path/shape/dtype, its normalized slices, and
    its own read fraction; the top-level fraction is element-weighted
    — 1/M for an even M-way resize, 1.0 when the plan degenerates to
    the full-array restore. The pure 1-D contiguous math lives in
    parallel/restore_plan.py (shared with the jax-free drill probe);
    this function derives the truth from the actual jax index maps,
    so any sharding — nested axes included — plans correctly."""
    template = {"params": params_template}
    if opt_state_template is not None:
        template["opt_state"] = opt_state_template
    leaves = []
    total = 0
    needed_total = 0
    flat = jax.tree_util.tree_flatten_with_path(template)[0]
    for path, leaf in flat:
        if not hasattr(leaf, "sharding") or not hasattr(leaf, "shape"):
            continue
        shape = tuple(leaf.shape)
        sharding = leaf.sharding
        if devices is None:
            index_values = list(
                sharding.addressable_devices_indices_map(
                    shape).values())
        else:
            wanted = set(devices)
            index_values = [
                idx for dev, idx in
                sharding.devices_indices_map(shape).items()
                if dev in wanted]
        unique: dict[tuple, tuple] = {}
        for idx in index_values:
            norm = tuple(
                (s.start or 0,
                 shape[d] if s.stop is None else s.stop)
                for d, s in enumerate(idx))
            unique[norm] = norm
        size = 1
        for dim in shape:
            size *= dim
        needed = sum(
            _prod(hi - lo for lo, hi in norm)
            for norm in unique.values())
        leaves.append({
            "path": _path_str(path), "shape": shape,
            "dtype": str(leaf.dtype),
            "slices": sorted(unique.values()),
            "read_fraction": needed / size if size else 1.0,
        })
        total += size
        needed_total += needed
    return {"leaves": leaves,
            "read_fraction": (needed_total / total if total
                              else 1.0)}


def _prod(values) -> int:
    out = 1
    for value in values:
        out *= max(0, value)
    return out


def reshard_on_restore(checkpoint_dir: str, params_template,
                       opt_state_template, per_host=None):
    """Elastic resume: load the latest COMMITTED checkpoint — saved
    at mesh size N — and re-shard params/opt-state onto the
    templates' mesh (size M). Returns (params, opt_state, step) or
    None when nothing is committed.

    Two mechanisms, chosen by host count:

    * **Per-host** (``per_host=True``, the default on a multi-host
      mesh): restore_args are built from the TARGET templates'
      shardings, so Orbax/TensorStore reads, on each host, only the
      checkpoint chunks that host's addressable devices need — the
      restore plan (``host_restore_plan``) is logged so the IO claim
      is inspectable. An N-host gang re-forms at M hosts without any
      host paying N-host restore IO (or RAM). Falls back to the
      host-side path below if this Orbax version refuses the
      cross-mesh sharded restore.
    * **Host-side** (single host): full arrays are restored against
      shape/dtype templates (no device shardings handed to Orbax —
      the checkpoint's layout metadata may describe a mesh that no
      longer exists), then laid out onto the M-mesh shardings the
      templates carry; ``place_like`` assembles per-shard on
      non-fully-addressable meshes.

    Global shapes are mesh-independent, so N->M needs no tensor
    surgery — only a re-placement. The equivalence oracle
    (tests/test_reshard_restore) pins the contract: a resume-at-M
    loss trajectory matches a fresh-at-M run restored from the same
    step."""
    import numpy as np

    from batch_shipyard_tpu.goodput import events as goodput_events
    from batch_shipyard_tpu.trace import spans as trace_spans
    from batch_shipyard_tpu.workloads import checkpoint as ckpt_mod

    step = ckpt_mod.latest_step(checkpoint_dir)
    if step is None:
        return None
    path = ckpt_mod._step_path(checkpoint_dir, step)
    template = {"params": params_template,
                "opt_state": opt_state_template, "step": step}
    import orbax.checkpoint as ocp
    if per_host is None:
        per_host = jax.process_count() > 1
    if per_host:
        plan = host_restore_plan(params_template, opt_state_template)
        logger.info(
            "per-host reshard-on-restore of step %d: this host reads "
            "%.1f%% of the checkpoint bytes (%d sharded leaves)",
            step, 100.0 * plan["read_fraction"],
            len(plan["leaves"]))
        try:
            with goodput_events.phase(
                    goodput_events.PROGRAM_CHECKPOINT_RESTORE,
                    step=step, resharded=True, per_host=True), \
                    trace_spans.phase(trace_spans.SPAN_CKPT_RESTORE,
                                      step=step, resharded=True,
                                      per_host=True):
                restored = ckpt_mod._checkpointer().restore(
                    path, item=template,
                    restore_args=(
                        ocp.checkpoint_utils.construct_restore_args(
                            template)))
            return (restored["params"], restored["opt_state"],
                    int(restored["step"]))
        except Exception as exc:  # noqa: BLE001 - orbax cross-mesh
            # support varies by version; the host-side path is the
            # recovery that works for all of them
            logger.warning(
                "per-host sharded restore of step %d failed (%s); "
                "falling back to the host-side full-array path",
                step, exc)

    def _host_leaf(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return np.zeros(leaf.shape, dtype=leaf.dtype)
        return leaf

    host_template = jax.tree_util.tree_map(_host_leaf, template)
    with goodput_events.phase(
            goodput_events.PROGRAM_CHECKPOINT_RESTORE, step=step,
            resharded=True), \
            trace_spans.phase(trace_spans.SPAN_CKPT_RESTORE,
                              step=step, resharded=True):
        restored = ckpt_mod._checkpointer().restore(
            path, item=host_template,
            restore_args=ocp.checkpoint_utils.construct_restore_args(
                host_template))
        params = place_like(params_template, restored["params"])
        opt_state = place_like(opt_state_template,
                               restored["opt_state"])
    return params, opt_state, int(restored["step"])

"""Table-driven classification of gcloud / Cloud TPU API errors.

Reference analog: the resize-error classification of
`/root/reference/convoy/batch.py:625-672` — Azure Batch surfaces a
typed `resize_errors` list; gcloud surfaces stderr text and JSON error
bodies, so the table below maps the payload shapes observed from real
`gcloud compute tpus tpu-vm create` / queued-resource failures onto a
stable taxonomy the pool manager can act on:

  kind    — quota | stockout | permission | invalid_argument |
            conflict | not_found | unavailable | internal | unknown
  fatal   — retrying the SAME request cannot succeed (config/auth
            error) — the reference's "fatal resize error" bucket
  retry   — suggested recovery: none | backoff | other_zone

Rules are ordered; first match wins. Matching is case-insensitive
substring over the combined stderr/JSON text — gcloud is not
consistent enough across versions for anything stricter, which is
exactly why the table (not scattered `in` checks) is the API and why
the test corpus pins real captured payloads
(tests/test_gcloud_errors.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class ErrorClass:
    kind: str
    fatal: bool
    retry: str           # none | backoff | other_zone
    rule: str            # the marker that matched (for logs)


# (marker, kind, fatal, retry) — ordered, first match wins.
_RULES: tuple[tuple[str, str, bool, str], ...] = (
    # Quota: permanent until the operator raises it.
    ("quota exceeded", "quota", True, "none"),
    ("quota_exceeded", "quota", True, "none"),
    ("exceeded quota", "quota", True, "none"),
    # Stockout/capacity: zone is dry; try elsewhere or wait. The
    # specific capacity phrasings come BEFORE the bare
    # RESOURCE_EXHAUSTED rule: GCP also returns RESOURCE_EXHAUSTED for
    # API rate limiting (HTTP 429), where other_zone would wrongly
    # abort the allocation — a bare status with no capacity wording
    # therefore backs off instead (advisor r2 finding #1).
    ("no more capacity in the zone", "stockout", False, "other_zone"),
    ("does not have enough resources available",
     "stockout", False, "other_zone"),
    ("stockout", "stockout", False, "other_zone"),
    ("not enough available capacity", "stockout", False, "other_zone"),
    ("insufficient capacity", "stockout", False, "other_zone"),
    ("resource_exhausted", "unavailable", False, "backoff"),
    # Config errors BEFORE the generic not-found rules: "Accelerator
    # type v5p-8 was not found" is a fatal config error, and the
    # generic "was not found" rule would otherwise classify it as a
    # non-fatal not_found and poll to timeout (advisor r2 finding #2).
    ("accelerator type .* not found", "invalid_argument", True,
     "none"),
    ("is not a valid accelerator-type", "invalid_argument", True,
     "none"),
    ("invalid value for field", "invalid_argument", True, "none"),
    ("unsupported runtime version", "invalid_argument", True, "none"),
    # Conflict / not-found / transient BEFORE the permission rules:
    # GCP conflates wording ("does not have permission ... or it may
    # not exist"), and a merely-mentioned "permission" must not brick
    # a pool when a more specific transient marker is present.
    ("already exists", "conflict", False, "none"),
    ("alreadyexists", "conflict", False, "none"),
    ("not_found", "not_found", False, "none"),
    ("was not found", "not_found", False, "none"),
    ("unavailable", "unavailable", False, "backoff"),
    ("service is currently unavailable", "unavailable", False,
     "backoff"),
    ("deadline_exceeded", "unavailable", False, "backoff"),
    ("deadline exceeded", "unavailable", False, "backoff"),
    ("connection reset", "unavailable", False, "backoff"),
    ("internal error", "internal", False, "backoff"),
    ("internal_error", "internal", False, "backoff"),
    ("rate limit", "unavailable", False, "backoff"),
    # Auth/permission: fatal, operator action required. Specific
    # phrasings only — a bare "permission" substring is too greedy.
    ("permission denied", "permission", True, "none"),
    ("permission_denied", "permission", True, "none"),
    ("permission '", "permission", True, "none"),
    ("does not have permission", "permission", True, "none"),
    ("request had insufficient authentication",
     "permission", True, "none"),
    ("unauthenticated", "permission", True, "none"),
    # Config errors: fatal, same request can never work. (The
    # specific phrasings live above the not-found rules; the bare
    # status string stays down here below the permission rules.)
    ("invalid_argument", "invalid_argument", True, "none"),
)


def classify(payload: str) -> ErrorClass:
    """Classify a gcloud failure payload (stderr text, JSON error
    body, or both concatenated)."""
    import re
    text = payload.lower()
    for marker, kind, fatal, retry in _RULES:
        if ".*" in marker:
            if re.search(marker, text):
                return ErrorClass(kind, fatal, retry, marker)
        elif marker in text:
            return ErrorClass(kind, fatal, retry, marker)
    return ErrorClass("unknown", False, "backoff", "")


def is_preemption_state(state: Optional[str]) -> bool:
    """Cloud TPU node states that mean the slice was taken away
    (spot/preemptible reclamation or maintenance) rather than deleted
    by us — the signal feeding slice-recreate recovery."""
    return (state or "").upper() in ("PREEMPTED", "TERMINATED",
                                     "SUSPENDED")

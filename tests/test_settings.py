"""Tests for typed settings accessors (reference analog: settings.py)."""

import pytest

from batch_shipyard_tpu.config import settings


POOL_CONF = {"pool_specification": {
    "id": "tpupool",
    "substrate": "tpu_vm",
    "tpu": {"accelerator_type": "v5litepod-16", "num_slices": 2},
    "task_slots_per_node": 2,
    "environment_variables": {"POOLVAR": "1"},
}}

JOBS_CONF = {"job_specifications": [{
    "id": "job1",
    "environment_variables": {"JOBVAR": "2"},
    "max_task_retries": 3,
    "tasks": [
        {"docker_image": "img", "command": "run",
         "environment_variables": {"TASKVAR": "3"}},
        {"singularity_image": "simg", "command": "run2"},
        {"command": "bare"},
    ],
}]}


def test_pool_settings_tpu():
    pool = settings.pool_settings(POOL_CONF)
    assert pool.id == "tpupool"
    assert pool.is_tpu_pool
    assert pool.tpu.workers_per_slice == 4
    assert pool.tpu.total_workers == 8
    assert pool.tpu.chips_per_worker == 4
    assert pool.is_gang_capable
    assert pool.current_node_count == 8


def test_pool_settings_non_tpu():
    conf = {"pool_specification": {
        "id": "cpupool",
        "vm_configuration": {
            "vm_size": "n2-standard-8",
            "vm_count": {"dedicated": 3, "low_priority": 2}},
    }}
    pool = settings.pool_settings(conf)
    assert not pool.is_tpu_pool
    assert pool.current_node_count == 5


def test_task_env_merge_pool_job_task():
    pool = settings.pool_settings(POOL_CONF)
    job = settings.job_settings_list(JOBS_CONF)[0]
    task = settings.task_settings(dict(job.tasks[0]), job, pool)
    assert task.environment_variables == {
        "POOLVAR": "1", "JOBVAR": "2", "TASKVAR": "3"}
    assert task.runtime == "docker"
    assert task.max_task_retries == 3
    assert task.tpu  # inherits pool TPU-ness


def test_task_runtime_inference():
    job = settings.job_settings_list(JOBS_CONF)[0]
    assert settings.task_settings(
        dict(job.tasks[1]), job).runtime == "singularity"
    assert settings.task_settings(dict(job.tasks[2]), job).runtime == "none"


def test_task_both_images_rejected():
    job = settings.job_settings_list(JOBS_CONF)[0]
    with pytest.raises(ValueError):
        settings.task_settings(
            {"docker_image": "a", "singularity_image": "b"}, job)


def test_multi_instance_resolution():
    pool = settings.pool_settings(POOL_CONF)
    job = settings.job_settings_list(JOBS_CONF)[0]
    task = settings.task_settings(
        {"command": "x", "multi_instance": {
            "num_instances": "pool_current_dedicated"}}, job, pool)
    assert task.is_multi_instance
    assert task.multi_instance.resolve_num_instances(pool) == 8
    assert task.multi_instance.jax_distributed.enabled


def test_credentials_defaults():
    creds = settings.credentials_settings({"credentials": {
        "storage": {"backend": "memory"}}})
    assert creds.storage.backend == "memory"
    assert creds.storage.prefix == "shipyardtpu"
    assert creds.gcp is None

"""Discrete-event fleet simulator: real policies, real pricing.

One event heap, one virtual clock (sim/clock.py), thousands of
``SimNode`` slots, and an arrival trace (sim/traces.py). Decisions —
which node claims, whether a cold node defers to a warm one, which
running task a starved high-priority task preempts, how many nodes
the fleet should hold — are made by the SHARED policy functions in
``sched/policy.py`` (the same code the live agent/autoscale paths
import). Every lifecycle edge emits the same goodput event dicts the
live system logs, and the final report is priced by the REAL engine
(``goodput.accounting.decompose_by_node``), so a policy's simulated
goodput delta is a statement about production decision code under
the production pricing model.

Determinism contract: same (seed, trace, policy) ⇒ byte-identical
report (tests/test_fleet_sim.py). Everything is a pure function of
the inputs — seeded RNGs only, heap ties broken by schedule order,
and zero wall-clock reads (the ``sim-wall-clock`` analyzer rule
bans them in this package outside clock.py).
"""

from __future__ import annotations

import hashlib
import heapq
import json
from collections import deque
from typing import Any, Optional

from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.goodput import events as ev
from batch_shipyard_tpu.sched import policy as sched_policy
from batch_shipyard_tpu.sim import clock as sim_clock
from batch_shipyard_tpu.sim.traces import SimTask

# Control-plane constants (virtual seconds): claim round trip, the
# defer-retry poll, the cooperative-drain notice latency, and the
# sweep cadence — fixed, not knobs: they model the substrate, not
# the policy under study.
CLAIM_LATENCY = 0.1
DEFER_RETRY_SECONDS = 1.0
NOTICE_LATENCY = 0.5
SWEEP_INTERVAL = 15.0
SWEEP_GRACE = 30.0
AUTOSCALE_TICK = 30.0


class SimNode:
    __slots__ = ("idx", "name", "up", "free", "health",
                 "fail_count", "warm", "pause_until", "born",
                 "retired_at")

    def __init__(self, idx: int, slots: int, born: float) -> None:
        self.idx = idx
        self.name = f"n{idx:05d}"
        self.up = True
        self.free = slots
        self.health = 1.0
        self.fail_count = 0
        self.warm: set = set()
        self.pause_until = 0.0
        self.born = born
        self.retired_at: Optional[float] = None


class _Running:
    __slots__ = ("task", "node", "attempt", "start_step",
                 "work_start", "drain_at", "preempt_pending")

    def __init__(self, task: SimTask, node: SimNode, attempt: int,
                 start_step: int, work_start: float) -> None:
        self.task = task
        self.node = node
        self.attempt = attempt
        self.start_step = start_step
        self.work_start = work_start
        self.drain_at: Optional[float] = None
        self.preempt_pending = False


class _Pending:
    __slots__ = ("task", "resume_step", "queue_since", "recovery",
                 "killed_at", "deferrals")

    def __init__(self, task: SimTask, resume_step: int = 0,
                 queue_since: Optional[float] = None,
                 recovery: Optional[str] = None,
                 killed_at: Optional[float] = None) -> None:
        self.task = task
        self.resume_step = resume_step
        self.queue_since = (task.arrival if queue_since is None
                            else queue_since)
        self.recovery = recovery  # None | "preempt" | "evict"
        self.killed_at = killed_at
        self.deferrals = 0


class FleetSimulator:
    """One simulation run. Build, ``run()``, read ``report()``."""

    def __init__(self, *, trace: list, nodes: int,
                 slots_per_node: int = 1,
                 policy: str = "baseline",
                 knobs: Optional[sched_policy.PolicyKnobs] = None,
                 injections: tuple = (),
                 autoscale: bool = False,
                 min_nodes: int = 1,
                 max_nodes: Optional[int] = None,
                 provision_seconds: float = 120.0,
                 horizon: Optional[float] = None) -> None:
        self.policy = sched_policy.POLICIES[policy] \
            if isinstance(policy, str) else policy
        self.knobs = knobs or sched_policy.PolicyKnobs()
        self.clock = sim_clock.VirtualClock()
        self.heap = sim_clock.EventHeap(self.clock)
        self.slots = max(1, slots_per_node)
        self.autoscale = autoscale
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes if max_nodes is not None else nodes
        self.provision_seconds = provision_seconds
        self.horizon = horizon
        self.nodes: list[SimNode] = [
            SimNode(i, self.slots, 0.0) for i in range(nodes)]
        self._free_heap: list = list(range(nodes))
        heapq.heapify(self._free_heap)
        # Max-index twin of the free heap: cold claims under the
        # affinity policy spread AWAY from the warm low-index core
        # (anti-affinity), so a freed warm node survives until its
        # identity's next task retries instead of being snatched as
        # the "best" cold node.
        self._free_heap_max: list = [-i for i in range(nodes)]
        heapq.heapify(self._free_heap_max)
        self._warm_free: dict[str, list] = {}
        self._warm_count: dict[str, int] = {}
        self.events: list[dict] = []
        self._pending: dict[int, deque] = {}   # priority -> deque
        self._running: dict[str, _Running] = {}
        self._attempts: dict[str, int] = {}
        # chaos state
        self._claim_freeze_until = 0.0
        self._claim_extra_latency = 0.0
        self._claim_backoff_until = 0.0
        self._sweep_frozen_until = 0.0
        self.metrics: dict[str, Any] = {
            "tasks_total": len(trace), "tasks_completed": 0,
            "queue_wait_total": 0.0, "queue_wait_max": 0.0,
            "deferrals": 0, "sweep_victims": 0, "preemptions": 0,
            "evictions": 0, "replayed_steps": 0, "kills": 0,
            "nodes_added": 0, "nodes_removed": 0,
        }
        for task in trace:
            self.heap.schedule(task.arrival, self._on_arrival,
                               _Pending(task))
        for inj in injections:
            self.heap.schedule(inj.at, self._on_injection, inj)
        self.heap.schedule(SWEEP_INTERVAL, self._on_sweep, None)
        if autoscale:
            self.heap.schedule(AUTOSCALE_TICK, self._on_autoscale,
                               None)

    # ------------------------- event emission -------------------------

    def _emit(self, kind: str, start: float, end: float,
              node: Optional[SimNode] = None,
              task_id: Optional[str] = None,
              **attrs) -> None:
        self.events.append({
            "kind": kind, "start": start, "end": end,
            "node_id": node.name if node is not None else None,
            "job_id": task_id, "task_id": task_id,
            "attrs": attrs or {}})

    # --------------------------- free index ---------------------------

    def _node_claimable(self, node: SimNode) -> bool:
        return (node.up and node.free > 0
                and node.pause_until <= self.clock.now)

    def _push_free(self, node: SimNode) -> None:
        heapq.heappush(self._free_heap, node.idx)
        heapq.heappush(self._free_heap_max, -node.idx)
        for identity in node.warm:
            heapq.heappush(
                self._warm_free.setdefault(identity, []), node.idx)

    def _pop_free(self, skip: Optional[int] = None,
                  coldest: bool = False) -> Optional[SimNode]:
        heap = self._free_heap_max if coldest else self._free_heap
        sign = -1 if coldest else 1
        stash = None
        while heap:
            idx = sign * heapq.heappop(heap)
            node = self.nodes[idx]
            if idx == skip:
                if stash is None and self._node_claimable(node):
                    stash = idx
                continue
            if self._node_claimable(node):
                if stash is not None:
                    heapq.heappush(heap, sign * stash)
                return node
        if stash is not None:
            heapq.heappush(heap, sign * stash)
        return None

    def _pop_warm_free(self, identity: str) -> Optional[SimNode]:
        heap = self._warm_free.get(identity)
        while heap:
            idx = heapq.heappop(heap)
            node = self.nodes[idx]
            if self._node_claimable(node) and identity in node.warm:
                return node
        return None

    # --------------------------- dispatch ----------------------------

    def _on_arrival(self, pend: _Pending) -> None:
        self._enqueue(pend)
        self._dispatch()

    def _enqueue(self, pend: _Pending) -> None:
        self._pending.setdefault(
            pend.task.priority, deque()).append(pend)

    def _claimable_now(self) -> bool:
        return self._claim_freeze_until <= self.clock.now

    def _dispatch(self) -> None:
        if not self._claimable_now():
            return
        while True:
            queue = None
            for priority in sorted(self._pending, reverse=True):
                if self._pending[priority]:
                    queue = self._pending[priority]
                    break
            if queue is None:
                return
            pend = queue[0]
            node, warm, score = self._pick_node(pend.task)
            if node is None:
                return
            queue.popleft()
            if self._maybe_defer(pend, node, warm, score):
                continue
            self._start(pend, node, warm)

    def _pick_node(self, task: SimTask) -> tuple:
        """(node, warm, score) via the SHARED claim-scoring policy:
        the best warm candidate and the best cold candidate are
        scored by sched_policy.claim_score and the cheaper claim
        wins (ties to the lower node index — deterministic)."""
        identity = task.cache_identity
        if not (self.policy.claim_scoring and identity):
            node = self._pop_free()
            if node is None:
                return None, False, 0.0
            return node, bool(identity) and identity in node.warm, 0.0
        warm_node = self._pop_warm_free(identity)
        cold_node = self._pop_free(
            skip=warm_node.idx if warm_node else None, coldest=True)
        best = None
        for node, warm in ((warm_node, True), (cold_node, False)):
            if node is None:
                continue
            score = sched_policy.claim_score(
                warm=warm, health=node.health,
                recent_failures=node.fail_count,
                has_identity=True, knobs=self.knobs)
            key = (score, node.idx)
            if best is None or key < best[0]:
                if best is not None:
                    self._push_free(best[1])
                best = (key, node, warm)
            else:
                self._push_free(node)
        if best is None:
            return None, False, 0.0
        return best[1], best[2], best[0][0]

    def _maybe_defer(self, pend: _Pending, node: SimNode,
                     warm: bool, score: float) -> bool:
        """Affinity window (shared should_defer_claim): a cold claim
        for an identity some busy node is warm for hands the task
        back for a beat; past the window it always places."""
        if not self.policy.claim_scoring or warm:
            return False
        identity = pend.task.cache_identity
        if not identity or not self._warm_count.get(identity):
            return False
        queued = self.clock.now - pend.queue_since
        if not sched_policy.should_defer_claim(score, queued,
                                               self.knobs):
            return False
        self._push_free(node)
        pend.deferrals += 1
        self.metrics["deferrals"] += 1
        self.heap.schedule_in(DEFER_RETRY_SECONDS, self._on_arrival,
                              pend)
        return True

    def _start(self, pend: _Pending, node: SimNode,
               warm: bool) -> None:
        now = self.clock.now
        task = pend.task
        node.free -= 1
        claim_t = now + CLAIM_LATENCY + self._claim_extra_latency
        if self._claim_backoff_until > now:
            # store_error window: the first claim round trip fails
            # and the retry supervisor's backoff is paid explicitly.
            self._emit(ev.TASK_BACKOFF, now, now + 1.0, node,
                       task.task_id)
            claim_t += 1.0
        wait = claim_t - pend.queue_since
        self.metrics["queue_wait_total"] += wait
        if wait > self.metrics["queue_wait_max"]:
            self.metrics["queue_wait_max"] = wait
        self._emit(ev.TASK_QUEUED, pend.queue_since, claim_t, node,
                   task.task_id)
        if pend.recovery == "preempt":
            self._emit(ev.TASK_PREEMPT_RECOVERY, pend.killed_at,
                       claim_t, node, task.task_id)
        elif pend.recovery == "evict":
            self._emit(ev.TASK_EVICTION_RECOVERY, pend.killed_at,
                       claim_t, node, task.task_id)
        work_start = claim_t
        identity = task.cache_identity
        if identity:
            if warm and identity in node.warm:
                self._emit(ev.PROGRAM_COMPILE, claim_t, claim_t,
                           node, task.task_id, cache_hit=True,
                           saved_seconds=task.compile_seconds)
            else:
                work_start = claim_t + task.compile_seconds
                self._emit(ev.PROGRAM_COMPILE, claim_t, work_start,
                           node, task.task_id, cache_hit=False)
                if identity not in node.warm:
                    node.warm.add(identity)
                    self._warm_count[identity] = \
                        self._warm_count.get(identity, 0) + 1
        attempt = self._attempts.get(task.task_id, 0) + 1
        self._attempts[task.task_id] = attempt
        run = _Running(task, node, attempt, pend.resume_step,
                       work_start)
        self._running[task.task_id] = run
        end = work_start + self._attempt_seconds(run)
        self.heap.schedule(end, self._on_complete,
                           (task.task_id, attempt))

    def _attempt_seconds(self, run: _Running) -> float:
        task = run.task
        remaining = max(0, task.steps - run.start_step)
        seconds = remaining * task.step_seconds
        if task.ckpt_every > 0 and task.ckpt_seconds > 0.0:
            commits = (task.steps // task.ckpt_every
                       - run.start_step // task.ckpt_every)
            seconds += max(0, commits) * task.ckpt_seconds
        return seconds

    def _on_complete(self, payload: tuple) -> None:
        task_id, attempt = payload
        run = self._running.get(task_id)
        if run is None or run.attempt != attempt:
            return  # attempt superseded by a kill/preempt
        now = self.clock.now
        task = run.task
        del self._running[task_id]
        if task.steps > run.start_step:
            self._emit(ev.PROGRAM_STEP_WINDOW, run.work_start, now,
                       run.node, task_id,
                       step_start=run.start_step,
                       step_end=task.steps)
        if task.ckpt_every > 0 and task.ckpt_seconds > 0.0:
            commits = max(0, task.steps // task.ckpt_every
                          - run.start_step // task.ckpt_every)
            if commits:
                dur = commits * task.ckpt_seconds
                self._emit(ev.PROGRAM_CHECKPOINT_SAVE, now - dur,
                           now, run.node, task_id)
        self.metrics["tasks_completed"] += 1
        self._free_slot(run.node)
        self._dispatch()

    def _free_slot(self, node: SimNode) -> None:
        node.free += 1
        if node.up:
            self._push_free(node)

    # ----------------------- kills and preemption ----------------------

    def _executed_steps(self, run: _Running, at: float) -> int:
        if at <= run.work_start:
            return run.start_step
        done = run.start_step + int(
            (at - run.work_start) / run.task.step_seconds)
        return min(run.task.steps, max(run.start_step, done))

    def _committed_step(self, run: _Running, executed: int) -> int:
        if run.task.ckpt_every <= 0:
            return min(run.start_step, executed)
        cadenced = (executed // run.task.ckpt_every) \
            * run.task.ckpt_every
        return max(run.start_step, min(cadenced, executed))

    def _kill(self, run: _Running, *, drained: bool,
              recovery: Optional[str], free_slot: bool = True,
              requeue: bool = True) -> None:
        """End a running attempt at virtual-now. ``drained`` means
        the victim got to flush a cooperative step-boundary commit —
        zero replay, but only for a task that checkpoints at all; a
        never-committing workload loses everything it executed no
        matter how polite the notice was. Hard kills always resume
        from the last COMMITTED step and the engine prices the
        replayed overlap as rework."""
        now = self.clock.now
        task = run.task
        self._running.pop(task.task_id, None)
        executed = self._executed_steps(run, now)
        if executed > run.start_step and now > run.work_start:
            self._emit(ev.PROGRAM_STEP_WINDOW, run.work_start, now,
                       run.node, task.task_id,
                       step_start=run.start_step, step_end=executed)
        resume = executed if drained and task.ckpt_every > 0 \
            else self._committed_step(run, executed)
        self.metrics["kills"] += 1
        self.metrics["replayed_steps"] += executed - resume
        if recovery == "preempt":
            self.metrics["preemptions"] += 1
        elif recovery == "evict":
            self.metrics["evictions"] += 1
        if free_slot:
            self._free_slot(run.node)
        if requeue:
            self._enqueue(_Pending(task, resume_step=resume,
                                   queue_since=now,
                                   recovery=recovery,
                                   killed_at=now))

    def _drain(self, run: _Running, recovery: str = "preempt",
               notice: float = NOTICE_LATENCY) -> None:
        """Cooperative preemption: the victim commits at its next
        step boundary after the notice lands, then exits preempted —
        the live drain protocol (agent/preemption.py) in virtual
        time."""
        if run.preempt_pending:
            return
        run.preempt_pending = True
        now = self.clock.now + notice
        step_s = run.task.step_seconds
        if now <= run.work_start:
            boundary = run.work_start
        else:
            k = -(-(now - run.work_start) // step_s)  # ceil
            boundary = run.work_start + k * step_s
        run.attempt += 1  # invalidate the scheduled completion
        self._attempts[run.task.task_id] = run.attempt
        self.heap.schedule(boundary, self._on_drained,
                           (run, recovery))

    def _on_drained(self, payload: tuple) -> None:
        run, recovery = payload
        if self._running.get(run.task.task_id) is not run:
            return  # killed harder in the meantime
        self._kill(run, drained=True, recovery=recovery)
        self._dispatch()

    # ---------------------------- the sweep ----------------------------

    def _on_sweep(self, _payload) -> None:
        self.heap.schedule_in(SWEEP_INTERVAL, self._on_sweep, None)
        if self._sweep_frozen_until > self.clock.now:
            return
        now = self.clock.now
        starved = []
        for priority in sorted(self._pending, reverse=True):
            for pend in self._pending[priority]:
                if now - pend.queue_since >= SWEEP_GRACE:
                    starved.append((priority, pend.queue_since,
                                    pend.task.task_id))
        if not starved:
            return
        starved.sort(key=lambda t: (-t[0], t[1], t[2]))
        victims = []
        for run in self._running.values():
            if run.preempt_pending:
                continue
            cost = 0.0
            if self.policy.victim_by_cost:
                executed = self._executed_steps(run, now)
                cost = sched_policy.victim_cost(
                    warm=bool(run.task.cache_identity),
                    steps_since_commit=(
                        executed - self._committed_step(run,
                                                        executed)),
                    step_seconds=run.task.step_seconds,
                    gang_size=run.task.gang_size, knobs=self.knobs)
            victims.append((sched_policy.victim_sort_key(
                run.task.priority, cost, run.task.task_id), run))
        victims.sort(key=lambda t: t[0])
        i = 0
        for priority, _since, _tid in starved:
            if i >= len(victims) or victims[i][0][0] >= priority:
                break
            self._drain(victims[i][1], recovery="preempt")
            self.metrics["sweep_victims"] += 1
            i += 1

    # --------------------------- autoscale -----------------------------

    def _up_nodes(self) -> list:
        return [n for n in self.nodes if n.up]

    def _on_autoscale(self, _payload) -> None:
        self.heap.schedule_in(AUTOSCALE_TICK, self._on_autoscale,
                              None)
        pending = sum(len(q) for q in self._pending.values())
        active = len(self._running)
        up = self._up_nodes()
        current = len(up)
        if self.policy.autoscale_goodput:
            target, _reason = sched_policy.autoscale_target(
                pending_tasks=pending, active_tasks=active,
                current_nodes=current, slots_per_node=self.slots,
                knobs=self.knobs)
        else:
            # Reactive baseline (pool/autoscale.py "pending_tasks"
            # scenario shape): size straight to the backlog.
            target = -(-(active + pending) // self.slots)
        target = max(self.min_nodes, min(self.max_nodes, target))
        if target > current:
            self._scale_up(target - current)
        elif target < current:
            self._scale_down(current - target)

    def _scale_up(self, count: int) -> None:
        now = self.clock.now
        for _ in range(count):
            idx = len(self.nodes)
            node = SimNode(idx, self.slots, now)
            node.up = False  # joins after provisioning
            self.nodes.append(node)
            self._emit(ev.NODE_PROVISIONING, now,
                       now + self.provision_seconds, node)
            self.heap.schedule(now + self.provision_seconds,
                               self._on_node_up, node)
            self.metrics["nodes_added"] += 1

    def _on_node_up(self, node: SimNode) -> None:
        node.up = True
        node.retired_at = None
        self._push_free(node)
        self._dispatch()

    def _scale_down(self, count: int) -> None:
        removed = 0
        for node in reversed(self.nodes):
            if removed >= count:
                break
            if node.up and node.free == self.slots:
                self._retire_node(node)
                removed += 1
        self.metrics["nodes_removed"] += removed

    def _retire_node(self, node: SimNode) -> None:
        node.up = False
        node.retired_at = self.clock.now
        for identity in node.warm:
            self._warm_count[identity] = max(
                0, self._warm_count.get(identity, 0) - 1)
        node.warm.clear()

    # ------------------------- chaos adapters --------------------------
    # Applied via sim/scenarios.KIND_ADAPTERS (every chaos/plan.py
    # INJECTION_KINDS entry maps to one of these or is declared
    # excluded — enforced by tests/test_names_consistency.py).

    def _on_injection(self, inj) -> None:
        from batch_shipyard_tpu.sim import scenarios
        adapter = scenarios.KIND_ADAPTERS.get(inj.kind)
        if adapter is not None:
            adapter(self, inj)
            self._dispatch()

    def _node_for(self, inj) -> Optional[SimNode]:
        up = self._up_nodes()
        if not up:
            return None
        return up[inj.node_index % len(up)]

    def _runs_on(self, node: SimNode) -> list:
        return sorted((r for r in self._running.values()
                       if r.node is node),
                      key=lambda r: r.task.task_id)

    def chaos_store_delay(self, inj) -> None:
        params = dict(inj.params)
        delay = float(params.get("delay", 0.5))
        window = float(params.get("window",
                                  params.get("duration", 5.0)))
        self._claim_extra_latency += delay
        self.heap.schedule_in(window, self._chaos_store_delay_end,
                              delay)

    def _chaos_store_delay_end(self, delay: float) -> None:
        self._claim_extra_latency = max(
            0.0, self._claim_extra_latency - delay)

    def chaos_store_error(self, inj) -> None:
        params = dict(inj.params)
        self._claim_backoff_until = max(
            self._claim_backoff_until,
            self.clock.now + float(params.get(
                "window", params.get("duration", 5.0))))

    def chaos_heartbeat_blackout(self, inj) -> None:
        node = self._node_for(inj)
        if node is None:
            return
        params = dict(inj.params)
        node.pause_until = self.clock.now + float(params.get(
            "window", params.get("duration", 10.0)))

    def chaos_task_kill(self, inj) -> None:
        node = self._node_for(inj)
        runs = self._runs_on(node) if node else []
        if runs:
            self._emit(ev.TASK_RETRY, self.clock.now,
                       self.clock.now, node, runs[0].task.task_id)
            self._kill(runs[0], drained=False, recovery=None)

    def chaos_task_wedge(self, inj) -> None:
        """Wedged-but-breathing: no progress from now, watchdog kill
        after the wedge window, retry-supervisor backoff priced."""
        node = self._node_for(inj)
        runs = self._runs_on(node) if node else []
        if not runs:
            return
        run = runs[0]
        params = dict(inj.params)
        wedge = float(params.get("window",
                                 params.get("duration", 5.0)))
        now = self.clock.now
        self._kill(run, drained=False, recovery=None,
                   requeue=False)
        self._emit(ev.TASK_BACKOFF, now, now + wedge, run.node,
                   run.task.task_id)
        pend = _Pending(run.task,
                        resume_step=self._committed_step(
                            run, self._executed_steps(run, now)),
                        queue_since=now)
        self.heap.schedule(now + wedge, self._on_arrival, pend)

    def _node_down(self, node: SimNode, down_seconds: float,
                   *, drained: bool, permanent: bool = False,
                   recovery: str = "preempt") -> None:
        now = self.clock.now
        for run in self._runs_on(node):
            self._kill(run, drained=drained, recovery=recovery,
                       free_slot=False)
        node.free = self.slots
        self._retire_node(node)
        if permanent:
            return
        self._emit(ev.NODE_PREEMPTED, now, now, node)  # count marker
        self._emit(ev.NODE_PREEMPTED, now, now + down_seconds, node)
        self.heap.schedule(now + down_seconds, self._on_node_up,
                           node)

    def chaos_node_preempt(self, inj) -> None:
        node = self._node_for(inj)
        if node is not None:
            params = dict(inj.params)
            self._node_down(node, float(params.get(
                "revive_after", params.get("down", 30.0))),
                drained=False)

    def chaos_node_preempt_notice(self, inj) -> None:
        """Provider preemption WITH notice: running work drains
        cooperatively (zero replay), then the node goes away."""
        node = self._node_for(inj)
        if node is None:
            return
        params = dict(inj.params)
        notice = float(params.get("notice", 2.5))
        down = float(params.get("revive_after",
                                params.get("down", 30.0)))
        for run in self._runs_on(node):
            self._drain(run, recovery="preempt", notice=notice)
        node.pause_until = self.clock.now + notice + down
        self.heap.schedule_in(notice + 0.01,
                              self._chaos_notice_down, (node, down))

    def _chaos_notice_down(self, payload: tuple) -> None:
        node, down = payload
        self._node_down(node, down, drained=True)

    def chaos_victim_ignore_notice(self, inj) -> None:
        """An uncooperative victim squats through the notice; the
        escalation ladder hard-kills it after the grace window and
        the exit prices as the eviction leg."""
        node = self._node_for(inj)
        runs = self._runs_on(node) if node else []
        if not runs:
            return
        run = runs[0]
        grace = float(dict(inj.params).get("grace", 5.0))
        run.preempt_pending = True
        self.heap.schedule_in(grace, self._chaos_evict, run)

    def _chaos_evict(self, run: _Running) -> None:
        if self._running.get(run.task.task_id) is not run:
            return
        self._kill(run, drained=False, recovery="evict")
        self._dispatch()

    def chaos_host_loss_resize(self, inj) -> None:
        node = self._node_for(inj)
        if node is None:
            return
        self._emit(ev.GANG_RESIZE, self.clock.now, self.clock.now,
                   node)
        self._node_down(node, 0.0, drained=False, permanent=True)

    def chaos_pool_capacity_loss(self, inj) -> None:
        frac = float(dict(inj.params).get("fraction", 0.25))
        up = self._up_nodes()
        for node in up[:max(1, int(len(up) * frac))]:
            self._node_down(node, 0.0, drained=False,
                            permanent=True)

    def chaos_store_outage(self, inj) -> None:
        params = dict(inj.params)
        dur = float(params.get("window",
                               params.get("duration", 10.0)))
        now = self.clock.now
        self._emit(ev.STORE_OUTAGE, now, now + dur)
        self._claim_freeze_until = max(self._claim_freeze_until,
                                       now + dur)
        self.heap.schedule(now + dur, self._on_thaw, None)

    def _on_thaw(self, _payload) -> None:
        self._dispatch()

    def chaos_leader_partition(self, inj) -> None:
        params = dict(inj.params)
        dur = float(params.get("window",
                               params.get("duration", 15.0)))
        self._sweep_frozen_until = max(self._sweep_frozen_until,
                                       self.clock.now + dur)

    def chaos_agent_restart(self, inj) -> None:
        node = self._node_for(inj)
        if node is None:
            return
        params = dict(inj.params)
        gap = float(params.get("revive_after",
                               params.get("gap", 2.0)))
        node.pause_until = self.clock.now + gap
        for run in self._runs_on(node):
            self._emit(ev.TASK_ADOPTION, self.clock.now,
                       self.clock.now + gap, node,
                       run.task.task_id)

    # ----------------------------- run/report --------------------------

    def run(self, max_events: int = 50_000_000) -> "FleetSimulator":
        popped = 0
        while True:
            if self.horizon is not None and \
                    self.clock.now >= self.horizon:
                break
            if not self._pending_work():
                break
            item = self.heap.pop()
            if item is None:
                break
            fn, payload = item
            fn(payload)
            popped += 1
            if popped >= max_events:
                raise RuntimeError(
                    f"simulation exceeded {max_events} events")
        self._finalize()
        return self

    def _pending_work(self) -> bool:
        if self._running:
            return True
        if any(self._pending.values()):
            return True
        # Only recurring ticks (sweep/autoscale) left? Then the
        # workload is done — peeking would never terminate.
        return any(fn not in (self._on_sweep, self._on_autoscale)
                   for _t, _s, fn, _p in self.heap._heap)

    def _finalize(self) -> None:
        """One idle span per node over its lifetime (birth → sim end,
        or permanent retirement): the sweep overlays every busier
        category on top, so uncovered node time prices as the idle
        badput it is — 1,999 idle nodes can never hide behind one
        busy one."""
        end = self.clock.now
        for node in self.nodes:
            upto = node.retired_at if node.retired_at is not None \
                else end
            if node.born < upto:
                self._emit(ev.NODE_IDLE, node.born, upto, node)

    def report(self) -> dict:
        """The run's full report: the REAL engine's node-seconds
        goodput partition + scheduler metrics + a canonical-JSON
        fingerprint (the byte-identity the determinism test pins)."""
        engine = accounting.decompose_by_node(self.events)
        partition = (engine["productive_seconds"]
                     + sum(engine["badput_seconds"].values())
                     + sum(engine["overlapped_seconds"].values()))
        wall = engine["wall_seconds"]
        completed = self.metrics["tasks_completed"]
        report = {
            "policy": self.policy.name,
            "nodes": len(self.nodes),
            "slots_per_node": self.slots,
            "virtual_seconds": round(self.clock.now, 6),
            "goodput": {
                "goodput_ratio": engine["goodput_ratio"],
                "availability_goodput":
                    engine["availability_goodput"],
                "resource_goodput": engine["resource_goodput"],
                "program_goodput": engine["program_goodput"],
                "wall_seconds": engine["wall_seconds"],
                "productive_seconds": engine["productive_seconds"],
                "badput_seconds": engine["badput_seconds"],
                "overlapped_seconds": engine["overlapped_seconds"],
                "compile_cache_hits": engine["compile_cache_hits"],
                "compile_cache_misses":
                    engine["compile_cache_misses"],
                "compile_saved_seconds":
                    engine["compile_saved_seconds"],
                "steps": engine["steps"],
                "preemptions": engine["preemptions"],
            },
            "partition_exact": abs(partition - wall) <= max(
                1e-6 * max(1.0, wall), 1e-6),
            "partition_error": partition - wall,
            "scheduler": dict(
                self.metrics,
                queue_wait_mean=(
                    self.metrics["queue_wait_total"] / completed
                    if completed else 0.0)),
        }
        report["fingerprint"] = hashlib.sha256(
            json.dumps(report, sort_keys=True).encode()
        ).hexdigest()[:16]
        return report


def run_sim(*, trace: list, nodes: int, policy: str = "baseline",
            knobs: Optional[sched_policy.PolicyKnobs] = None,
            slots_per_node: int = 1, injections: tuple = (),
            autoscale: bool = False, min_nodes: int = 1,
            max_nodes: Optional[int] = None,
            provision_seconds: float = 120.0,
            horizon: Optional[float] = None) -> dict:
    """Build, run, report — the one-call surface the CLI, bench, and
    tests share."""
    sim = FleetSimulator(
        trace=trace, nodes=nodes, slots_per_node=slots_per_node,
        policy=policy, knobs=knobs, injections=injections,
        autoscale=autoscale, min_nodes=min_nodes,
        max_nodes=max_nodes, provision_seconds=provision_seconds,
        horizon=horizon)
    return sim.run().report()


def compare(reports: dict) -> dict:
    """Per-policy deltas vs the ``baseline`` entry, priced by the
    shared accounting delta helper."""
    base = reports.get("baseline")
    out: dict = {}
    for name, rep in reports.items():
        entry: dict = {"report": rep}
        if base is not None and name != "baseline":
            entry["delta_vs_baseline"] = accounting.report_delta(
                base["goodput"], rep["goodput"])
            entry["queue_wait_mean_delta"] = (
                rep["scheduler"]["queue_wait_mean"]
                - base["scheduler"]["queue_wait_mean"])
        out[name] = entry
    return out

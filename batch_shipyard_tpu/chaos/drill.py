"""Chaos drill: run a seeded fault schedule against a real fakepod
pool and assert the self-healing invariants.

The drill is the proof the recovery layer demands: it builds a pool of
REAL NodeAgents (threads over a shared state store), submits a batch
of watchdog-protected tasks, replays a ChaosPlan's injections at their
scheduled offsets — wedges, mid-run kills, node preemptions, heartbeat
blackouts, store faults — then verifies that the system healed:

  * every task reached ``completed`` (bounded retries beat every
    injected fault),
  * exactly-once effects (each task's output holds exactly its line),
  * no orphaned coordination state (gang rows, queue messages),
  * the goodput partition stayed exact (productive + badput +
    overlapped == wall) — chaos may move seconds between categories
    but can never lose any.

Used by `shipyard chaos drill`, tools/chaos_drill.py, and the test
suite (tests/test_chaos_recovery.py drives small, fast drills).
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import sys
import tempfile
import threading
import time
from typing import Optional

from batch_shipyard_tpu.chaos import injectors as injectors_mod
from batch_shipyard_tpu.chaos.plan import ChaosPlan
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state import resilient as state_resilient
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def _submit_jobs(store, pool, jobs) -> dict:
    """Every drill's submission leg rides the group-commit lane
    (state/resilient.py ``group_commit``): task rows and queue
    messages buffer, coalesce, and land in combined round trips —
    the same seeds that pin the recovery layer now also pin that
    write-combining preserves submission semantics exactly (any
    lost or double-applied write breaks the drill's completion,
    exactly-once, or goodput-partition invariants)."""
    gc_store = state_resilient.ResilientStore(
        store,
        journal_path=os.path.join(
            tempfile.gettempdir(),
            f"shipyard-drill-gc-{os.getpid()}-{id(store)}.jsonl"))
    with gc_store.group_commit():
        return jobs_mgr.add_jobs(gc_store, pool, jobs)

POOL_ID = "chaos-drill"
JOB_ID = "drill"
# Every drill workload carries one real gang task alongside the
# regular tasks: without it TABLE_GANGS is empty by construction and
# the "no orphaned gang rows" invariant would be vacuously true — a
# leak in _clear_gang_rows/_recover_broken_gang under chaos would
# pass every drill.
GANG_TASK_ID = "g000"
GANG_INSTANCES = 2


def run_drill(seed: int = 0, tasks: int = 16,
              accelerator: str = "v5litepod-16",
              duration: float = 4.0,
              kinds: Optional[tuple[str, ...]] = None,
              injections_per_kind: int = 1,
              task_sleep: float = 1.2,
              wait_timeout: float = 120.0,
              plan: Optional[ChaosPlan] = None) -> dict:
    """Run one drill; returns the report dict (invariants + plan
    fingerprint + goodput decomposition). Raises AssertionError when
    an invariant does not hold.

    Defaults are tuned so the submitted work SPANS the injection
    window (tasks * task_sleep ≈ 2-3x duration / slots): a kill
    scheduled at t=3 must find a victim actually running, or the
    drill proves nothing about the kill paths. ``tasks`` counts the
    regular tasks; one gang task (``GANG_TASK_ID``) always rides
    along so the gang-row cleanup invariant is actually exercised."""
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    raw_store = MemoryStateStore()
    chaos_store = injectors_mod.ChaosStore(raw_store)
    # Agents live on the chaos-wrapped store (they must survive the
    # faults); the drill driver itself orchestrates through the raw
    # store so an injected error never masquerades as a driver bug.
    substrate = FakePodSubstrate(chaos_store, node_stale_seconds=3.0)
    substrate.agent_kwargs = {
        "retry_backoff_base": 0.2, "retry_backoff_cap": 2.0,
        # The claimed-message window floors crashed-node recovery
        # latency; production's 60s would dominate a seconds-scale
        # drill.
        "claim_visibility_seconds": 5.0,
        # Fast janitor cadence: a cleanup lost to an injected store
        # fault must be swept inside the invariant-check window.
        "gang_sweep_interval": 1.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "tpu": {"accelerator_type": accelerator},
        "task_slots_per_node": 2,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    if plan is None:
        plan = ChaosPlan.generate(
            seed, duration=duration,
            num_nodes=pool.tpu.total_workers if pool.tpu else 4,
            kinds=kinds, injections_per_kind=injections_per_kind)
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    try:
        pool_mgr.create_pool(raw_store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": f"t{i:03d}",
                       "command": (f"sleep {task_sleep} && "
                                   f"echo drill-{i}"),
                       "max_task_retries": 8,
                       "progress_deadline_seconds": 2}
                      for i in range(tasks)]
                     + [{"id": GANG_TASK_ID,
                         "command": (f"sleep {task_sleep} && "
                                     "echo drill-gang"),
                         "max_task_retries": 8,
                         "progress_deadline_seconds": 2,
                         "multi_instance": {
                             "num_instances": GANG_INSTANCES}}],
        }]})
        started = time.monotonic()
        _submit_jobs(raw_store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, chaos_store, report),
            daemon=True, name="chaos-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            raw_store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=max(0.0, duration -
                                (time.monotonic() - started)) + 5.0)
        _check_invariants(raw_store, task_rows, tasks, report)
    finally:
        substrate.stop_all()
    return report


def run_preemption_drill(seed: int = 0, instances: int = 4,
                         steps: int = 60, step_seconds: float = 0.08,
                         duration: float = 4.0,
                         wait_timeout: float = 120.0) -> dict:
    """Preemption-recovery drill: a seeded node_preempt_notice
    schedule preempts a RUNNING ``instances``-wide gang mid-training
    (the preempt_probe workload — real beats, real step windows, the
    real COMMITTED-marker commit protocol). Asserts the elastic-
    training acceptance invariants:

      * the gang drained cooperatively, requeued with the distinct
        preempted status, and resumed from the forced COMMITTED
        checkpoint with ZERO lost steps beyond the barrier (the step
        ledger is contiguous and replay-free),
      * the retry budget was untouched (retries == 0) and
        preempt_count advanced,
      * node health was not debited (an externally-caused exit says
        nothing about the node),
      * the goodput partition stayed exact AND the
        preemption_recovery leg is actually populated.

    Raises AssertionError on any violation; returns the report."""
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    # Fast heartbeats: preempt-request delivery rides the heartbeat
    # loop, and the drill's notice windows must dwarf one beat.
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=5.0)
    substrate.agent_kwargs = {"claim_visibility_seconds": 5.0,
                              "gang_sweep_interval": 1.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16"},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    plan = ChaosPlan.generate(seed, duration=duration,
                              num_nodes=instances,
                              kinds=("node_preempt_notice",))
    # Deterministic cooperation: widen every notice window well past
    # one heartbeat + one step, so the drill always exercises the
    # COOPERATIVE path (the hard-kill fallback is the generic drill's
    # territory). Pure function of the seed, still.
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(inj, params=tuple(sorted(
            {**dict(inj.params), "notice": 2.5}.items())))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    ckpt = os.path.join(substrate.work_root, "probe", "state.json")
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": GANG_TASK_ID,
                       "command": (
                           f"{sys.executable} -m batch_shipyard_tpu"
                           f".workloads.preempt_probe "
                           f"--steps {steps} "
                           f"--step-seconds {step_seconds} "
                           f"--ckpt {ckpt}"),
                       "environment_variables": {
                           "PYTHONPATH": repo_root},
                       "max_task_retries": 3,
                       "multi_instance": {
                           "num_instances": instances,
                           "jax_distributed": {"enabled": False}}}],
        }]})
        started = time.monotonic()
        _submit_jobs(store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, None, report),
            daemon=True, name="chaos-preempt-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=5.0)
        _check_preemption_invariants(store, task_rows, ckpt, steps,
                                     report)
    finally:
        substrate.stop_all()
    return report


def _check_preemption_invariants(store, task_rows: list, ckpt: str,
                                 steps: int, report: dict) -> None:
    invariants = report["invariants"]
    task = task_rows[0]
    invariants["state"] = task.get("state")
    assert task.get("state") == "completed", task
    # Full budget preserved: preemption consumed ZERO retries.
    invariants["retries"] = int(task.get("retries", 0))
    invariants["preempt_count"] = int(
        task.get(names.TASK_COL_PREEMPT_COUNT, 0) or 0)
    assert invariants["retries"] == 0, (
        f"preemption consumed retry budget: {task}")
    assert invariants["preempt_count"] >= 1, (
        f"drill never preempted the gang: {report['applied']}")
    # Zero lost steps beyond the barrier: the writer's step ledger is
    # contiguous (each preempted attempt's commit is exactly where
    # the next attempt resumed — no replay, no gap) and covers every
    # step exactly once.
    with open(ckpt + ".steps.log", encoding="utf-8") as fh:
        ledger = [line.split() for line in fh if line.strip()]
    invariants["step_ledger"] = [" ".join(parts) for parts in ledger]
    cursor = 0
    for _inst, span, _status in ledger:
        lo, hi = span.split("..")
        assert int(lo) == cursor, (
            f"step ledger not contiguous (lost or replayed steps): "
            f"{invariants['step_ledger']}")
        cursor = int(hi)
    assert cursor == steps, invariants["step_ledger"]
    assert ledger[-1][2] == "completed", invariants["step_ledger"]
    # Node health untouched: externally-caused exits are neutral.
    for node in store.query_entities(names.TABLE_NODES,
                                     partition_key=POOL_ID):
        health = float(node.get(names.NODE_COL_HEALTH, 1.0) or 1.0)
        assert health >= 1.0, (
            f"preemption debited node health: "
            f"{node['_rk']}={health}")
        assert not node.get(names.NODE_COL_QUARANTINED), node
    invariants["node_health_untouched"] = True
    # Goodput: partition exact AND the preemption_recovery leg is
    # actually populated by the drill (the recovery interval from
    # preempted exit to re-claim).
    pool_report = _assert_partition_exact(store, POOL_ID, invariants)
    recovery = pool_report["badput_seconds"].get(
        "preemption_recovery", 0.0)
    invariants["preemption_recovery_seconds"] = recovery
    assert recovery > 0.0, (
        f"preemption_recovery not populated: "
        f"{pool_report['badput_seconds']}")
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }
    invariants["ok"] = True


def _assert_partition_exact(store, pool_id: str,
                            invariants: dict) -> dict:
    """THE shared acceptance check of every drill: chaos may move
    seconds between goodput categories but can never create or lose
    any — productive + badput + overlapped == wall to fp tolerance.
    Returns the pool report so callers assert their leg-specific
    invariants against the same snapshot."""
    pool_report = accounting.pool_report(store, pool_id,
                                         include_jobs=False)
    total = (pool_report["productive_seconds"]
             + sum(pool_report["badput_seconds"].values())
             + sum(pool_report["overlapped_seconds"].values()))
    invariants["goodput_wall_seconds"] = pool_report["wall_seconds"]
    invariants["goodput_partition_total"] = total
    assert abs(total - pool_report["wall_seconds"]) <= max(
        1e-6 * max(1.0, pool_report["wall_seconds"]), 1e-6), (
        f"goodput partition broke: {total} != "
        f"{pool_report['wall_seconds']}")
    return pool_report


def _await_no_gang_rows(store, invariants: dict,
                        timeout: float = 30.0) -> None:
    """No-orphaned-coordination-state invariant: gang rendezvous
    rows must all be retired within a bounded window (cleanups lost
    to injected faults are repaired by the janitor sweep)."""
    deadline = time.monotonic() + timeout
    while True:
        leftover = list(store.query_entities(names.TABLE_GANGS))
        if not leftover or time.monotonic() >= deadline:
            break
        time.sleep(0.25)
    invariants["orphaned_gang_rows"] = len(leftover)
    assert not leftover, leftover


def run_victim_selection_drill(seed: int = 0, steps: int = 160,
                               step_seconds: float = 0.05,
                               wait_timeout: float = 120.0) -> dict:
    """Victim-SELECTION drill: the preemption drill's missing half.
    The preemption drill proves a victim drains correctly; this one
    proves the sweep picks the RIGHT victim. Two eligible victims run
    side by side on a two-node pool:

      * ``aa-costly`` — never commits mid-run and advertises a warm
        compile-cache identity: killing it destroys warm state and
        replays every executed step (high goodput cost). Its task id
        sorts FIRST, so the pre-policy (priority, task_id) tie-break
        would elect it.
      * ``zz-cheap``  — commits EVERY step (steps-since-commit ~= 0)
        and holds nothing warm: killing it costs almost nothing.

    A strictly higher-priority task then starves. The sweep's shared
    goodput-cost ordering (sched/policy.py ``victim_cost_from_row`` +
    ``victim_sort_key``, the very functions the fleet simulator
    prices) must deterministically elect ``zz-cheap`` — the id order
    guarantees the choice can only come from the cost term, pinning
    the policy in the LIVE sweep path. Asserts:

      * both victims' sched hints were mirrored into their task rows
        (the heartbeat `_sync_sched_hints` leg) and priced the costly
        victim strictly dearer BEFORE the starver existed,
      * ``zz-cheap`` was preempted (cooperatively, zero retries) and
        ``aa-costly`` was NOT touched (no preempt, no evict),
      * the starver and both victims all completed,
      * the goodput partition stayed exact with the
        preemption_recovery leg populated."""
    from batch_shipyard_tpu.sched import policy as sched_policy
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=5.0)
    substrate.agent_kwargs = {
        "claim_visibility_seconds": 5.0, "gang_sweep_interval": 1.0,
        # One election per starvation episode: the sweep interval must
        # dwarf drain + re-claim latency (~0.5s), or a second sweep
        # fires while the starver is still queued and elects the
        # costly victim too — the drill asserts it is never touched.
        "preempt_sweep_interval": 2.5,
        "preempt_grace_seconds": 1.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 2}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    report: dict = {"seed": seed, "fingerprint": f"victim-sel-{seed}",
                    "applied": [], "invariants": {}}
    work = os.path.join(substrate.work_root, "probe")
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    victims_job = "victims"
    starver_job = "starver"
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        probe = (f"{sys.executable} -m batch_shipyard_tpu"
                 f".workloads.preempt_probe "
                 f"--steps {steps} --step-seconds {step_seconds} ")
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": victims_job,
            "priority": 0,
            "tasks": [
                {"id": "aa-costly",
                 "command": (probe +
                             f"--cache-identity drill-warm "
                             f"--ckpt {work}/costly.json"),
                 "environment_variables": {"PYTHONPATH": repo_root},
                 "max_task_retries": 3},
                {"id": "zz-cheap",
                 "command": (probe +
                             f"--checkpoint-every 1 "
                             f"--ckpt {work}/cheap.json"),
                 "environment_variables": {"PYTHONPATH": repo_root},
                 "max_task_retries": 3},
            ]}]})
        _submit_jobs(store, pool, jobs)
        # Gate the starver on mirrored hints: the election is only a
        # policy decision once both victims' costs are priceable from
        # their rows.
        pk = names.task_pk(POOL_ID, victims_job)
        deadline = time.monotonic() + wait_timeout / 2.0
        rows: dict = {}
        while time.monotonic() < deadline:
            rows = {r["_rk"]: r for r in store.query_entities(
                names.TABLE_TASKS, partition_key=pk)}
            costly = rows.get("aa-costly", {})
            cheap = rows.get("zz-cheap", {})
            ch = costly.get(names.TASK_COL_SCHED_HINTS)
            zh = cheap.get(names.TASK_COL_SCHED_HINTS)
            if (costly.get("state") == "running"
                    and cheap.get("state") == "running"
                    and isinstance(ch, dict)
                    and ch.get("cache_identity")
                    and isinstance(zh, dict)
                    and float(zh.get("ckpt_step", 0) or 0) >= 1):
                break
            time.sleep(0.2)
        else:
            raise AssertionError(
                f"sched hints never mirrored into victim rows: {rows}")
        cost_costly = sched_policy.victim_cost_from_row(
            rows["aa-costly"])
        cost_cheap = sched_policy.victim_cost_from_row(
            rows["zz-cheap"])
        report["invariants"]["victim_costs"] = {
            "aa-costly": cost_costly, "zz-cheap": cost_cheap}
        assert cost_costly > cost_cheap, (
            f"policy priced the warm never-committer cheaper: "
            f"{report['invariants']['victim_costs']}")
        _submit_jobs(store, pool, settings_mod.job_settings_list(
            {"job_specifications": [{
                "id": starver_job,
                "priority": 100,
                "tasks": [{"id": "hipri",
                           "command": (f"{sys.executable} -c "
                                       f"'import time; "
                                       f"time.sleep(0.5)'")}],
            }]}))
        jobs_mgr.wait_for_tasks(store, POOL_ID, starver_job,
                                timeout=wait_timeout,
                                poll_interval=0.25)
        victim_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, victims_job, timeout=wait_timeout,
            poll_interval=0.25)
        _check_victim_selection_invariants(store, victim_rows, report)
    finally:
        substrate.stop_all()
    return report


def _check_victim_selection_invariants(store, victim_rows: list,
                                       report: dict) -> None:
    invariants = report["invariants"]
    rows = {r["_rk"]: r for r in victim_rows}
    for rk, row in rows.items():
        assert row.get("state") == "completed", row
        assert int(row.get("retries", 0)) == 0, (
            f"preemption consumed retry budget: {row}")
    invariants["retries"] = max(
        int(row.get("retries", 0)) for row in rows.values())
    cheap = rows["zz-cheap"]
    costly = rows["aa-costly"]
    invariants["cheap_preempt_count"] = int(
        cheap.get(names.TASK_COL_PREEMPT_COUNT, 0) or 0)
    invariants["costly_preempt_count"] = int(
        costly.get(names.TASK_COL_PREEMPT_COUNT, 0) or 0)
    invariants["costly_evict_count"] = int(
        costly.get(names.TASK_COL_EVICT_COUNT, 0) or 0)
    assert invariants["cheap_preempt_count"] >= 1, (
        f"the cheap victim was never elected: {invariants}")
    assert invariants["costly_preempt_count"] == 0, (
        f"the sweep touched the EXPENSIVE victim — goodput-cost "
        f"ordering did not drive the election: {invariants}")
    assert invariants["costly_evict_count"] == 0, invariants
    pool_report = _assert_partition_exact(store, POOL_ID, invariants)
    recovery = pool_report["badput_seconds"].get(
        "preemption_recovery", 0.0)
    invariants["preemption_recovery_seconds"] = recovery
    assert recovery > 0.0, pool_report["badput_seconds"]
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }
    invariants["ok"] = True


def run_eviction_drill(seed: int = 0, steps: int = 140,
                       step_seconds: float = 0.05,
                       checkpoint_every: int = 8,
                       duration: float = 4.0,
                       wait_timeout: float = 120.0) -> dict:
    """Forcible-eviction drill: a seeded ``victim_ignore_notice``
    schedule stamps a cooperative preempt request on a running
    --ignore-notice probe — a victim that acknowledges the notice in
    its ledger and keeps squatting. The injector does NOT kill
    anything: the sweep's escalation (grace lapsed -> escalated_at
    stamped) and the owning agent's enforcement (docker rm -f +
    SIGKILL) are the code under test. Asserts the fleet-elasticity
    acceptance invariants:

      * the hard kill fired and the exit was classified ``evicted``
        (claimable, full retry budget — retries == 0) and never
        ``wedged``/failed,
      * the rerun resumed from the last COMMITTED barrier strictly
        BEFORE the notice (the drain never happened) and completed
        with no committed work lost,
      * node health untouched (externally-caused exits are neutral),
      * the goodput partition stayed exact AND the ``eviction`` leg
        is actually populated (TASK_EVICTED marker + recovery
        interval)."""
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=5.0)
    substrate.agent_kwargs = {
        "claim_visibility_seconds": 5.0, "gang_sweep_interval": 1.0,
        # Tight escalation clock: sweep every 0.4s, 0.8s of grace
        # past the notice, and a short preempt-cache TTL so the
        # enforcement heartbeat sees the escalation promptly.
        "preempt_sweep_interval": 0.4,
        "preempt_grace_seconds": 0.8,
        "job_state_ttl": 0.2}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    plan = ChaosPlan.generate(seed, duration=duration, num_nodes=1,
                              kinds=("victim_ignore_notice",))
    # Deterministic sequencing (the preemption drill's notice-widening
    # trick): the stamp must land after the probe's first cadenced
    # commit, so the "resume strictly pre-notice" assertion is never
    # vacuous. Still a pure function of the seed.
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(inj, at=max(inj.at, 1.2))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    ckpt = os.path.join(substrate.work_root, "probe", "state.json")
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": "t0",
                       "command": (
                           f"{sys.executable} -m batch_shipyard_tpu"
                           f".workloads.preempt_probe "
                           f"--steps {steps} "
                           f"--step-seconds {step_seconds} "
                           f"--checkpoint-every {checkpoint_every} "
                           f"--ignore-notice --ckpt {ckpt}"),
                       "environment_variables": {
                           "PYTHONPATH": repo_root},
                       "max_task_retries": 2}],
        }]})
        started = time.monotonic()
        _submit_jobs(store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, None, report),
            daemon=True, name="chaos-evict-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=5.0)
        _check_eviction_invariants(store, task_rows, ckpt, steps,
                                   checkpoint_every, report)
    finally:
        substrate.stop_all()
    return report


def _check_eviction_invariants(store, task_rows: list, ckpt: str,
                               steps: int, checkpoint_every: int,
                               report: dict) -> None:
    invariants = report["invariants"]
    task = task_rows[0]
    invariants["state"] = task.get("state")
    assert task.get("state") == "completed", task
    # Classified evicted, never wedged/failed: the retry budget is
    # untouched and the eviction counter advanced.
    invariants["retries"] = int(task.get("retries", 0))
    invariants["evict_count"] = int(
        task.get(names.TASK_COL_EVICT_COUNT, 0) or 0)
    assert invariants["retries"] == 0, (
        f"eviction consumed retry budget: {task}")
    assert invariants["evict_count"] >= 1, (
        f"drill never evicted the victim: {report['applied']}")
    assert not task.get(names.TASK_COL_PREEMPT_COUNT), (
        f"uncooperative victim cannot have drained: {task}")
    # Resume strictly from the PRE-NOTICE barrier: the ledger's
    # notice-ignored line pins when the victim saw (and burned) its
    # notice; the completed rerun must start at a cadenced COMMITTED
    # step at or before it, and cover through the end — no committed
    # work lost.
    with open(ckpt + ".steps.log", encoding="utf-8") as fh:
        ledger = [line.split() for line in fh if line.strip()]
    invariants["step_ledger"] = [" ".join(parts) for parts in ledger]
    assert ledger and ledger[0][2] == "notice-ignored", (
        invariants["step_ledger"])
    assert ledger[-1][2] == "completed", invariants["step_ledger"]
    notice_step = int(ledger[0][1].split("..")[1])
    resume_lo, resume_hi = (int(x) for x in
                            ledger[-1][1].split(".."))
    invariants["notice_step"] = notice_step
    invariants["resumed_from"] = resume_lo
    assert resume_hi == steps, invariants["step_ledger"]
    assert resume_lo > 0, (
        "rerun restarted from scratch — the pre-notice barrier was "
        f"lost: {invariants['step_ledger']}")
    assert resume_lo % checkpoint_every == 0, (
        f"resume point {resume_lo} is not a cadenced barrier")
    assert resume_lo <= notice_step, (
        f"resume point {resume_lo} is past the notice at "
        f"{notice_step} — an uncooperative victim cannot have "
        f"committed after its notice")
    # Node health untouched: eviction is externally caused.
    for node in store.query_entities(names.TABLE_NODES,
                                     partition_key=POOL_ID):
        health = float(node.get(names.NODE_COL_HEALTH, 1.0) or 1.0)
        assert health >= 1.0, (
            f"eviction debited node health: {node['_rk']}={health}")
        assert not node.get(names.NODE_COL_QUARANTINED), node
    invariants["node_health_untouched"] = True
    # Goodput: partition exact AND the eviction leg populated.
    from batch_shipyard_tpu.goodput import events as gp_events
    kinds = [e["kind"] for e in gp_events.query(store, POOL_ID)]
    invariants["evicted_events"] = kinds.count(
        gp_events.TASK_EVICTED)
    assert invariants["evicted_events"] >= 1, kinds
    pool_report = _assert_partition_exact(store, POOL_ID, invariants)
    eviction = pool_report["badput_seconds"].get("eviction", 0.0)
    invariants["eviction_seconds"] = eviction
    assert eviction > 0.0, (
        f"eviction leg not populated: "
        f"{pool_report['badput_seconds']}")
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }
    invariants["ok"] = True


def run_host_resize_drill(seed: int = 0, steps: int = 100,
                          step_seconds: float = 0.06, dim: int = 24,
                          checkpoint_every: int = 5,
                          duration: float = 4.0,
                          wait_timeout: float = 120.0) -> dict:
    """Multi-host reshard-on-restore drill: a 2-host (multi-process
    fakepod) gang runs the SHARDED reshard probe — each instance owns
    half the state vector and the commit protocol writes per-host
    shard files + a .LAYOUT sidecar (the .MESH analog). A seeded
    ``host_loss_resize`` injection permanently crashes one host; the
    elastic recovery re-forms the gang at 1 host, whose restore must
    follow the per-host plan (parallel/restore_plan.py): read BOTH
    source shards, exactly the slices its new range needs. Asserts:

      * the gang completed at size 1 with a GANG_RESIZE event,
      * params/opt-state BIT-EXACT vs a pure replay oracle (resume
        from the committed barrier loses nothing, reshard included),
      * the rerun's recorded reads == the restore plan (each host
        read only what it needed, from the shards that had it),
      * the loss trajectory at every commit matches the oracle,
      * goodput partition exact, no orphaned gang rows."""
    from batch_shipyard_tpu.parallel import restore_plan
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=2.0)
    substrate.agent_kwargs = {
        "claim_visibility_seconds": 3.0, "gang_sweep_interval": 1.0,
        "gang_timeout": 10.0, "retry_backoff_base": 0.2,
        "retry_backoff_cap": 1.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 2}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    plan = ChaosPlan.generate(seed, duration=duration, num_nodes=2,
                              kinds=("host_loss_resize",))
    # The crash must land after formation + the first sharded commit
    # (else the reads-match-plan assertion is vacuous — a fresh start
    # reads nothing). Pure function of the seed, still.
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(inj, at=max(inj.at, 2.0))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    ckpt = os.path.join(substrate.work_root, "probe", "state.json")
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": GANG_TASK_ID,
                       "command": (
                           f"{sys.executable} -m batch_shipyard_tpu"
                           f".workloads.reshard_probe "
                           f"--steps {steps} "
                           f"--step-seconds {step_seconds} "
                           f"--dim {dim} "
                           f"--checkpoint-every {checkpoint_every} "
                           f"--ckpt {ckpt}"),
                       "environment_variables": {
                           "PYTHONPATH": repo_root},
                       "max_task_retries": 3,
                       "multi_instance": {
                           "num_instances": 2, "min_instances": 1,
                           "jax_distributed": {"enabled": False}}}],
        }]})
        started = time.monotonic()
        _submit_jobs(store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, None, report),
            daemon=True, name="chaos-resize-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=5.0)
        _check_resize_invariants(store, task_rows, ckpt, steps, dim,
                                 restore_plan, report)
    finally:
        substrate.stop_all()
    return report


def _resize_oracle(dim: int, steps: int) -> list[float]:
    """Pure replay of the probe's deterministic per-element update —
    state[i] after S steps is sum_{s=1..S} s*(i+1), accumulated the
    same way the probe accumulates it (bit-exactness is the claim)."""
    state = [0.0] * dim
    for step in range(steps):
        for i in range(dim):
            state[i] += float((step + 1) * (i + 1))
    return state


def _check_resize_invariants(store, task_rows: list, ckpt: str,
                             steps: int, dim: int, restore_plan,
                             report: dict) -> None:
    import json as json_mod

    invariants = report["invariants"]
    task = task_rows[0]
    invariants["state"] = task.get("state")
    assert task.get("state") == "completed", task
    invariants["gang_size"] = task.get(names.TASK_COL_GANG_SIZE)
    assert invariants["gang_size"] == 1, (
        f"gang did not resize to the surviving host: {task}")
    from batch_shipyard_tpu.goodput import events as gp_events
    resizes = [e for e in gp_events.query(store, POOL_ID)
               if e["kind"] == gp_events.GANG_RESIZE]
    assert resizes and \
        resizes[-1]["attrs"].get("new_size") == 1, resizes
    invariants["gang_resize_events"] = len(resizes)
    # Bit-exact params/opt-state: the committed final state (1 shard
    # covering the full vector) equals the pure replay oracle.
    with open(f"{ckpt}.s{steps}.shard0of1", encoding="utf-8") as fh:
        final = json_mod.load(fh)
    assert final["step"] == steps, final
    expected = _resize_oracle(dim, steps)
    assert final["values"] == expected, (
        "restored+resumed state is not bit-exact vs the oracle")
    invariants["state_bit_exact"] = True
    # The rerun read EXACTLY its per-host plan: 1 target host of a
    # 2-shard source — both shards, full slices, in order.
    with open(ckpt + ".reads.log", encoding="utf-8") as fh:
        read_lines = [ln.strip() for ln in fh if "i0of1" in ln]
    planned = restore_plan.host_reads(dim, 2, 1, 0)
    expected_reads = [
        f"shard={r.shard}of2 [{r.lo}..{r.hi})" for r in planned]
    got_reads = [" ".join(ln.split()[2:]) for ln in read_lines]
    invariants["planned_reads"] = expected_reads
    invariants["recorded_reads"] = got_reads
    assert got_reads[-len(expected_reads):] == expected_reads, (
        f"per-host reads diverge from the restore plan: "
        f"{got_reads} vs {expected_reads}")
    # Loss-trajectory oracle: every recorded commit loss matches the
    # pure replay at that (step, size) — instance 0's shard is the
    # first dim/size elements.
    with open(ckpt + ".loss.log", encoding="utf-8") as fh:
        losses = [ln.split() for ln in fh if ln.strip()]
    assert losses, "no loss trajectory recorded"
    for entry in losses:
        rec = dict(part.split("=", 1) for part in entry)
        step, size = int(rec["step"]), int(rec["size"])
        shard = _resize_oracle(dim, step)[: dim // size]
        assert abs(float(rec["loss"]) - sum(shard)) < 1e-6, (
            f"loss trajectory diverged at {rec}")
    invariants["loss_trajectory_ok"] = True
    # No orphaned coordination state; partition exact.
    _await_no_gang_rows(store, invariants)
    pool_report = _assert_partition_exact(store, POOL_ID, invariants)
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }
    invariants["ok"] = True


POOL_A = "drill-pool-a"
POOL_B = "drill-pool-b"
FED_ID = "drill-fed"


def run_migration_drill(seed: int = 0, steps: int = 60,
                        step_seconds: float = 0.06,
                        checkpoint_every: int = 10,
                        duration: float = 5.0,
                        wait_timeout: float = 120.0) -> dict:
    """Cross-pool migration drill: two fakepod pools in one
    federation; a gang job is federation-scheduled onto one, runs
    past its first COMMITTED barrier, then a seeded
    ``pool_capacity_loss`` injection crashes EVERY node of that pool
    (no revive). Only the federation's elastic evaluator can finish
    the job: it reclaims the stranded tasks, observes the starvation
    past the grace window, and atomically re-targets the job onto the
    sibling pool — where the gang re-forms, restores from the shared
    COMMITTED barrier, and completes. Asserts:

      * the job completed on the SIBLING pool with the locator row
        re-pointed (etag-claimed migration),
      * zero lost steps: the rerun resumed from a cadenced COMMITTED
        barrier (the step ledger proves it),
      * ONE trace spans the migration: the completed task's rows
        carry the original trace id, and a gang_migrate span under
        that trace records the move,
      * the ``migration`` badput leg is populated on the destination
        and its goodput partition stays exact,
      * no orphaned gang rows anywhere (source partitions retired by
        the migration itself — the source pool has no agents left to
        janitor them)."""
    from batch_shipyard_tpu.federation import federation as fed_mod
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=2.0)
    substrate.agent_kwargs = {
        "claim_visibility_seconds": 3.0, "gang_sweep_interval": 1.0,
        "gang_timeout": 15.0, "retry_backoff_base": 0.2,
        "retry_backoff_cap": 1.0}
    plan = ChaosPlan.generate(seed, duration=duration, num_nodes=2,
                              kinds=("pool_capacity_loss",))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    ckpt = os.path.join(substrate.work_root, "probe", "state.json")
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    processor = fed_mod.FederationProcessor(
        store, poll_interval=0.2, elastic_interval=0.5,
        elastic_grace_seconds=0.8, node_stale_seconds=2.0)
    proc_thread = threading.Thread(target=processor.run,
                                   daemon=True, name="fed-proc")
    try:
        for pool_id in (POOL_A, POOL_B):
            conf = {"pool_specification": {
                "id": pool_id, "substrate": "fake",
                "vm_configuration": {"vm_count": {"dedicated": 2}},
                "task_slots_per_node": 1,
                "max_wait_time_seconds": 60}}
            pool_mgr.create_pool(
                store, substrate, settings_mod.pool_settings(conf),
                settings_mod.global_settings({}), conf)
        fed_mod.create_federation(store, FED_ID)
        fed_mod.add_pool_to_federation(store, FED_ID, POOL_A)
        fed_mod.add_pool_to_federation(store, FED_ID, POOL_B)
        proc_thread.start()
        started = time.monotonic()
        fed_mod.submit_job_to_federation(store, FED_ID, {
            "job_specifications": [{
                "id": JOB_ID,
                "tasks": [{"id": GANG_TASK_ID,
                           "command": (
                               f"{sys.executable} -m "
                               f"batch_shipyard_tpu.workloads"
                               f".preempt_probe "
                               f"--steps {steps} "
                               f"--step-seconds {step_seconds} "
                               f"--checkpoint-every "
                               f"{checkpoint_every} "
                               f"--ckpt {ckpt}"),
                           "environment_variables": {
                               "PYTHONPATH": repo_root},
                           "max_task_retries": 3,
                           "multi_instance": {
                               "num_instances": 2,
                               "min_instances": 2,
                               "jax_distributed": {
                                   "enabled": False}}}],
            }]})
        # Resolve where the scheduler placed the job (the injection
        # targets THAT pool), then hold the seeded injection until
        # the gang has committed once — the zero-lost-steps claim is
        # about resuming a barrier, not starting over.
        src = _wait_for(lambda: _located_pool(store, fed_mod),
                        30.0, "federation placement")
        report["source_pool"] = src
        _wait_for(lambda: os.path.exists(ckpt + ".COMMITTED")
                  or None, 60.0, "first committed barrier")
        trace_id = jobs_mgr.get_task(
            store, src, JOB_ID, GANG_TASK_ID).get("trace_id")
        report["trace_id"] = trace_id
        for injection in plan.injections:
            delay = injection.at - (time.monotonic() - started)
            if delay > 0:
                time.sleep(delay)
            try:
                record = injectors_mod.apply_injection(
                    injection, substrate, src)
            except Exception as exc:  # noqa: BLE001 - record it
                record = {"kind": injection.kind, "error": str(exc)}
            logger.info("chaos injection %s", record)
            report["applied"].append(record)
        task_rows = jobs_mgr.wait_for_tasks(
            store, POOL_B if src == POOL_A else POOL_A, JOB_ID,
            timeout=wait_timeout, poll_interval=0.25)
        _check_migration_invariants(store, fed_mod, task_rows, ckpt,
                                    steps, checkpoint_every, src,
                                    trace_id, report)
    finally:
        processor.stop_event.set()
        if proc_thread.is_alive():
            proc_thread.join(timeout=5.0)
        substrate.stop_all()
    return report


def _located_pool(store, fed_mod):
    try:
        return fed_mod.locate_federation_job(store, FED_ID, JOB_ID)
    except ValueError:
        return None


def _wait_for(probe, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = probe()
        if value:
            return value
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def _check_migration_invariants(store, fed_mod, task_rows: list,
                                ckpt: str, steps: int,
                                checkpoint_every: int, src: str,
                                trace_id, report: dict) -> None:
    invariants = report["invariants"]
    dst = POOL_B if src == POOL_A else POOL_A
    locator = store.get_entity(names.TABLE_FEDJOBS, FED_ID, JOB_ID)
    invariants["migrated_to"] = locator.get("pool_id")
    invariants["migrated_from"] = locator.get("migrated_from")
    assert locator.get("pool_id") == dst, locator
    assert locator.get("migrated_from") == src, locator
    task = task_rows[0]
    invariants["state"] = task.get("state")
    assert task.get("state") == "completed", task
    # One trace spans the migration: the task rows moved verbatim, so
    # the completed row still carries the submission's trace id, and
    # the migration span was recorded under it.
    invariants["trace_id_preserved"] = (
        task.get("trace_id") == trace_id and trace_id is not None)
    assert invariants["trace_id_preserved"], (
        f"trace broke across the migration: {task.get('trace_id')} "
        f"!= {trace_id}")
    from batch_shipyard_tpu.trace import spans as trace_spans
    migrate_spans = [
        s for s in trace_spans.query(store, dst)
        if s.get("kind") == trace_spans.SPAN_GANG_MIGRATE]
    assert migrate_spans and \
        migrate_spans[0].get("trace_id") == trace_id, migrate_spans
    invariants["gang_migrate_spans"] = len(migrate_spans)
    # Zero lost steps: the rerun resumed from a cadenced COMMITTED
    # barrier (the first attempt was hard-crashed — no drain line —
    # so the single completed line's start IS the barrier).
    with open(ckpt + ".steps.log", encoding="utf-8") as fh:
        ledger = [line.split() for line in fh if line.strip()]
    invariants["step_ledger"] = [" ".join(parts) for parts in ledger]
    assert ledger[-1][2] == "completed", invariants["step_ledger"]
    resume_lo, resume_hi = (int(x) for x in
                            ledger[-1][1].split(".."))
    invariants["resumed_from"] = resume_lo
    assert resume_hi == steps, invariants["step_ledger"]
    assert resume_lo > 0 and resume_lo % checkpoint_every == 0, (
        f"rerun did not resume from a committed barrier: "
        f"{invariants['step_ledger']}")
    # Migration leg populated on the destination; partition exact.
    pool_report = _assert_partition_exact(store, dst, invariants)
    migration = pool_report["badput_seconds"].get("migration", 0.0)
    invariants["migration_seconds"] = migration
    assert migration > 0.0, (
        f"migration leg not populated: "
        f"{pool_report['badput_seconds']}")
    # No orphaned gang rows ANYWHERE: the migration retired the
    # source partitions itself (no live janitor remains there).
    _await_no_gang_rows(store, invariants)
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }
    invariants["ok"] = True


def run_store_outage_drill(seed: int = 0, tasks: int = 6,
                           outage: float = 2.0,
                           task_sleep: float = 1.0,
                           duration: float = 6.0,
                           wait_timeout: float = 120.0) -> dict:
    """Store-outage ride-through drill: agents run on the resilient
    wrapper (state/resilient.py) over a chaos store, and a seeded
    ``store_outage`` injection takes the store DOWN for a sustained
    window mid-run — every op fails, not a per-op burst. Asserts the
    control-plane acceptance invariants:

      * every task completed with ZERO retries — the outage never
        killed or requeued running work (critical ops rode it out),
      * zero lost advisory events: exactly one TASK_QUEUED and one
        TASK_RUNNING interval per task survive into the store (the
        WAL journaled what the outage would have dropped and
        replayed it in order),
      * the ``store_outage`` badput leg is populated with the exact
        outage window and the journal actually replayed entries,
      * every agent's journal drained to zero after recovery,
      * the goodput partition stayed exact ACROSS the outage."""
    from batch_shipyard_tpu.goodput import events as gp_events
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    raw_store = MemoryStateStore()
    chaos_store = injectors_mod.ChaosStore(raw_store)
    substrate = FakePodSubstrate(chaos_store,
                                 heartbeat_interval=0.2,
                                 node_stale_seconds=30.0)
    substrate.agent_kwargs = {
        "claim_visibility_seconds": 5.0,
        "gang_sweep_interval": 1.0,
        # THE knob under test: the resilient wrapper, tuned for a
        # seconds-scale drill (production keeps the defaults).
        "resilience": {"retry_base": 0.05, "retry_cap": 0.5,
                       "probe_interval": 0.25,
                       "max_outage_seconds": 60.0}}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 2}},
        "task_slots_per_node": 2,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    plan = ChaosPlan.generate(seed, duration=duration, num_nodes=2,
                              kinds=("store_outage",))
    # Deterministic sequencing: the outage must land with work in
    # flight (claims made, tasks running) and last the configured
    # window. Pure function of the seed, still.
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(
            inj, at=min(max(inj.at, 1.2), 2.0),
            params=tuple(sorted(
                {**dict(inj.params), "window": outage}.items())))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    try:
        pool_mgr.create_pool(raw_store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": f"t{i:03d}",
                       "command": (f"sleep {task_sleep} && "
                                   f"echo outage-{i}"),
                       "max_task_retries": 3}
                      for i in range(tasks)],
        }]})
        started = time.monotonic()
        _submit_jobs(raw_store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, chaos_store, report),
            daemon=True, name="chaos-outage-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            raw_store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=5.0)
        invariants = report["invariants"]
        states = {}
        total_retries = 0
        for task in task_rows:
            states[task.get("state")] = \
                states.get(task.get("state"), 0) + 1
            total_retries += int(task.get("retries", 0) or 0)
        invariants["tasks"] = states
        assert states == {"completed": tasks}, states
        invariants["retries"] = total_retries
        assert total_retries == 0, (
            f"the outage cost retries: {total_retries}")
        # Zero lost advisory events: with zero retries there is
        # EXACTLY one queued + one running interval per task — any
        # event the outage swallowed breaks the count.
        events = gp_events.query(raw_store, POOL_ID)
        queued = [e for e in events
                  if e["kind"] == gp_events.TASK_QUEUED]
        running = [e for e in events
                   if e["kind"] == gp_events.TASK_RUNNING]
        invariants["queued_events"] = len(queued)
        invariants["running_events"] = len(running)
        assert len(queued) == tasks, (
            f"lost queued intervals: {len(queued)} != {tasks}")
        assert len(running) == tasks, (
            f"lost running intervals: {len(running)} != {tasks}")
        outages = [e for e in events
                   if e["kind"] == gp_events.STORE_OUTAGE]
        invariants["outage_events"] = len(outages)
        assert outages, "no store_outage interval was recorded"
        replayed = sum(int((e.get("attrs") or {})
                           .get("replayed", 0)) for e in outages)
        invariants["journal_replayed"] = replayed
        assert replayed >= 1, (
            "the WAL never buffered anything — the outage was "
            "vacuous")
        # Journals drained on every agent.
        deadline = time.monotonic() + 15.0
        backlog = None
        while time.monotonic() < deadline:
            backlog = sum(
                agent.store.journal_backlog()
                for agent in injectors_mod._live_agents(substrate,
                                                        POOL_ID))
            if backlog == 0:
                break
            time.sleep(0.2)
        invariants["journal_backlog"] = backlog
        assert backlog == 0, f"undrained WAL backlog: {backlog}"
        pool_report = _assert_partition_exact(raw_store, POOL_ID,
                                              invariants)
        leg = pool_report["badput_seconds"].get("store_outage", 0.0)
        invariants["store_outage_seconds"] = leg
        assert leg > 0.0, (
            f"store_outage leg not populated: "
            f"{pool_report['badput_seconds']}")
        report["goodput"] = {
            "goodput_ratio": pool_report["goodput_ratio"],
            "badput_seconds": pool_report["badput_seconds"],
        }
        invariants["ok"] = True
    finally:
        substrate.stop_all()
    return report


def run_leader_partition_drill(seed: int = 0,
                               victim_steps: int = 140,
                               step_seconds: float = 0.05,
                               wait_timeout: float = 120.0) -> dict:
    """Leader-partition drill: the preempt-sweep LEADER's heartbeats
    and lease renewals stall (its sweep loop keeps running — the
    exact shape the old heartbeat-freshness election double-fired
    under) while a starved high-priority task is waiting. Asserts
    the lease acceptance invariants:

      * exactly ONE preemption stamp fired across the leadership
        change (zero double-fired stamps: the deposed leader
        abdicated on its own clock before the successor could act),
      * the stamp carries the SUCCESSOR's fencing epoch — strictly
        newer than the pre-partition term — and that epoch is the
        one live term at drill end (exactly one local lease holder),
      * the victim drained cooperatively with its retry budget
        untouched; every task completed; partition exact."""
    from batch_shipyard_tpu.state import leases as state_leases
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=5.0)
    substrate.agent_kwargs = {
        "claim_visibility_seconds": 5.0,
        "gang_sweep_interval": 1.0,
        # Sweep fast, short lease: failover must fit the drill
        # window. Grace doubles as the starvation threshold, so it
        # must EXCEED the partitioned leader's residual authority
        # (one lease duration) — the stamp then provably belongs to
        # the successor's term.
        "preempt_sweep_interval": 0.8,
        "preempt_grace_seconds": 2.0,
        "leader_lease_seconds": 1.0,
        "job_state_ttl": 0.2}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 2}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    plan = ChaosPlan.generate(seed, duration=6.0, num_nodes=2,
                              kinds=("leader_partition",))
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(inj, params=tuple(sorted(
            {**dict(inj.params), "window": 4.0}.items())))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    epoch_key = names.leader_epoch_key(
        POOL_ID, state_leases.ROLE_PREEMPT_SWEEP)
    ckpt = os.path.join(substrate.work_root, "probe", "state.json")
    repo_root = str(pathlib.Path(__file__).resolve().parents[2])
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        victims = settings_mod.job_settings_list(
            {"job_specifications": [{
                "id": "victims",
                # Long enough that the stamp — landing AFTER the
                # grace window + the leadership failover — always
                # finds its victim still running with drain runway:
                # a victim finishing naturally before the drain
                # races would make the preemption vacuous.
                # priority -1: victims live in the LO queue band, so
                # the starved task's normal-band message — which the
                # worker scan never idle-skips — deterministically
                # wins the freed slot ahead of the drained victim's
                # own requeue. (With both in the same band, the
                # rerun can win the race and the sweep legitimately
                # re-stamps each interval — correct behavior, but it
                # would make the exactly-one-stamp assertion about
                # claim-race luck instead of leadership.)
                "tasks": [{"id": f"v{i}",
                           "command": (
                               f"{sys.executable} -m "
                               f"batch_shipyard_tpu.workloads"
                               f".preempt_probe "
                               f"--steps {victim_steps} "
                               f"--step-seconds {step_seconds} "
                               f"--checkpoint-every 10 "
                               f"--ckpt {ckpt}.v{i}"),
                           "environment_variables": {
                               "PYTHONPATH": repo_root},
                           "priority": -1,
                           "max_task_retries": 3}
                          for i in range(2)],
            }]})
        _submit_jobs(store, pool, victims)
        # Both victims running + a preempt-sweep term recorded: only
        # then is "partition the leader" well-defined.
        _wait_for(
            lambda: (sum(1 for t in jobs_mgr.list_tasks(
                store, POOL_ID, "victims")
                if t.get("state") == "running") == 2) or None,
            30.0, "both victims running")
        before = _wait_for(
            lambda: state_leases.read_leader(store, epoch_key),
            30.0, "preempt-sweep leadership term")
        report["leader_before"] = before
        hi = settings_mod.job_settings_list({"job_specifications": [{
            "id": "hi",
            "tasks": [{"id": "h0", "command": "echo placed",
                       "priority": 0, "max_task_retries": 2}],
        }]})
        _submit_jobs(store, pool, hi)
        # Partition the leader NOW — before the starvation grace can
        # elapse — so the stamp decision crosses the failover.
        for injection in plan.injections:
            try:
                record = injectors_mod.apply_injection(
                    injection, substrate, POOL_ID)
            except Exception as exc:  # noqa: BLE001 - record it
                record = {"kind": injection.kind, "error": str(exc)}
            logger.info("chaos injection %s", record)
            report["applied"].append(record)
        hi_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, "hi", timeout=wait_timeout,
            poll_interval=0.25)
        victim_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, "victims", timeout=wait_timeout,
            poll_interval=0.25)
        _check_partition_invariants(
            store, substrate, state_leases, epoch_key, before,
            hi_rows, victim_rows, report)
    finally:
        substrate.stop_all()
    return report


def _check_partition_invariants(store, substrate, state_leases,
                                epoch_key: str, before: dict,
                                hi_rows: list, victim_rows: list,
                                report: dict) -> None:
    from batch_shipyard_tpu.goodput import events as gp_events
    invariants = report["invariants"]
    assert hi_rows[0].get("state") == "completed", hi_rows[0]
    states = {t["_rk"]: t.get("state") for t in victim_rows}
    invariants["victim_states"] = states
    assert all(s == "completed" for s in states.values()), states
    # ZERO double-fired stamps: exactly one preemption notice across
    # the whole drill, leadership change included.
    notices = [e for e in gp_events.query(store, POOL_ID)
               if e["kind"] == gp_events.TASK_PREEMPT_NOTICE]
    invariants["preempt_notices"] = len(notices)
    fired = [(n.get("job_id"), n.get("task_id"), n.get("attrs"))
             for n in notices]
    assert len(notices) == 1, (
        f"double-fired preemption stamps under partition: {fired}")
    # The stamp belongs to the SUCCESSOR's term: its fencing epoch
    # is strictly newer than the pre-partition term and matches the
    # term live at drill end.
    after = state_leases.read_leader(store, epoch_key)
    report["leader_after"] = after
    invariants["epoch_before"] = before["epoch"]
    invariants["epoch_after"] = after["epoch"]
    assert after["epoch"] > before["epoch"], (
        f"no leadership term change: {before} -> {after}")
    assert after.get("owner") != before.get("owner"), (
        f"the partitioned leader kept the lease: {after}")
    stamp_epoch = (notices[0].get("attrs") or {}).get("leader_epoch")
    invariants["stamp_epoch"] = stamp_epoch
    assert stamp_epoch == after["epoch"], (
        f"stamp epoch {stamp_epoch} is not the successor term "
        f"{after['epoch']} — a deposed leader fired it")
    # Exactly one LIVE lease holder at drill end.
    holders = [
        agent.identity.node_id
        for agent in injectors_mod._live_agents(substrate, POOL_ID)
        if (lease := agent._sweep_leases.get(
            state_leases.ROLE_PREEMPT_SWEEP)) is not None
        and lease.held_locally()]
    invariants["lease_holders"] = holders
    assert len(holders) == 1, (
        f"not exactly one live lease epoch: holders={holders}")
    # The preempted victim paid NO retry budget; the other victim
    # was never touched.
    preempted = [t for t in victim_rows
                 if int(t.get(names.TASK_COL_PREEMPT_COUNT, 0)
                        or 0) > 0]
    invariants["victims_preempted"] = len(preempted)
    assert len(preempted) == 1, (
        f"expected exactly one preempted victim: {states}")
    assert int(preempted[0].get("retries", 0) or 0) == 0, (
        f"preemption consumed retry budget: {preempted[0]}")
    pool_report = _assert_partition_exact(store, POOL_ID, invariants)
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }
    invariants["ok"] = True


def run_agent_restart_drill(seed: int = 0, task_sleep: float = 2.5,
                            wait_timeout: float = 120.0) -> dict:
    """Agent crash-restart adoption drill: a seeded ``agent_restart``
    injection kills the agent PROCESS under a running task — no
    offline write, no lease release, every in-flight completion path
    abandoned — while the task's own session keeps running; the
    revived agent on the same work_dir must re-adopt it from the
    slot ledger. Asserts the adoption acceptance invariants:

      * the task ran EXACTLY once (its start marker appears once —
        adoption, not the reclaim-rerun path) and completed with
        retries == 0,
      * the adopted completion ran the full exit path (stdout
        uploaded),
      * the ``adoption`` badput leg is populated (the control-plane
        gap: last pre-crash heartbeat -> re-adoption) and a
        SPAN_AGENT_RESTART span joined the task's trace,
      * node health neutral (an agent crash says nothing about the
        task), queues drained, partition exact."""
    from batch_shipyard_tpu.goodput import events as gp_events
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    from batch_shipyard_tpu.trace import spans as trace_spans

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=5.0)
    substrate.agent_kwargs = {"claim_visibility_seconds": 3.0,
                              "gang_sweep_interval": 1.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    plan = ChaosPlan.generate(seed, duration=4.0, num_nodes=1,
                              kinds=("agent_restart",))
    # The crash must land while the task RUNS (claimed within
    # ~0.3s; finishes at ~task_sleep) and the revival must leave
    # adoption runway. Pure function of the seed, still.
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(
            inj, at=min(max(inj.at, 0.8), task_sleep - 1.0),
            params=tuple(sorted(
                {**dict(inj.params),
                 "revive_after": max(0.4, inj.param(
                     "revive_after", 0.5))}.items())))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    probe_dir = os.path.join(substrate.work_root, "probe")
    starts_log = os.path.join(probe_dir, "starts.log")
    try:
        os.makedirs(probe_dir, exist_ok=True)
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": JOB_ID,
            "tasks": [{"id": "t0",
                       "command": (f"echo start-$$ >> {starts_log} "
                                   f"&& sleep {task_sleep} && "
                                   f"echo adopted-done"),
                       "max_task_retries": 2}],
        }]})
        started = time.monotonic()
        _submit_jobs(store, pool, jobs)
        driver = threading.Thread(
            target=_inject_schedule,
            args=(plan, started, substrate, None, report),
            daemon=True, name="chaos-restart-driver")
        driver.start()
        task_rows = jobs_mgr.wait_for_tasks(
            store, POOL_ID, JOB_ID, timeout=wait_timeout,
            poll_interval=0.25)
        driver.join(timeout=5.0)
        invariants = report["invariants"]
        task = task_rows[0]
        invariants["state"] = task.get("state")
        assert task.get("state") == "completed", task
        invariants["retries"] = int(task.get("retries", 0) or 0)
        assert invariants["retries"] == 0, (
            f"the restart cost retries (reclaim-rerun, not "
            f"adoption): {task}")
        assert any(r.get("applied") for r in report["applied"]), (
            f"agent_restart never applied: {report['applied']}")
        # Exactly ONE start: the process ran THROUGH the restart.
        with open(starts_log, encoding="utf-8") as fh:
            starts = [ln for ln in fh.read().splitlines() if ln]
        invariants["task_starts"] = len(starts)
        assert len(starts) == 1, (
            f"task re-ran instead of being adopted: {starts}")
        # The adopted completion ran the full exit path.
        out = jobs_mgr.get_task_output(store, POOL_ID, JOB_ID, "t0")
        assert out.strip() == b"adopted-done", out
        # Adoption leg + trace span.
        adoptions = [e for e in gp_events.query(store, POOL_ID)
                     if e["kind"] == gp_events.TASK_ADOPTION]
        invariants["adoption_events"] = len(adoptions)
        assert adoptions, "no adoption interval was recorded"
        assert all(float(e["end"]) > float(e["start"])
                   for e in adoptions), adoptions
        restart_spans = [
            s for s in trace_spans.query(store, POOL_ID)
            if s.get("kind") == trace_spans.SPAN_AGENT_RESTART]
        invariants["agent_restart_spans"] = len(restart_spans)
        assert restart_spans, "no SPAN_AGENT_RESTART recorded"
        # Neutral health: an agent crash says nothing about the node
        # or the task.
        for node in store.query_entities(names.TABLE_NODES,
                                         partition_key=POOL_ID):
            health = float(node.get(names.NODE_COL_HEALTH, 1.0)
                           or 1.0)
            assert health >= 1.0, (
                f"adoption debited node health: "
                f"{node['_rk']}={health}")
            assert not node.get(names.NODE_COL_QUARANTINED), node
        invariants["node_health_untouched"] = True
        # Queues drain once the redelivered message meets the
        # terminal entity.
        deadline = time.monotonic() + 30.0
        queues = names.task_queues(POOL_ID, 1)
        depth = None
        while time.monotonic() < deadline:
            depth = sum(store.queue_length(q) for q in queues)
            if depth == 0:
                break
            time.sleep(0.25)
        invariants["queue_depth"] = depth
        assert depth == 0, f"undrained task queues: {depth}"
        pool_report = _assert_partition_exact(store, POOL_ID,
                                              invariants)
        leg = pool_report["badput_seconds"].get("adoption", 0.0)
        invariants["adoption_seconds"] = leg
        assert leg > 0.0, (
            f"adoption leg not populated: "
            f"{pool_report['badput_seconds']}")
        report["goodput"] = {
            "goodput_ratio": pool_report["goodput_ratio"],
            "badput_seconds": pool_report["badput_seconds"],
        }
        invariants["ok"] = True
    finally:
        substrate.stop_all()
    return report


def _inject_schedule(plan: ChaosPlan, started: float, substrate,
                     chaos_store, report: dict) -> None:
    for injection in plan.injections:
        delay = injection.at - (time.monotonic() - started)
        if delay > 0:
            time.sleep(delay)
        try:
            record = injectors_mod.apply_injection(
                injection, substrate, POOL_ID, store=chaos_store)
        except Exception as exc:  # noqa: BLE001 - record, keep going
            record = {"kind": injection.kind, "error": str(exc)}
        logger.info("chaos injection %s", record)
        report["applied"].append(record)


def _check_invariants(store, task_rows: list, expected: int,
                      report: dict) -> None:
    invariants = report["invariants"]
    # 1. Every task completed (exactly the expected set, each once —
    # entities are unique by id, so completion is single-valued).
    states: dict = {}
    for task in task_rows:
        states[task.get("state")] = states.get(task.get("state"), 0) + 1
    invariants["tasks"] = states
    assert states == {"completed": expected + 1}, (
        f"drill tasks not all completed: {states}")
    # 2. Exactly-once effects: the final output of each task is its
    # single line (a double-completed task would have been re-run
    # after success and is a claim-protocol bug).
    for task in task_rows:
        task_id = task["_rk"]
        if task_id == GANG_TASK_ID:
            # Gang instance 0's final output holds its single line
            # (a recovered attempt overwrites the same key, so this
            # checks the LAST attempt ran cleanly).
            out = jobs_mgr.get_task_output(
                store, POOL_ID, JOB_ID, task_id, instance=0)
            assert out.strip() == b"drill-gang", (
                f"{task_id}: unexpected gang output {out!r}")
            continue
        index = int(task_id[1:])
        out = jobs_mgr.get_task_output(store, POOL_ID, JOB_ID, task_id)
        assert out.strip() == f"drill-{index}".encode(), (
            f"{task_id}: unexpected output {out!r}")
    # 3. No orphaned coordination state: gang rows are gone and the
    # task queues drain, each within a bounded window (terminal-task
    # messages get deleted on next delivery; a gang cleanup lost to
    # an injected store fault is repaired by the agents' orphan
    # janitor sweep). The workload's gang task guarantees gang rows
    # EXISTED during the drill, so an empty table here proves
    # cleanup, not absence of gangs.
    deadline = time.monotonic() + 30.0
    queues = names.task_queues(POOL_ID, 1)
    while True:
        leftover_gangs = list(store.query_entities(names.TABLE_GANGS))
        depth = sum(store.queue_length(q) for q in queues)
        if (not leftover_gangs and depth == 0) or \
                time.monotonic() >= deadline:
            break
        time.sleep(0.25)
    invariants["orphaned_gang_rows"] = len(leftover_gangs)
    assert not leftover_gangs, leftover_gangs
    invariants["queue_depth"] = depth
    assert depth == 0, f"undrained task queues: {depth} messages"
    # 4. Goodput partition exactness: chaos moves time between
    # categories; it must never create or lose a second.
    pool_report = _assert_partition_exact(store, POOL_ID, invariants)
    invariants["retries"] = pool_report.get("retries", 0)
    invariants["backoff_seconds"] = (
        pool_report["badput_seconds"].get("backoff", 0.0))
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
        "overlapped_seconds": pool_report["overlapped_seconds"],
    }
    invariants["ok"] = True

"""VM-backed provisioning: GCE helper, remotefs lifecycle verbs,
monitoring VM, slurm control plane + munge distribution, and the
fake-substrate slurm resume->join->suspend end-to-end path."""

import pytest

from batch_shipyard_tpu.state.base import NotFoundError
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.gce_vm import GceVmError, GceVmManager


class FakeRunner:
    """Records gcloud argvs; answers describe queries."""

    def __init__(self):
        self.calls = []
        self.fail_next = None
        self.status = "RUNNING"

    def __call__(self, argv, **_kw):
        self.calls.append(list(argv))
        # Snapshot startup scripts now — create_vm deletes the temp
        # file (it can embed secrets) before returning.
        for arg in argv:
            if arg.startswith("--metadata-from-file=startup-script="):
                with open(arg.split("=", 2)[2],
                          encoding="utf-8") as fh:
                    self.startup_scripts = getattr(
                        self, "startup_scripts", [])
                    self.startup_scripts.append(fh.read())
        if self.fail_next:
            msg, self.fail_next = self.fail_next, None
            return 1, "", msg
        joined = " ".join(argv)
        if "describe" in joined and "networkIP" in joined:
            return 0, "10.0.0.5\n", ""
        if "describe" in joined and "status" in joined:
            return 0, f"{self.status}\n", ""
        return 0, "", ""

    def verbs(self):
        return [c[2] + ":" + c[3] for c in self.calls]


@pytest.fixture()
def vms():
    runner = FakeRunner()
    return GceVmManager("proj", zone="us-central1-a",
                        runner=runner), runner


def test_gce_vm_create_and_lifecycle(vms):
    mgr, runner = vms
    ip = mgr.create_vm("vm1", "e2-standard-2",
                       startup_script="#!/bin/bash\necho hi\n",
                       disks=[("d0", "data0")], tags=("t1",))
    assert ip == "10.0.0.5"
    create = runner.calls[0]
    assert "--machine-type=e2-standard-2" in create
    assert "--tags=t1" in create
    assert any(a.startswith("--metadata-from-file=startup-script=")
               for a in create)
    assert "name=d0,device-name=data0,mode=rw" in create
    assert "--project=proj" in create and "--zone=us-central1-a" in \
        create
    mgr.stop_vm("vm1")
    mgr.set_machine_type("vm1", "e2-standard-8")
    mgr.start_vm("vm1")
    assert mgr.vm_status("vm1") == "RUNNING"
    mgr.delete_vm("vm1")
    assert "instances:stop" in runner.verbs()
    assert "instances:set-machine-type" in runner.verbs()


def test_gce_vm_error_surface(vms):
    mgr, runner = vms
    runner.fail_next = "quota exceeded"
    with pytest.raises(GceVmError, match="quota exceeded"):
        mgr.create_disk("d1", 100)


# ----------------------------- remotefs --------------------------------


def test_remotefs_full_lifecycle():
    from batch_shipyard_tpu.remotefs import manager as remotefs

    store = MemoryStateStore()
    runner = FakeRunner()
    mgr = GceVmManager("proj", zone="z", runner=runner)
    remotefs.create_storage_cluster_record(store, "fsA", disk_count=2,
                                           disk_size_gb=128)
    remotefs.provision_nfs_server(store, "fsA", "proj", vms=mgr)
    st = remotefs.storage_cluster_status(store, "fsA", vms=mgr)
    assert st["cluster"]["state"] == "provisioned"
    assert st["nodes"][0]["internal_ip"] == "10.0.0.5"
    assert st["vm_status"] == "RUNNING"
    # disk creates: 2 disks then instance create
    assert runner.verbs()[:3] == ["disks:create", "disks:create",
                                  "instances:create"]

    remotefs.suspend_storage_cluster(store, "fsA", "proj", vms=mgr)
    assert remotefs.get_storage_cluster(store, "fsA")["state"] == \
        "suspended"
    remotefs.start_storage_cluster(store, "fsA", "proj", vms=mgr)
    assert remotefs.get_storage_cluster(store, "fsA")["state"] == \
        "provisioned"

    remotefs.resize_storage_cluster(store, "fsA", "n2-standard-16",
                                    "proj", vms=mgr)
    cluster = remotefs.get_storage_cluster(store, "fsA")
    assert cluster["vm_size"] == "n2-standard-16"
    # resize = stop, set-machine-type, start
    seq = runner.verbs()
    i = seq.index("instances:set-machine-type")
    assert seq[i - 1] == "instances:stop"
    assert seq[i + 1] == "instances:start"

    script = remotefs.expand_storage_cluster_live(
        store, "fsA", 2, "proj", vms=mgr)
    assert "mdadm --grow /dev/md0 --raid-devices=4" in script
    assert "resize2fs" in script
    assert remotefs.get_storage_cluster(store, "fsA")["disk_count"] == 4
    assert seq.count("disks:create") == 2  # before expand
    assert runner.verbs().count("instances:attach-disk") == 2


def test_nfs_bootstrap_stripes_multiple_disks():
    from batch_shipyard_tpu.remotefs import manager as remotefs
    script = remotefs.generate_nfs_bootstrap_script(
        {"disk_count": 3, "export_path": "/export/x"})
    assert "--raid-devices=3" in script
    assert "google-data2" in script


# ------------------------------ monitor --------------------------------


def test_monitor_vm_provision_and_destroy():
    from batch_shipyard_tpu.monitor import provision
    from batch_shipyard_tpu.state import names

    store = MemoryStateStore()
    runner = FakeRunner()
    mgr = GceVmManager("proj", runner=runner)
    ip = provision.provision_monitoring_vm(store, "proj", vms=mgr,
                                           grafana_port=3001)
    assert ip == "10.0.0.5"
    rec = store.get_entity(names.TABLE_MONITOR, "vms",
                           "shipyard-monitor")
    assert rec["state"] == "running"
    # The startup script ships the bundle as a base64 tarball and
    # enables the systemd unit.
    import re
    script = runner.startup_scripts[0]
    assert "base64 -d" in script and "tar -xz" in script
    assert "systemctl enable --now shipyard-monitoring.service" in \
        script
    assert re.search(r"echo '[A-Za-z0-9+/=]{100,}'", script)

    provision.destroy_monitoring_vm(store, "proj", vms=mgr)
    with pytest.raises(NotFoundError):
        store.get_entity(names.TABLE_MONITOR, "vms",
                         "shipyard-monitor")


def test_monitor_tls_bundle_binds_loopback(tmp_path):
    from batch_shipyard_tpu.monitor import provision
    bundle = provision.generate_monitoring_bundle(
        str(tmp_path), lets_encrypt_fqdn="mon.example.com")
    compose = (tmp_path / "docker-compose.yml").read_text()
    assert '"127.0.0.1:3000:3000"' in compose
    assert '"127.0.0.1:9090:9090"' in compose
    assert "nginx" in compose


def test_monitor_plain_bundle_publishes_ports(tmp_path):
    from batch_shipyard_tpu.monitor import provision
    provision.generate_monitoring_bundle(str(tmp_path))
    compose = (tmp_path / "docker-compose.yml").read_text()
    assert '"3000:3000"' in compose
    assert "127.0.0.1" not in compose


# ------------------------------- slurm ---------------------------------


def test_munge_key_publish_fetch_roundtrip():
    from batch_shipyard_tpu.slurm import provision as sp

    store = MemoryStateStore()
    sp.publish_munge_key(store, "c1", b"\x01\x02keybytes")
    assert sp.fetch_munge_key(store, "c1", timeout=1.0) == \
        b"\x01\x02keybytes"
    with pytest.raises(TimeoutError):
        sp.fetch_munge_key(store, "other", timeout=0.2,
                           poll_interval=0.05)


def test_slurm_config_generators():
    from batch_shipyard_tpu.slurm import provision as sp

    dbd = sp.generate_slurmdbd_conf("ctrl0", "pw123")
    assert "DbdHost=ctrl0" in dbd
    assert "StoragePass=pw123" in dbd
    assert "accounting_storage/mysql" in dbd
    sql = sp.generate_db_init_sql("pw123")
    assert "slurm_acct_db" in sql and "pw123" in sql
    wrappers = sp.generate_power_save_wrappers()
    assert set(wrappers) == {"slurm_resume.sh", "slurm_suspend.sh",
                             "slurm_resume_fail.sh"}
    assert "scontrol show hostnames" in wrappers["slurm_resume.sh"]
    assert "slurm resume" in wrappers["slurm_resume.sh"]
    assert "slurm suspend" in wrappers["slurm_resume_fail.sh"]


def test_slurm_controller_bootstrap_contents():
    from batch_shipyard_tpu.slurm import provision as sp

    conf = "ClusterName=c1\n"
    script = sp.generate_controller_bootstrap("c1", conf, "pw")
    for needle in ("slurmctld", "mariadb-server", "slurmdbd",
                   "publish-munge-key", "slurm_resume.sh",
                   "slurm_suspend.sh", "ClusterName=c1",
                   "systemctl enable --now slurmctld"):
        assert needle in script, needle
    lean = sp.generate_controller_bootstrap("c1", conf, "pw",
                                            with_slurmdbd=False)
    assert "mariadb" not in lean
    # The framework CLI + its store config are installed before any
    # store-mediated step (munge publication, power-save wrappers).
    wired = sp.generate_controller_bootstrap(
        "c1", conf, "pw", package_source="gs://bkt/pkg.whl",
        store_config_yaml="credentials:\n  storage: {backend: gcs}\n")
    assert "gcloud storage cp gs://bkt/pkg.whl" in wired
    assert "pip3 install" in wired
    assert "credentials.yaml" in wired
    assert wired.index("pip3 install") < wired.index(
        "publish-munge-key")


def test_slurm_compute_join_and_login_scripts():
    from batch_shipyard_tpu.slurm import provision as sp

    conf = "ClusterName=c1\n"
    join = sp.generate_compute_join_script("c1", conf)
    assert "fetch-munge-key" in join
    assert "systemctl restart slurmd" in join
    assert "ClusterName=c1" in join
    login = sp.generate_login_bootstrap("c1", conf)
    assert "slurm-client" in login and "fetch-munge-key" in login


def test_slurm_cluster_create_destroy_status():
    from batch_shipyard_tpu.slurm import provision as sp

    store = MemoryStateStore()
    runner = FakeRunner()
    mgr = GceVmManager("proj", runner=runner)
    record = sp.create_slurm_cluster(
        store, "c1", "ClusterName=c1\n", "pw", "proj", vms=mgr,
        login_count=2)
    assert record["controller_ip"] == "10.0.0.5"
    assert len(record["logins"]) == 2
    status = sp.slurm_cluster_status(store, "c1", vms=mgr)
    assert status["controller_status"] == "RUNNING"
    assert runner.verbs().count("instances:create") == 3
    sp.destroy_slurm_cluster(store, "c1", "proj", vms=mgr)
    with pytest.raises(ValueError):
        sp.slurm_cluster_status(store, "c1")
    assert runner.verbs().count("instances:delete") == 3


def test_slurm_resume_join_suspend_e2e():
    """Fake-substrate end-to-end: resume grows the pool and binds
    hosts; the compute join script is generated for those hosts; the
    munge key flows controller->node through the store; suspend
    releases and reclaims (VERDICT r1 next #4 done criterion)."""
    from batch_shipyard_tpu.config import settings as S
    from batch_shipyard_tpu.pool import manager as pool_mgr
    from batch_shipyard_tpu.slurm import burst
    from batch_shipyard_tpu.slurm import provision as sp
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    conf = {"pool_specification": {
        "id": "slurmpool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-8"},
        "max_wait_time_seconds": 30}}
    pool = S.pool_settings(conf)
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             S.global_settings({}), conf)

        # Controller boots: publishes its munge key.
        sp.publish_munge_key(store, "c1", b"controller-key")

        # Slurm asks for 2 elastic nodes -> resume binds pool nodes.
        hosts = burst.expand_hostlist("part-[0-1]")
        assignments = burst.process_resume(
            store, substrate, pool, "c1", "part", hosts,
            wait_timeout=30.0)
        assert set(assignments) == {"part-0", "part-1"}
        assert len(set(assignments.values())) == 2

        # Compute nodes join: fetch the munge key + join script.
        assert sp.fetch_munge_key(store, "c1", timeout=1.0) == \
            b"controller-key"
        join = sp.generate_compute_join_script(
            "c1", burst.generate_slurm_conf(
                "c1", {"part": {"max_nodes": 2}}))
        assert "NodeName=part-[0-1]" in join

        # Suspend releases the bindings.
        released = burst.process_suspend(store, substrate, pool,
                                         "c1", "part", hosts)
        assert released == 2
        assert burst.host_assignments(store, "c1", "part") == {}
    finally:
        substrate.stop_all()


# ----------------------------- federation ------------------------------


def test_federation_proxy_vm_lifecycle():
    from batch_shipyard_tpu.federation import provision as fed_prov
    from batch_shipyard_tpu.state import names

    from batch_shipyard_tpu.federation import federation as fed_mod

    store = MemoryStateStore()
    runner = FakeRunner()
    mgr = GceVmManager("proj", runner=runner)
    with pytest.raises(ValueError):
        fed_prov.provision_proxy_vm(store, "nope", "proj", vms=mgr)
    fed_mod.create_federation(store, "fedA")
    ip = fed_prov.provision_proxy_vm(
        store, "fedA", "proj", vms=mgr, replica=0,
        store_config_yaml="credentials:\n  storage: {backend: gcs}\n")
    assert ip == "10.0.0.5"
    script = runner.startup_scripts[0]
    assert "fed proxy" in script
    assert "shipyard-fed-proxy.service" in script
    assert "pip3 install" in script and "credentials.yaml" in script
    rec = store.get_entity(names.TABLE_FEDERATIONS, "proxies",
                           "shipyard-fed-fedA-proxy0")
    assert rec["federation_id"] == "fedA"
    fed_prov.provision_proxy_vm(store, "fedA", "proj", vms=mgr,
                                replica=1)
    # One replica's VM failing to delete must not block the other or
    # wedge retries: 'not found' clears the stale record.
    runner.fail_next = "resource not found"
    assert fed_prov.destroy_proxy_vms(store, "fedA", "proj",
                                      vms=mgr) == 2
    assert runner.verbs().count("instances:delete") == 2
    from batch_shipyard_tpu.state import names as _n
    assert not list(store.query_entities(_n.TABLE_FEDERATIONS,
                                         partition_key="proxies"))


def test_gcs_bucket_mount_commands_quote_user_values():
    """fs.yaml values reach the nodeprep shell; metacharacters must be
    inert (advisor r2 #5)."""
    from batch_shipyard_tpu.remotefs import manager as rfm

    cmds = rfm.gcs_bucket_mount_commands(
        {"remote_fs": {"gcs_buckets": {"b": {
            "bucket": "my bucket; rm -rf /",
            "mount_point": "/mnt/evil $(whoami)",
            "mount_options": ["implicit-dirs", "uid=100; reboot"],
        }}}}, "b")
    assert len(cmds) == 1
    cmd = cmds[0]
    # Every user value appears only inside single quotes.
    assert "'my bucket; rm -rf /'" in cmd
    assert "'/mnt/evil $(whoami)'" in cmd
    assert "-o 'uid=100; reboot'" in cmd
    # And never bare (outside the quoted spans).
    stripped = (cmd.replace("'my bucket; rm -rf /'", "")
                   .replace("'/mnt/evil $(whoami)'", "")
                   .replace("'uid=100; reboot'", ""))
    assert "rm -rf" not in stripped
    assert "$(whoami)" not in stripped
    assert "reboot" not in stripped


def test_create_vm_no_public_ip(vms):
    """public_ip=False (monitor/federation/slurm public_ip.enabled:
    false) creates the VM with --no-address."""
    mgr, runner = vms
    mgr.create_vm("private-vm", "e2-small", public_ip=False)
    create = runner.calls[0]
    assert "--no-address" in create
    runner.calls.clear()
    mgr.create_vm("public-vm", "e2-small")
    assert "--no-address" not in runner.calls[0]

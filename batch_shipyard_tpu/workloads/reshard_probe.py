"""Reshard-probe: a featherweight SHARDED "trainer" for resize drills.

The host_loss_resize drill's acceptance criterion is about multi-host
reshard-on-restore — an N-host gang re-forms at M hosts and each new
host reads ONLY the checkpoint shards its range needs — not about
matmuls. Like workloads/preempt_probe.py, this speaks the real
contracts with stdlib-only imports:

  * progress beats + goodput step windows + preempt watcher (the
    preempt_probe surfaces),
  * a SHARDED commit protocol mirroring checkpoint.py's: each gang
    instance owns a contiguous shard of a ``--dim``-wide float state
    vector and writes ``<ckpt>.shard{k}of{n}`` atomically; instance 0
    then writes a ``.LAYOUT`` sidecar (the ``.MESH`` analog: source
    shard count + dim) and the ``.COMMITTED`` marker — torn saves are
    never picked up,
  * per-host restore planning (parallel/restore_plan.py — the SAME
    pure math the jax path's host_restore_plan cross-checks): on
    restore at a different gang size, each instance consults the
    sidecar's source layout vs its own target range and reads only
    the overlapping shard files, recording WHICH into the read log
    (``<ckpt>.reads.log``) so the drill can assert reads == plan.

State update is per-element and deterministic —
``state[i] += (step+1) * (i+1)`` — so instances never need to
communicate, any (step, size) point is pure-replayable by the drill's
oracle, and bit-exactness across a resize is a meaningful assertion.
The per-commit "loss" (sum of this instance's shard) appends to
``<ckpt>.loss.log``: the drill's loss-trajectory oracle replays the
expected values from the barrier.
"""

from __future__ import annotations

import argparse
import json
import os
import time

from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.agent import progress
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.parallel import restore_plan


def _shard_path(ckpt: str, step: int, shard: int,
                parts: int) -> str:
    """STEP-SCOPED shard file (checkpoint.py's per-step dirs): a
    later attempt's staged-but-never-committed write must not
    clobber the committed step's shard — the survivor of a broken
    gang keeps staging right up to the barrier timeout."""
    return f"{ckpt}.s{step}.shard{shard}of{parts}"


def _atomic_write(path: str, payload: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(payload))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _read_json(path: str):
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _append(path: str, line: str) -> None:
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(line + "\n")


def _commit(ckpt: str, step: int, instance: int, instances: int,
            dim: int, shard: list[float],
            barrier_timeout: float = 3.0) -> bool:
    """The sharded commit: every instance writes its shard for this
    step; instance 0 waits for the full set, then writes the .LAYOUT
    sidecar + .COMMITTED marker (the multi-writer analog of
    checkpoint.py's staging -> barrier -> COMMITTED order: a crash at
    any point leaves the previous committed step pickable, never a
    torn mix of steps). Returns False when the barrier timed out — a
    peer died mid-commit; the previous commit stands, and the CALLER
    latches off further commit attempts (the gang is broken; the
    recovery requeue owns the rerun, and re-waiting the barrier at
    every later cadence boundary would stall the survivor for the
    rest of its zombie life)."""
    _atomic_write(_shard_path(ckpt, step, instance, instances),
                  {"step": step, "values": shard})
    if instance != 0:
        return True
    deadline = time.monotonic() + barrier_timeout
    while time.monotonic() < deadline:
        if all((_read_json(_shard_path(ckpt, step, k, instances))
                or {}).get("step") == step
               for k in range(instances)):
            break
        progress.beat()  # alive, waiting on peers — not wedged
        time.sleep(0.02)
    else:
        return False
    _atomic_write(ckpt + ".LAYOUT",
                  {"step": step, "parts": instances, "dim": dim})
    _atomic_write(ckpt + ".COMMITTED", {"step": step})
    _gc_stale_shards(ckpt, step)
    return True


def _gc_stale_shards(ckpt: str, committed_step: int) -> None:
    """Retention (writer-only, AFTER the marker landed): shard files
    of steps older than the just-committed one can never be restored
    again — a restore only ever reads the COMMITTED step."""
    base = os.path.basename(ckpt) + ".s"
    parent = os.path.dirname(ckpt) or "."
    try:
        names = os.listdir(parent)
    except OSError:
        return
    for name in names:
        if not name.startswith(base):
            continue
        try:
            step = int(name[len(base):].split(".", 1)[0])
        except ValueError:
            continue
        if step < committed_step:
            try:
                os.remove(os.path.join(parent, name))
            except OSError:
                pass


def _restore(ckpt: str, instance: int, instances: int,
             dim: int) -> tuple[int, list[float]]:
    """Per-host planned restore: committed step + THIS instance's
    target shard, assembled by reading only the source shard files
    the restore plan names. Records the reads issued (the drill
    asserts they match restore_plan.host_reads exactly)."""
    committed = _read_json(ckpt + ".COMMITTED")
    layout = _read_json(ckpt + ".LAYOUT")
    lo, hi = restore_plan.shard_ranges(dim, instances)[instance]
    if not committed or not layout or \
            layout.get("step") != committed.get("step"):
        return 0, [0.0] * (hi - lo)
    step = int(committed["step"])
    source_parts = int(layout["parts"])
    reads = restore_plan.host_reads(dim, source_parts, instances,
                                    instance)
    values = [0.0] * (hi - lo)
    for read in reads:
        payload = _read_json(_shard_path(ckpt, step, read.shard,
                                         source_parts))
        if payload is None or payload.get("step") != step:
            return 0, [0.0] * (hi - lo)  # torn source; start fresh
        chunk = payload["values"][read.lo:read.hi]
        values[read.dst_lo:read.dst_lo + len(chunk)] = chunk
        _append(ckpt + ".reads.log",
                f"i{instance}of{instances} step={step} "
                f"shard={read.shard}of{source_parts} "
                f"[{read.lo}..{read.hi})")
    return step, values


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--step-seconds", type=float, default=0.05)
    parser.add_argument("--dim", type=int, default=24,
                        help="global state width (must split over "
                             "every gang size the drill uses)")
    parser.add_argument("--checkpoint-every", type=int, default=10)
    parser.add_argument("--ckpt", required=True,
                        help="shared state prefix (job scratch/"
                             "shared dir)")
    args = parser.parse_args()

    instance = int(os.environ.get("SHIPYARD_TASK_INSTANCE", "0"))
    instances = int(os.environ.get("SHIPYARD_TASK_INSTANCES", "1"))
    lo, hi = restore_plan.shard_ranges(args.dim,
                                       instances)[instance]
    start_step, shard = _restore(args.ckpt, instance, instances,
                                 args.dim)
    watcher = preemption.PreemptWatcher()
    window_started = time.time()

    def _loss() -> float:
        return sum(shard)

    def _record_loss(step: int) -> None:
        if instance == 0:
            _append(args.ckpt + ".loss.log",
                    f"step={step} size={instances} "
                    f"loss={_loss():.6f}")

    peer_lost = False
    for step in range(start_step, args.steps):
        time.sleep(args.step_seconds)
        progress.beat()
        for k in range(len(shard)):
            # Per-element deterministic update: pure-replayable at
            # any (step, size), so resized resumes are bit-exact.
            shard[k] += float((step + 1) * (lo + k + 1))
        done = step + 1
        drain = watcher.poll() is not None
        if not peer_lost and (
                drain or (args.checkpoint_every
                          and done % args.checkpoint_every == 0)):
            if _commit(args.ckpt, done, instance, instances,
                       args.dim, shard):
                _record_loss(done)
            else:
                peer_lost = True  # broken gang: stop committing
        if drain:
            goodput_events.record(
                goodput_events.PROGRAM_STEP_WINDOW, window_started,
                time.time(), step_start=start_step, step_end=done)
            return preemption.EXIT_PREEMPTED
    if not peer_lost:
        if _commit(args.ckpt, args.steps, instance, instances,
                   args.dim, shard):
            _record_loss(args.steps)
    goodput_events.record(
        goodput_events.PROGRAM_STEP_WINDOW, window_started,
        time.time(), step_start=start_step, step_end=args.steps)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""account quota + allocation preflight (VERDICT r4 next #4): pinned
gcloud payloads through the injectable runner; pool-add advisory
warnings; stockout advisory folded into the allocation error record.
Reference: shipyard.py:1009-1078 (account quota/images),
convoy/batch.py:661-672 (resize error classification)."""

import json

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.substrate import quota as quota_mod

# Pinned payload: gcloud compute tpus accelerator-types list
# --format=json (full resource names, current gcloud shape).
ACCEL_TYPES = json.dumps([
    {"name": "projects/p/locations/us-central1-a/acceleratorTypes/"
             "v5litepod-16", "acceleratorType": "v5litepod-16"},
    {"name": "projects/p/locations/us-central1-a/acceleratorTypes/"
             "v5litepod-8", "acceleratorType": "v5litepod-8"},
    {"name": "projects/p/locations/us-central1-a/acceleratorTypes/"
             "v3-8"},
])

# Pinned payload: gcloud alpha services quota list (ServiceQuota
# shape: metric -> consumerQuotaLimits -> quotaBuckets).
QUOTAS = json.dumps([
    {"metric": "tpu.googleapis.com/v5litepod_chips",
     "consumerQuotaLimits": [{
         "unit": "1/{project}/{region}",
         "quotaBuckets": [
             {"effectiveLimit": "16",
              "dimensions": {"region": "us-central1"}},
             {"defaultLimit": "8"},
         ]}]},
    {"metric": "tpu.googleapis.com/v4_chips",
     "consumerQuotaLimits": [{
         "unit": "1/{project}/{region}",
         "quotaBuckets": [
             {"effectiveLimit": "0",
              "dimensions": {"region": "us-central1"}}]}]},
])


class FakeGcloudRunner:
    def __init__(self, accel_by_zone=None, quotas=QUOTAS):
        self.accel_by_zone = accel_by_zone or {
            "us-central1-a": ACCEL_TYPES}
        self.quotas = quotas
        self.calls = []

    def __call__(self, argv, **_kw):
        self.calls.append(argv)
        joined = " ".join(argv)
        if "accelerator-types" in joined:
            zone = [a for a in argv if a.startswith("--zone=")][0]
            zone = zone.split("=", 1)[1]
            payload = self.accel_by_zone.get(zone)
            if payload is None:
                return 1, "", "zone not found"
            return 0, payload, ""
        if "services quota" in joined or "quota" in joined:
            return 0, self.quotas, ""
        return 1, "", f"unexpected argv {argv}"


def client(**kw):
    return quota_mod.TpuQuotaClient("proj",
                                    runner=FakeGcloudRunner(**kw))


def make_pool(accel="v5litepod-16", slices=1, zone=None):
    spec = {"pool_specification": {
        "id": "qp", "substrate": "tpu_vm",
        "tpu": {"accelerator_type": accel, "num_slices": slices}}}
    if zone:
        spec["pool_specification"]["zone"] = zone
    return settings_mod.pool_settings(spec)


def test_accelerator_types_parses_both_shapes():
    types = client().accelerator_types("us-central1-a")
    assert types == ["v3-8", "v5litepod-16", "v5litepod-8"]


def test_quota_limits_filtered_by_region():
    rows = client().quota_limits(region="us-central1")
    metrics = {r["metric"]: r["limit"] for r in rows
               if r["region"] == "us-central1"}
    assert metrics["tpu.googleapis.com/v5litepod_chips"] == 16
    assert metrics["tpu.googleapis.com/v4_chips"] == 0
    # The dimensionless default bucket also passes the filter.
    assert any(r["region"] == "" and r["limit"] == 8 for r in rows)


def test_quota_report_shape():
    report = quota_mod.quota_report(client(), "us-central1-a")
    assert report["project"] == "proj"
    assert "v5litepod-16" in report["accelerator_types"]
    assert report["quota_limits"]


def test_preflight_ok_is_silent():
    pool = make_pool(zone="us-central1-a")
    assert quota_mod.preflight_pool(pool, client()) == []


def test_preflight_warns_on_unoffered_type():
    pool = make_pool(accel="v5p-8", zone="us-central1-a")
    warnings = quota_mod.preflight_pool(pool, client())
    assert len(warnings) == 1
    assert "not offered in zone us-central1-a" in warnings[0]


def test_preflight_warns_when_request_exceeds_quota():
    # 2 slices of v5litepod-16 = 32 chips > 16 chip quota.
    pool = make_pool(slices=2, zone="us-central1-a")
    warnings = quota_mod.preflight_pool(pool, client())
    assert any("needs 32 v5litepod chips" in w and "is 16" in w
               for w in warnings)


def test_preflight_degrades_when_gcloud_fails():
    pool = make_pool(zone="europe-west4-a")  # zone not in fake
    warnings = quota_mod.preflight_pool(pool, client())
    assert len(warnings) == 1
    assert "preflight unavailable" in warnings[0]


def test_preflight_no_zone_is_silent():
    assert quota_mod.preflight_pool(make_pool(), client()) == []


def test_stockout_advisory_names_sibling_zones():
    c = quota_mod.TpuQuotaClient("proj", runner=FakeGcloudRunner(
        accel_by_zone={"us-central1-a": ACCEL_TYPES,
                       "us-central1-b": ACCEL_TYPES,
                       "us-central1-c": json.dumps([])}))
    advisory = quota_mod.stockout_advisory(
        c, "v5litepod-16", "us-central1-a",
        ["us-central1-b", "us-central1-c", "us-central1-d"])
    assert "us-central1-b" in advisory
    assert "us-central1-c" not in advisory
    # No zone offers it -> no advisory at all.
    assert quota_mod.stockout_advisory(
        c, "v6e-8", "us-central1-a", ["us-central1-b"]) is None


def test_pool_add_preflight_via_fleet(monkeypatch, tmp_path):
    """fleet.action_pool_add surfaces preflight warnings without
    blocking the (fake-substrate-backed) allocation."""
    from batch_shipyard_tpu import fleet as fleet_mod

    class Ctx:  # minimal Context duck
        pool = make_pool(slices=2, zone="us-central1-a")
        credentials = settings_mod.credentials_settings(
            {"credentials": {"gcp": {"project": "proj",
                                     "zone": "us-central1-a"},
                             "storage": {"backend": "memory"}}})

    warnings = fleet_mod._quota_preflight(Ctx(), client())
    assert any("needs 32" in w for w in warnings)
    # Non-tpu_vm pools skip preflight entirely.
    Ctx.pool = settings_mod.pool_settings({"pool_specification": {
        "id": "qp", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16",
                "num_slices": 2}}})
    assert fleet_mod._quota_preflight(Ctx(), client()) == []


def test_gcp_substrate_folds_advisory_into_stockout(monkeypatch):
    from batch_shipyard_tpu.state import names
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.gcp_tpu import GcpTpuSubstrate

    monkeypatch.setattr("shutil.which", lambda _: "/usr/bin/gcloud")
    store = MemoryStateStore()
    creds = settings_mod.credentials_settings({"credentials": {
        "gcp": {"project": "proj", "zone": "us-central1-a"},
        "storage": {"backend": "memory"}}})
    sub = GcpTpuSubstrate(store, creds)
    sub.quota_client = quota_mod.TpuQuotaClient(
        "proj", runner=FakeGcloudRunner(
            accel_by_zone={"us-central1-b": ACCEL_TYPES}))

    def fake_gcloud(self, *args, parse_json=False, zone=None):
        if args[0] == "create":
            raise RuntimeError(
                "There is no more capacity in the zone")
        return {} if parse_json else ""

    monkeypatch.setattr(GcpTpuSubstrate, "_gcloud", fake_gcloud)
    pool = make_pool(zone="us-central1-a")
    store.insert_entity(names.TABLE_POOLS, "pools", pool.id, {})
    with pytest.raises(RuntimeError):
        sub.allocate_pool(pool)
    row = store.get_entity(names.TABLE_POOLS, "pools", pool.id)
    assert row["allocation_error_kind"] == "stockout"
    assert "us-central1-b" in row["allocation_error_advisory"]
"""Pool lifecycle on the FakePod substrate: create/ready/recovery/
resize/delete (reference behavior: batch.py:625-720 recovery loop)."""

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate


def make_pool_conf(pool_id="p1", accel="v5litepod-16", slices=1,
                   **node_prep):
    return {"pool_specification": {
        "id": pool_id,
        "substrate": "fake",
        "tpu": {"accelerator_type": accel, "num_slices": slices},
        "max_wait_time_seconds": 30,
        "node_prep": node_prep,
    }}


GLOBAL = settings_mod.global_settings({})


@pytest.fixture()
def env():
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    yield store, substrate
    substrate.stop_all()


def test_create_pool_ready(env):
    store, substrate = env
    conf = make_pool_conf()
    pool = settings_mod.pool_settings(conf)
    nodes = pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    assert len(nodes) == 4  # v5e-16 = 4 workers
    assert all(n.state in ("idle", "running") for n in nodes)
    assert pool_mgr.get_pool(store, "p1")["state"] == "ready"
    stats = pool_mgr.pool_stats(store, "p1")
    assert stats["nodes"]["total"] == 4


def test_create_pool_duplicate_rejected(env):
    store, substrate = env
    conf = make_pool_conf()
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    with pytest.raises(pool_mgr.PoolExistsError):
        pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)


def test_start_task_failed_no_recovery_raises(env):
    store, substrate = env
    substrate.inject["p1-s0-w1"] = "nodeprep_fail"
    conf = make_pool_conf()
    pool = settings_mod.pool_settings(conf)
    with pytest.raises(pool_mgr.PoolAllocationError) as exc:
        pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    assert "start task failed" in str(exc.value)


def test_start_task_failed_reboot_recovers(env):
    store, substrate = env
    substrate.inject["p1-s0-w1"] = "nodeprep_fail_once"
    conf = make_pool_conf(reboot_on_start_task_failed=True)
    pool = settings_mod.pool_settings(conf)
    nodes = pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    assert len([n for n in nodes if n.state == "idle"]) == 4


def test_unusable_recovery(env):
    store, substrate = env
    substrate.inject["p1-s0-w2"] = "unusable"
    conf = make_pool_conf(attempt_recovery_on_unusable=True)
    pool = settings_mod.pool_settings(conf)

    # Recovery recreates the slice; clear the injection so the second
    # boot succeeds (transient-unusable scenario).
    orig = substrate.recreate_slice

    def recreate_and_heal(p, s):
        substrate.inject.pop("p1-s0-w2", None)
        orig(p, s)

    substrate.recreate_slice = recreate_and_heal
    nodes = pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    assert len([n for n in nodes if n.state == "idle"]) == 4


def test_resize_grow_and_shrink(env):
    store, substrate = env
    conf = make_pool_conf(slices=1)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    pool_mgr.resize_pool(store, substrate, pool, 2)
    assert len(pool_mgr.list_nodes(store, "p1")) == 8
    pool_mgr.resize_pool(store, substrate, pool, 1)
    assert len(pool_mgr.list_nodes(store, "p1")) == 4


def test_delete_pool(env):
    store, substrate = env
    conf = make_pool_conf()
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    pool_mgr.delete_pool(store, substrate, "p1")
    assert not pool_mgr.pool_exists(store, "p1")
    assert pool_mgr.list_nodes(store, "p1") == []
    with pytest.raises(pool_mgr.PoolNotFoundError):
        pool_mgr.get_pool(store, "p1")

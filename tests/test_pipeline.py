"""Pipeline parallelism: equivalence with sequential execution,
differentiability, and composition with data parallelism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import pipeline


def make_mesh_pp(pp, dp=1):
    # pp rides the 'ep' slot order trick? No: pipeline uses its own
    # axis name; build a mesh with explicit axes.
    import numpy as onp
    from jax.sharding import Mesh
    devices = onp.array(jax.devices()[:pp * dp]).reshape(dp, pp)
    return Mesh(devices, ("dp", "pp"))


def mlp_stage(params, x):
    """One stage = one dense layer with tanh (shape-preserving)."""
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stage_params(num_stages, width, seed=0):
    rng = np.random.RandomState(seed)
    return pipeline.stack_stage_params([
        {"w": jnp.asarray(rng.randn(width, width) * 0.3, jnp.float32),
         "b": jnp.asarray(rng.randn(width) * 0.1, jnp.float32)}
        for _ in range(num_stages)])


@pytest.mark.parametrize("pp,microbatches", [(2, 2), (4, 4), (4, 8),
                                             (8, 4)])
def test_pipeline_matches_sequential(pp, microbatches):
    mesh = make_mesh_pp(pp)
    params = make_stage_params(pp, width=16)
    x = jnp.asarray(np.random.RandomState(1).randn(8, 16), jnp.float32)
    expected = pipeline.sequential_apply(params, x, mlp_stage)
    got = pipeline.pipeline_apply(
        params, x, mesh=mesh, stage_fn=mlp_stage,
        num_microbatches=microbatches, batch_axes=("dp",))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_gradients_match_sequential():
    mesh = make_mesh_pp(4)
    params = make_stage_params(4, width=16)
    x = jnp.asarray(np.random.RandomState(2).randn(8, 16), jnp.float32)

    def loss_pipe(params):
        y = pipeline.pipeline_apply(
            params, x, mesh=mesh, stage_fn=mlp_stage,
            num_microbatches=4, batch_axes=("dp",))
        return jnp.sum(y ** 2)

    def loss_seq(params):
        return jnp.sum(pipeline.sequential_apply(params, x,
                                                 mlp_stage) ** 2)

    grads_pipe = jax.grad(loss_pipe)(params)
    grads_seq = jax.grad(loss_seq)(params)
    for gp, gs in zip(jax.tree_util.tree_leaves(grads_pipe),
                      jax.tree_util.tree_leaves(grads_seq)):
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                                   atol=1e-5, rtol=1e-4)


def test_pipeline_composes_with_dp():
    mesh = make_mesh_pp(pp=4, dp=2)
    params = make_stage_params(4, width=16)
    x = jnp.asarray(np.random.RandomState(3).randn(8, 16), jnp.float32)
    expected = pipeline.sequential_apply(params, x, mlp_stage)
    got = pipeline.pipeline_apply(
        params, x, mesh=mesh, stage_fn=mlp_stage, num_microbatches=2,
        batch_axes=("dp",))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_transformer_blocks():
    """Pipeline real transformer blocks: 4 stages x 1 block each."""
    from batch_shipyard_tpu.models import transformer as tfm
    config = tfm.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=16, dtype=jnp.float32,
        param_dtype=jnp.float32)
    block = tfm.Block(config)
    positions = jnp.arange(16, dtype=jnp.int32)
    x0 = jnp.asarray(np.random.RandomState(4).randn(4, 16, 32),
                     jnp.float32)
    per_stage = []
    for s in range(4):
        per_stage.append(block.init(
            jax.random.PRNGKey(s), x0, positions)["params"])
    stacked = pipeline.stack_stage_params(per_stage)

    def stage_fn(params, x):
        return block.apply({"params": params}, x, positions)

    mesh = make_mesh_pp(4)
    expected = pipeline.sequential_apply(stacked, x0, stage_fn)
    got = pipeline.pipeline_apply(
        stacked, x0, mesh=mesh, stage_fn=stage_fn,
        num_microbatches=4, batch_axes=("dp",))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-4, rtol=1e-4)


def test_pipeline_rejects_bad_microbatch():
    mesh = make_mesh_pp(2)
    params = make_stage_params(2, width=16)
    x = jnp.zeros((7, 16), jnp.float32)
    with pytest.raises(ValueError):
        pipeline.pipeline_apply(params, x, mesh=mesh,
                                stage_fn=mlp_stage,
                                num_microbatches=2,
                                batch_axes=("dp",))


@pytest.mark.slow
def test_pipeline_transformer_training():
    """Full pipeline-parallel training: pp=4 x dp=2 mesh, loss
    decreases, and the pipelined forward equals a sequential pass
    over the same stage parameters."""
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.parallel import train as train_mod
    mesh = make_mesh_pp(pp=4, dp=2)
    config = tfm.TransformerConfig(
        vocab_size=128, d_model=32, n_layers=4, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=32, dtype=jnp.float32,
        param_dtype=jnp.float32)
    harness = train_mod.build_transformer_train_pp(
        mesh, config, batch_size=8, seq_len=32, num_microbatches=4,
        seed=0)
    rng = np.random.RandomState(0)
    batch = {
        "tokens": jnp.asarray(rng.randint(0, 128, (8, 32)), jnp.int32),
        "targets": jnp.asarray(rng.randint(0, 128, (8, 32)),
                               jnp.int32)}
    params, opt_state = harness.params, harness.opt_state
    first = None
    for _ in range(5):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
        if first is None:
            first = float(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < first


@pytest.mark.slow
def test_pipeline_transformer_matches_nonpipelined():
    """The pp=4 pipelined forward loss equals running the same blocks
    sequentially (no pipeline) with identical parameters."""
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.parallel import train as train_mod
    mesh = make_mesh_pp(pp=4, dp=1)
    config = tfm.TransformerConfig(
        vocab_size=128, d_model=32, n_layers=4, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=32, dtype=jnp.float32,
        param_dtype=jnp.float32)
    harness = train_mod.build_transformer_train_pp(
        mesh, config, batch_size=4, seq_len=32, num_microbatches=2,
        seed=3)
    rng = np.random.RandomState(1)
    tokens = jnp.asarray(rng.randint(0, 128, (4, 32)), jnp.int32)
    targets = jnp.asarray(rng.randint(0, 128, (4, 32)), jnp.int32)

    from flax import linen as nn
    embed = nn.Embed(128, 32, dtype=jnp.float32,
                     param_dtype=jnp.float32)
    norm = tfm.RMSNorm(dtype=jnp.float32)
    block = tfm.Block(config)
    positions = jnp.arange(32, dtype=jnp.int32)
    params = jax.device_get(harness.params)

    def sequential_loss():
        h = embed.apply({"params": params["embed"]}, tokens)
        stages = params["stages"]
        num_stages = jax.tree_util.tree_leaves(stages)[0].shape[0]
        for s in range(num_stages):
            stage_p = jax.tree_util.tree_map(lambda p: p[s], stages)
            layers = jax.tree_util.tree_leaves(stage_p)[0].shape[0]
            for li in range(layers):
                layer_p = jax.tree_util.tree_map(
                    lambda p: p[li], stage_p)
                h = block.apply({"params": layer_p}, h, positions)
        h = norm.apply({"params": params["final_norm"]}, h)
        return tfm.lm_loss_chunked(
            h, params["embed"]["embedding"], targets)

    # One pipelined step on fresh params reports the pre-update loss.
    _p, _o, metrics = harness.step(harness.params, harness.opt_state,
                                   {"tokens": tokens,
                                    "targets": targets})
    np.testing.assert_allclose(float(metrics["loss"]),
                               float(sequential_loss()), rtol=1e-5)


def _mb_mean_loss(last_params, h, targets, last_fn, num_microbatches):
    mb = h.shape[0] // num_microbatches
    total = 0.0
    for i in range(num_microbatches):
        total = total + last_fn(last_params,
                                h[i * mb:(i + 1) * mb],
                                targets[i * mb:(i + 1) * mb])
    return total / num_microbatches


@pytest.mark.parametrize("pp,microbatches", [(4, 4), (4, 8), (2, 8)])
@pytest.mark.slow
def test_1f1b_matches_autodiff(pp, microbatches):
    """The manual 1F1B fwd+bwd schedule reproduces autodiff's loss AND
    gradients (stage params, last-stage params, input cotangent) for
    an MLP pipeline with a quadratic 'head'."""
    mesh = make_mesh_pp(pp)
    params = make_stage_params(pp, width=16)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(16, 16), jnp.float32)
    targets = jnp.asarray(rng.randn(16, 16), jnp.float32)
    last_params = {"w": jnp.asarray(rng.randn(16, 16) * 0.3,
                                    jnp.float32)}

    def last_fn(lp, y, t):
        return jnp.mean((y @ lp["w"] - t) ** 2)

    loss, dstage, dlast, dx = pipeline.pipeline_1f1b_train(
        params, x, targets, last_params, mesh=mesh,
        stage_fn=mlp_stage, last_fn=last_fn,
        num_microbatches=microbatches, batch_axes=("dp",))

    def ref(params, x, last_params):
        h = pipeline.sequential_apply(params, x, mlp_stage)
        return _mb_mean_loss(last_params, h, targets, last_fn,
                             microbatches)

    ref_loss, (g_stage, g_x, g_last) = jax.value_and_grad(
        ref, argnums=(0, 1, 2))(params, x, last_params)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=2e-5)
    for got, want in zip(jax.tree_util.tree_leaves(dstage),
                         jax.tree_util.tree_leaves(g_stage)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)
    for got, want in zip(jax.tree_util.tree_leaves(dlast),
                         jax.tree_util.tree_leaves(g_last)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(g_x),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.slow
def test_1f1b_transformer_step_matches_sequential_loss():
    """build_transformer_train_1f1b: one step on the dp x pp mesh
    reports the same pre-update loss as the non-pipelined model."""
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.parallel import train as train_mod

    mesh = make_mesh_pp(4, dp=2)
    config = tfm.TransformerConfig(
        vocab_size=128, d_model=32, n_layers=4, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=32, dtype=jnp.float32,
        param_dtype=jnp.float32)
    harness = train_mod.build_transformer_train_1f1b(
        mesh, config, batch_size=16, seq_len=32, num_microbatches=8)
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randint(0, 128, (16, 32)), jnp.int32)
    targets = jnp.asarray(rng.randint(0, 128, (16, 32)), jnp.int32)

    from flax import linen as nn
    embed = nn.Embed(128, 32, dtype=jnp.float32,
                     param_dtype=jnp.float32)
    norm = tfm.RMSNorm(dtype=jnp.float32)
    block = tfm.Block(config)
    positions = jnp.arange(32, dtype=jnp.int32)
    params = jax.device_get(harness.params)

    def sequential_loss():
        h = embed.apply({"params": params["embed"]}, tokens)
        stages = params["stages"]
        num_stages = jax.tree_util.tree_leaves(stages)[0].shape[0]
        for s in range(num_stages):
            stage_p = jax.tree_util.tree_map(lambda p: p[s], stages)
            layers = jax.tree_util.tree_leaves(stage_p)[0].shape[0]
            for li in range(layers):
                layer_p = jax.tree_util.tree_map(
                    lambda p: p[li], stage_p)
                h = block.apply({"params": layer_p}, h, positions)
        h = norm.apply({"params": params["final_norm"]}, h)
        return tfm.lm_loss_chunked(
            h, params["embed"]["embedding"], targets)

    _p, _o, metrics = harness.step(harness.params, harness.opt_state,
                                   {"tokens": tokens,
                                    "targets": targets})
    np.testing.assert_allclose(float(metrics["loss"]),
                               float(sequential_loss()), rtol=1e-5)


def test_1f1b_peak_memory_below_gpipe():
    """The 1F1B schedule's compiled per-device temp memory stays below
    GPipe-with-autodiff at many microbatches (the whole point: GPipe
    holds every microbatch's tick residuals; 1F1B is bounded by the
    stage count)."""
    pp, microbatches, width, batch = 4, 16, 128, 64
    mesh = make_mesh_pp(pp)
    params = make_stage_params(pp, width=width)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(batch, width), jnp.float32)
    targets = jnp.asarray(rng.randn(batch, width), jnp.float32)
    last_params = {"w": jnp.asarray(rng.randn(width, width) * 0.3,
                                    jnp.float32)}

    def last_fn(lp, y, t):
        return jnp.mean((y @ lp["w"] - t) ** 2)

    def loss_1f1b(params, x, last_params):
        loss, _, _, _ = pipeline.pipeline_1f1b_train(
            params, x, targets, last_params, mesh=mesh,
            stage_fn=mlp_stage, last_fn=last_fn,
            num_microbatches=microbatches, batch_axes=("dp",))
        return loss

    def loss_gpipe(params, x, last_params):
        h = pipeline.pipeline_apply(
            params, x, mesh=mesh, stage_fn=mlp_stage,
            num_microbatches=microbatches, batch_axes=("dp",))
        return _mb_mean_loss(last_params, h, targets, last_fn,
                             microbatches)

    def temp_bytes(fn, grad: bool):
        f = jax.grad(fn, argnums=(0, 2)) if grad else fn
        compiled = jax.jit(f).lower(params, x, last_params).compile()
        mem = compiled.memory_analysis()
        if mem is None:
            pytest.skip("memory analysis unavailable on this backend")
        return mem.temp_size_in_bytes

    m_1f1b = temp_bytes(loss_1f1b, grad=False)
    m_gpipe = temp_bytes(loss_gpipe, grad=True)
    assert m_1f1b < m_gpipe, (m_1f1b, m_gpipe)


@pytest.mark.slow
def test_1f1b_with_tensor_parallel_stages_matches():
    """1F1B over a dp x pp x tp mesh (Megatron tp INSIDE each stage:
    column/row-sharded projections with explicit f/g operators)
    reproduces the pure-pp run's loss trajectory exactly."""
    import numpy as onp
    from jax.sharding import Mesh

    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.parallel import train as train_mod

    config = tfm.TransformerConfig(
        vocab_size=128, d_model=32, n_layers=4, n_heads=4, d_head=8,
        d_ff=64, max_seq_len=32, dtype=jnp.float32,
        param_dtype=jnp.float32)
    rng = np.random.RandomState(7)
    tokens = jnp.asarray(rng.randint(0, 128, (8, 32)), jnp.int32)
    targets = jnp.asarray(rng.randint(0, 128, (8, 32)), jnp.int32)
    batch = {"tokens": tokens, "targets": targets}

    def losses(mesh):
        harness = train_mod.build_transformer_train_1f1b(
            mesh, config, batch_size=8, seq_len=32,
            num_microbatches=4, seed=11)
        params, opt = harness.params, harness.opt_state
        out = []
        for _ in range(3):
            params, opt, metrics = harness.step(params, opt, batch)
            out.append(float(metrics["loss"]))
        return out

    mesh_pp = Mesh(onp.array(jax.devices()[:4]).reshape(2, 2),
                   ("dp", "pp"))
    mesh_tp = Mesh(onp.array(jax.devices()[:8]).reshape(2, 2, 2),
                   ("dp", "pp", "tp"))
    ref = losses(mesh_pp)
    got = losses(mesh_tp)
    np.testing.assert_allclose(got, ref, rtol=2e-5)


# ---------------- interleaved (virtual-stage) 1F1B ----------------

def make_chunk_params(num_stages, num_chunks, width, seed=0):
    rng = np.random.RandomState(seed)
    per_chunk = [
        {"w": jnp.asarray(rng.randn(width, width) * 0.3, jnp.float32),
         "b": jnp.asarray(rng.randn(width) * 0.1, jnp.float32)}
        for _ in range(num_stages * num_chunks)]
    return per_chunk, pipeline.stack_interleaved_chunk_params(
        per_chunk, num_stages)


def test_interleaved_schedule_reduces_bubble():
    """Virtual stages shrink warmup/drain bubbles: idle fraction at
    V=4 must be well under the V=1 (plain 1F1B one-op-per-tick)
    schedule's."""
    flat = pipeline.interleaved_1f1b_schedule(4, 1, 16)
    inter = pipeline.interleaved_1f1b_schedule(4, 4, 16)
    assert inter["idle_fraction"] < flat["idle_fraction"] / 2
    # Every op executes exactly once: 2 * M * V per device.
    assert (inter["kind"] > 0).sum() == 4 * 2 * 16 * 4


def test_interleaved_schedule_requires_divisibility():
    with pytest.raises(ValueError):
        pipeline.interleaved_1f1b_schedule(4, 2, 6)


@pytest.mark.parametrize("pp,chunks,microbatches",
                         [(2, 2, 4), (4, 2, 8)])
@pytest.mark.slow
def test_interleaved_1f1b_matches_autodiff(pp, chunks, microbatches):
    """The interleaved schedule reproduces autodiff's loss and
    gradients (chunk params, head params, input cotangent)."""
    mesh = make_mesh_pp(pp)
    per_chunk, chunk_params = make_chunk_params(pp, chunks, width=16)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(16, 16), jnp.float32)
    targets = jnp.asarray(rng.randn(16, 16), jnp.float32)
    last_params = {"w": jnp.asarray(rng.randn(16, 16) * 0.3,
                                    jnp.float32)}

    def last_fn(lp, y, t):
        return jnp.mean((y @ lp["w"] - t) ** 2)

    loss, dchunk, dlast, dx = pipeline.pipeline_interleaved_1f1b_train(
        chunk_params, x, targets, last_params, mesh=mesh,
        stage_fn=mlp_stage, last_fn=last_fn,
        num_microbatches=microbatches, num_chunks=chunks,
        batch_axes=("dp",))

    def ref(per_chunk_params, x, last_params):
        h = x
        for p in per_chunk_params:
            h = mlp_stage(p, h)
        return _mb_mean_loss(last_params, h, targets, last_fn,
                             microbatches)

    ref_loss, (g_chunks, g_x, g_last) = jax.value_and_grad(
        ref, argnums=(0, 1, 2))(per_chunk, x, last_params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    # Repack reference per-chunk grads into the [S, V, ...] layout.
    g_repacked = pipeline.stack_interleaved_chunk_params(
        list(g_chunks), pp)
    for got, want in zip(jax.tree_util.tree_leaves(dchunk),
                         jax.tree_util.tree_leaves(g_repacked)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)
    for got, want in zip(jax.tree_util.tree_leaves(dlast),
                         jax.tree_util.tree_leaves(g_last)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(g_x),
                               atol=1e-5, rtol=1e-4)


@pytest.mark.slow
def test_interleaved_composes_with_dp():
    """dp x pp mesh: data-parallel shards see different microbatches;
    grads pmean across dp — loss equals the full-batch reference."""
    mesh = make_mesh_pp(2, dp=2)
    per_chunk, chunk_params = make_chunk_params(2, 2, width=8, seed=5)
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, 8), jnp.float32)
    targets = jnp.asarray(rng.randn(8, 8), jnp.float32)
    last_params = {"w": jnp.asarray(rng.randn(8, 8) * 0.3,
                                    jnp.float32)}

    def last_fn(lp, y, t):
        return jnp.mean((y @ lp["w"] - t) ** 2)

    loss, dchunk, _dlast, _dx = \
        pipeline.pipeline_interleaved_1f1b_train(
            chunk_params, x, targets, last_params, mesh=mesh,
            stage_fn=mlp_stage, last_fn=last_fn,
            num_microbatches=2, num_chunks=2, batch_axes=("dp",))

    def ref(per_chunk_params):
        h = x
        for p in per_chunk_params:
            h = mlp_stage(p, h)
        # dp=2 halves, each split into 2 microbatches of 2.
        total = 0.0
        for half in range(2):
            hh = h[half * 4:(half + 1) * 4]
            tt = targets[half * 4:(half + 1) * 4]
            total = total + _mb_mean_loss(last_params, hh, tt,
                                          last_fn, 2)
        return total / 2

    ref_loss, g_chunks = jax.value_and_grad(ref)(per_chunk)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    g_repacked = pipeline.stack_interleaved_chunk_params(
        list(g_chunks), 2)
    for got, want in zip(jax.tree_util.tree_leaves(dchunk),
                         jax.tree_util.tree_leaves(g_repacked)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)

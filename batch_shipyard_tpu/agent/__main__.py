"""Node agent process entrypoint.

Started by the localhost substrate (subprocess per node) and by
nodeprep on real TPU VM workers (systemd unit). All wiring comes from
a JSON bootstrap file to keep the exec contract trivial:

    python -m batch_shipyard_tpu.agent /path/to/bootstrap.json

Bootstrap schema: {
  storage: {backend, root|bucket, prefix},
  pool_config: <raw pool yaml dict>,
  identity: {pool_id, node_id, node_index, hostname, internal_ip,
             slice_index, worker_index},
  work_dir: str, heartbeat_interval: float, poll_interval: float
}
"""

from __future__ import annotations

import json
import signal
import sys

from batch_shipyard_tpu.agent.cascade import CascadeImageProvisioner
from batch_shipyard_tpu.agent.node_agent import NodeAgent, NodeIdentity
from batch_shipyard_tpu.agent.nodeprep import run_node_prep
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.config.settings import StorageCredentialsSettings
from batch_shipyard_tpu.state.factory import create_statestore


def main(argv: list[str]) -> int:
    with open(argv[1], "r", encoding="utf-8") as fh:
        boot = json.load(fh)
    storage = StorageCredentialsSettings(
        backend=boot["storage"]["backend"],
        bucket=boot["storage"].get("bucket"),
        prefix=boot["storage"].get("prefix", "shipyardtpu"),
        root=boot["storage"].get("root"),
    )
    store = create_statestore(storage)
    pool = settings_mod.pool_settings(boot["pool_config"])
    identity = NodeIdentity(**boot["identity"])
    provisioner = CascadeImageProvisioner(store)
    agent = NodeAgent(
        store, identity, pool, work_dir=boot["work_dir"],
        heartbeat_interval=boot.get("heartbeat_interval", 10.0),
        poll_interval=boot.get("poll_interval", 0.5),
        node_stale_seconds=boot.get("node_stale_seconds", 30.0),
        nodeprep=(run_node_prep if boot.get("run_nodeprep", True)
                  else None),
        image_provisioner=provisioner,
        output_upload_cap_bytes=boot.get("output_upload_cap_bytes"),
        # Store-outage ride-through ON by default for real agent
        # processes: critical ops retry through outages, advisory
        # goodput/trace/heartbeat publishes journal to the node-local
        # WAL and replay in order on recovery (state/resilient.py).
        # Opt out (or tune) via the bootstrap's "resilience" block.
        resilience=boot.get("resilience", {}))

    def _stop(signum, frame):
        agent.stop()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    agent.start()
    agent.join()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))

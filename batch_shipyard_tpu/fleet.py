"""Fleet orchestration: one action_* function per CLI verb.

Reference analog: convoy/fleet.py (5486 LoC, ~90 action_* functions,
fleet.py:2974-5486). Ours is thinner because the heavy lifting lives in
the domain services (pool/jobs managers) and on the node agents; fleet
owns config loading/validation, wiring (state store + substrate), and
the cross-service flows.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import sys
from typing import Any, Optional

import yaml

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.config.validator import ConfigType, validate_config
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.state.factory import create_statestore
from batch_shipyard_tpu.substrate.base import (
    ComputeSubstrate, create_substrate)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_CONFIG_TYPES = {
    "credentials": ConfigType.CREDENTIALS,
    "config": ConfigType.GLOBAL,
    "pool": ConfigType.POOL,
    "jobs": ConfigType.JOBS,
    "fs": ConfigType.REMOTEFS,
    "monitor": ConfigType.MONITOR,
    "federation": ConfigType.FEDERATION,
    "slurm": ConfigType.SLURM,
}


@dataclasses.dataclass
class Context:
    """CliContext analog (shipyard.py:55): loaded+validated configs and
    lazily constructed clients."""

    configs: dict[str, dict]
    _store: Optional[StateStore] = None
    _substrates: dict[str, ComputeSubstrate] = dataclasses.field(
        default_factory=dict)
    substrate_kwargs: dict[str, Any] = dataclasses.field(
        default_factory=dict)
    _resolved_credentials: Optional[dict] = None

    # ------------------------- config access ---------------------------

    @property
    def secret_io(self) -> tuple:
        """(secrets_file, gcp_project) for secret:// resolution and
        storage — the single place that knows where these live in the
        credentials config (used by lazy credential resolution and
        the secrets CLI group)."""
        creds = self.configs.get("credentials", {}).get(
            "credentials", {})
        return ((creds.get("secrets") or {}).get("file"),
                (creds.get("gcp") or {}).get("project"))

    @property
    def credentials(self):
        # Secret indirection resolves lazily, on first credential use:
        # commands that never touch credentials must not fail (or pay
        # gcloud round trips) on secret:// values
        # (keyvault.parse_secret_ids analog).
        if self._resolved_credentials is None:
            raw = self.configs.get("credentials", {})
            from batch_shipyard_tpu.utils import secrets
            secrets_file, project = self.secret_io
            self._resolved_credentials = (
                secrets.resolve_config_secrets(raw, secrets_file,
                                               project))
        return settings_mod.credentials_settings(
            self._resolved_credentials)

    @property
    def global_settings(self):
        return settings_mod.global_settings(self.configs.get("config", {}))

    @property
    def pool(self):
        if "pool" not in self.configs:
            raise ValueError("pool config not loaded (pass --configdir "
                             "with pool.yaml or --pool)")
        return settings_mod.pool_settings(self.configs["pool"])

    @property
    def jobs(self):
        if "jobs" not in self.configs:
            raise ValueError("jobs config not loaded")
        return settings_mod.job_settings_list(self.configs["jobs"])

    # --------------------------- clients -------------------------------

    @property
    def store(self) -> StateStore:
        if self._store is None:
            creds = self.credentials
            # Headless identity (federation proxy VM, monitor VM,
            # slurm controller): activate the configured service
            # account before ANY cloud client is constructed.
            from batch_shipyard_tpu.utils import auth
            auth.ensure_service_account(creds.gcp)
            self._store = create_statestore(creds.storage)
        return self._store

    def substrate(self, pool=None) -> ComputeSubstrate:
        pool = pool or self.pool
        kind = pool.substrate
        if kind not in self._substrates:
            from batch_shipyard_tpu.utils import auth
            auth.ensure_service_account(self.credentials.gcp)
            kwargs = dict(self.substrate_kwargs.get(kind, {}))
            if kind == "localhost":
                kwargs.setdefault("pool_config", self.configs.get("pool"))
            self._substrates[kind] = create_substrate(
                kind, self.store, self.credentials, **kwargs)
        return self._substrates[kind]


def load_context(configdir: Optional[str] = None,
                 config_files: Optional[dict[str, str]] = None,
                 extra: Optional[dict[str, dict]] = None) -> Context:
    """Load + strictly validate every present config file
    (CliContext._init_config analog, --configdir convention
    shipyard.py:804)."""
    configs: dict[str, dict] = {}
    if configdir:
        base = pathlib.Path(configdir)
        for name in _CONFIG_TYPES:
            for suffix in (".yaml", ".yml", ".json"):
                path = base / f"{name}{suffix}"
                if path.exists():
                    with open(path, "r", encoding="utf-8") as fh:
                        configs[name] = yaml.safe_load(fh) or {}
                    break
    for name, path in (config_files or {}).items():
        with open(path, "r", encoding="utf-8") as fh:
            configs[name] = yaml.safe_load(fh) or {}
    for name, data in (extra or {}).items():
        configs[name] = data
    for name, data in configs.items():
        validate_config(_CONFIG_TYPES[name], data)
    return Context(configs=configs)


def _emit(payload: Any, raw: bool = False) -> None:
    if raw:
        sys.stdout.write(json.dumps(payload, indent=2, default=str) + "\n")
    else:
        yaml.safe_dump(payload, sys.stdout, default_flow_style=False,
                       sort_keys=False)


# ------------------------------ pool actions ---------------------------

def action_pool_add(ctx: Context, wait: bool = True,
                    quota_client=None) -> list:
    """pool add (fleet.py:3390 analog), preceded by an advisory
    quota/capacity preflight on real-cloud pools (reference `account
    quota` + resize-error classification, shipyard.py:1009,
    batch.py:661 — here the warning lands BEFORE allocation burns
    minutes). ``quota_client`` injects a fake for tests."""
    pool = ctx.pool
    for warning in _quota_preflight(ctx, quota_client):
        logger.warning("pool add preflight: %s", warning)
    nodes = pool_mgr.create_pool(
        ctx.store, ctx.substrate(), pool, ctx.global_settings,
        ctx.configs.get("pool"), wait=wait)
    logger.info("pool %s ready with %d nodes", pool.id, len(nodes))
    return nodes


def _quota_preflight(ctx: Context, quota_client=None) -> list[str]:
    """Advisory-only: never raises, never blocks (substrate/quota.py
    module doc)."""
    pool = ctx.pool
    if pool.substrate != "tpu_vm" or pool.tpu is None:
        return []
    try:
        from batch_shipyard_tpu.substrate import quota as quota_mod
        if quota_client is None:
            import shutil as shutil_mod
            if shutil_mod.which("gcloud") is None or \
                    ctx.credentials.gcp is None:
                return []
            quota_client = quota_mod.TpuQuotaClient(
                ctx.credentials.gcp.project)
        zone = pool.zone or (ctx.credentials.gcp.zone
                             if ctx.credentials.gcp else None)
        return quota_mod.preflight_pool(pool, quota_client,
                                        zone=zone)
    except Exception as exc:  # noqa: BLE001 - advisory only
        logger.debug("quota preflight skipped: %s", exc)
        return []


def action_pool_list(ctx: Context, raw: bool = False) -> None:
    pools = [{"id": p["_rk"], "state": p.get("state"),
              "created_at": p.get("created_at")}
             for p in pool_mgr.list_pools(ctx.store)]
    _emit({"pools": pools}, raw)


def action_pool_del(ctx: Context, pool_id: Optional[str] = None) -> None:
    pool_id = pool_id or ctx.pool.id
    pool_mgr.delete_pool(ctx.store, ctx.substrate(), pool_id)
    logger.info("pool %s deleted", pool_id)


def action_pool_resize(ctx: Context, num_slices: int,
                       wait: bool = True) -> None:
    pool_mgr.resize_pool(ctx.store, ctx.substrate(), ctx.pool,
                         num_slices, wait=wait)


def action_pool_nodes_list(ctx: Context, raw: bool = False) -> None:
    nodes = [dataclasses.asdict(n)
             for n in pool_mgr.list_nodes(ctx.store, ctx.pool.id)]
    _emit({"nodes": nodes}, raw)


def action_pool_stats(ctx: Context, raw: bool = False) -> None:
    _emit(pool_mgr.pool_stats(ctx.store, ctx.pool.id), raw)


def action_pool_nodes_count(ctx: Context, raw: bool = False) -> None:
    """Node-state histogram (reference shipyard.py:1868)."""
    _emit(pool_mgr.node_counts(ctx.store, ctx.pool.id), raw)


def action_pool_nodes_grls(ctx: Context,
                           node_id: Optional[str] = None,
                           raw: bool = False) -> None:
    """Remote-login settings for node(s) (reference
    convoy/batch.py:3074)."""
    _emit({"remote_login": pool_mgr.remote_login_settings(
        ctx.store, ctx.substrate(), ctx.pool.id, node_id)}, raw)


def action_pool_nodes_ps(ctx: Context,
                         node_id: Optional[str] = None,
                         raw: bool = False) -> None:
    """Running tasks/containers per node via the agent control
    channel (reference docker-ps-over-ssh, convoy/fleet.py:2468)."""
    # Fake-substrate agents live in-process: revive them so the
    # request/reply verbs have someone listening (no-op on real
    # substrates, whose agents run on the nodes).
    ctx.substrate().ensure_attached(ctx.pool)
    _emit({"nodes": pool_mgr.nodes_ps(ctx.store, ctx.pool.id,
                                      node_id)}, raw)


def action_pool_nodes_zap(ctx: Context,
                          node_id: Optional[str] = None,
                          raw: bool = False) -> None:
    """Kill all live task processes/containers on node(s)
    (reference shipyard.py:1906)."""
    ctx.substrate().ensure_attached(ctx.pool)
    _emit({"nodes": pool_mgr.nodes_zap(ctx.store, ctx.pool.id,
                                       node_id)}, raw)


def action_pool_nodes_prune(ctx: Context,
                            node_id: Optional[str] = None,
                            raw: bool = False) -> None:
    """Prune unreferenced image-cache entries on node(s)
    (reference shipyard.py:1919)."""
    ctx.substrate().ensure_attached(ctx.pool)
    _emit({"nodes": pool_mgr.nodes_prune(ctx.store, ctx.pool.id,
                                         node_id)}, raw)


def action_pool_nodes_reboot(ctx: Context, node_id: str) -> None:
    """Reboot a node by recreating its slice (reference
    shipyard.py:1882; TPU recovery granularity is the slice)."""
    s = pool_mgr.reboot_node(ctx.store, ctx.substrate(), ctx.pool,
                             node_id)
    _emit({"node_id": node_id, "recreated_slice": s})


def action_pool_nodes_del(ctx: Context, node_id: str) -> None:
    """Delete a node by deallocating its slice without replacement
    (reference shipyard.py:1795)."""
    s = pool_mgr.delete_node(ctx.store, ctx.substrate(), ctx.pool,
                             node_id)
    _emit({"node_id": node_id, "deallocated_slice": s})


def action_pool_ssh(ctx: Context, node_id: str) -> Optional[tuple]:
    login = ctx.substrate().get_remote_login(ctx.pool.id, node_id)
    if login is None:
        logger.error("no remote login for %s", node_id)
        return None
    _emit({"node": node_id, "ip": login[0], "port": login[1]})
    return login


def action_pool_images_update(ctx: Context, image: str,
                              kind: str = "docker") -> None:
    """Force image (re)load on all nodes (fleet.py:2241 analog)."""
    for node in pool_mgr.list_nodes(ctx.store, ctx.pool.id):
        pool_mgr.send_control(ctx.store, ctx.pool.id, node.node_id, {
            "type": "load_images", "images": [image], "kind": kind})


def action_pool_suspend(ctx: Context) -> None:
    pool = ctx.pool
    ctx.substrate().suspend_pool(pool)
    ctx.store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                           {"state": "suspended"})
    logger.info("pool %s suspended", pool.id)


def action_pool_start(ctx: Context) -> None:
    pool = ctx.pool
    ctx.substrate().start_pool(pool)
    nodes = pool_mgr.wait_for_pool_ready(ctx.store, ctx.substrate(),
                                         pool)
    ctx.store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                           {"state": "ready"})
    logger.info("pool %s started with %d nodes", pool.id, len(nodes))


def action_pool_user_add(ctx: Context, username: str,
                         output_dir: str = ".") -> tuple[str, str]:
    """Generate a keypair and install the public key on every node
    (pool user add analog, batch.py:1045)."""
    from batch_shipyard_tpu.utils import crypto
    private_path, public_path = crypto.generate_ssh_keypair(
        output_dir, name=f"id_rsa_shipyard_{ctx.pool.id}")
    with open(public_path, "r", encoding="utf-8") as fh:
        public_key = fh.read().strip()
    for node in pool_mgr.list_nodes(ctx.store, ctx.pool.id):
        pool_mgr.send_control(ctx.store, ctx.pool.id, node.node_id, {
            "type": "install_ssh_key", "username": username,
            "public_key": public_key})
    logger.info("ssh key %s fanned out to pool %s", public_path,
                ctx.pool.id)
    return private_path, public_path


def action_pool_user_del(ctx: Context, username: str) -> None:
    for node in pool_mgr.list_nodes(ctx.store, ctx.pool.id):
        pool_mgr.send_control(ctx.store, ctx.pool.id, node.node_id, {
            "type": "remove_ssh_user", "username": username})


def action_diag_logs_upload(ctx: Context) -> int:
    """Ask every node to ship its logs to the object store
    (diag logs upload analog, batch.py:3151)."""
    count = 0
    for node in pool_mgr.list_nodes(ctx.store, ctx.pool.id):
        pool_mgr.send_control(ctx.store, ctx.pool.id, node.node_id,
                              {"type": "upload_logs"})
        count += 1
    return count


def action_account_info(ctx: Context, raw: bool = False) -> None:
    """Account/environment summary (account info/quota analog,
    shipyard.py:1009)."""
    creds = ctx.credentials
    info: dict = {
        "storage_backend": creds.storage.backend,
        "storage_prefix": creds.storage.prefix,
        "gcp_project": creds.gcp.project if creds.gcp else None,
        "pools": [p["_rk"] for p in pool_mgr.list_pools(ctx.store)],
    }
    # Subprocess probe with a hard timeout: a wedged accelerator
    # relay must yield an honest "unreachable" here, not a hung CLI
    # (in-process jax.devices() can BLOCK, not fail — see
    # TPU_WEDGE_REPORT.md).
    from batch_shipyard_tpu.utils.util import probe_default_devices
    count, reason = probe_default_devices(timeout=30.0)
    info["local_accelerator_count"] = count
    if reason:
        info["local_accelerator_error"] = reason
    _emit(info, raw)


# ------------------------------ job actions ----------------------------

def _submit_auto_pool_job(ctx: Context, job) -> dict:
    """Provision a dedicated pool for one job and submit the job to it
    (reference _construct_auto_pool_specification, fleet.py:1768: pool
    lifetime tied to the job). The pool spec is the configured pool
    with a derived id; action_autopool_reap (or the CLI's
    `jobs autopool-reap`) deletes it once the job completes."""
    import copy

    auto_id = f"{job.id}-autopool"
    conf = copy.deepcopy(ctx.configs.get("pool"))
    conf["pool_specification"]["id"] = auto_id
    auto_pool = settings_mod.pool_settings(conf)
    substrate = ctx.substrate(auto_pool)
    create_exc: Optional[BaseException] = None
    try:
        pool_mgr.create_pool(ctx.store, substrate, auto_pool,
                             ctx.global_settings, conf)
    except BaseException as exc:
        create_exc = exc
        raise
    finally:
        # Mark even on a failed/timed-out create (the record is
        # inserted before allocation): a half-created auto pool must
        # stay reapable, never a leaked allocation. The bookkeeping
        # must not mask an in-flight create_pool exception — but on
        # the success path a marking failure MUST surface (an
        # unmarked pool would silently leak).
        try:
            if pool_mgr.pool_exists(ctx.store, auto_id):
                ctx.store.merge_entity(names.TABLE_POOLS, "pools",
                                       auto_id, {
                    "auto_pool_for": job.id,
                    "auto_pool_keep_alive": bool(
                        (job.auto_pool or {}).get("keep_alive",
                                                  False)),
                })
        except Exception:  # noqa: BLE001
            logger.exception(
                "failed to mark auto pool %s reapable", auto_id)
            if create_exc is None:
                raise
    if not job.auto_complete:
        # The pool's lifetime is the job's: the job must be able to
        # reach a completed state on its own.
        job = dataclasses.replace(job, auto_complete=True)
    # Override any job-level pool_id: an auto_pool job lives on its
    # derived pool by definition.
    return jobs_mgr.add_jobs(ctx.store, auto_pool, [job],
                             pool_id_override=auto_id)


def action_autopool_reap(ctx: Context) -> list[str]:
    """Delete auto pools whose job completed (keep_alive pools are
    left). Run after jobs finish or periodically."""
    reaped = []
    for rec in pool_mgr.list_pools(ctx.store):
        job_id = rec.get("auto_pool_for")
        if not job_id or rec.get("auto_pool_keep_alive"):
            continue
        pool_id = rec["_rk"]
        try:
            job = jobs_mgr.get_job(ctx.store, pool_id, job_id)
        except jobs_mgr.JobNotFoundError:
            # Job record deleted: the pool has nothing to live for.
            # (Transient store errors must propagate — never treat
            # them as "completed" and delete a live pool.)
            job = {"state": "completed"}
        if job.get("state") == "completed":
            spec = rec.get("spec", {}).get("pool_specification", {})
            kind_pool = settings_mod.pool_settings(rec.get("spec", {})) \
                if spec else ctx.pool
            pool_mgr.delete_pool(ctx.store, ctx.substrate(kind_pool),
                                 pool_id)
            reaped.append(pool_id)
            logger.info("auto pool %s reaped (job %s completed)",
                        pool_id, job_id)
    return reaped


def action_jobs_add(ctx: Context, tail: Optional[str] = None) -> dict:
    """jobs add (fleet.py:4000 analog). tail: stream the given file of
    the last task submitted (reference --tail)."""
    pool = ctx.pool
    # Recurrence-bearing jobs REGISTER as pool schedules (fired by the
    # pool-resident scheduler or `jobs schedule`) instead of running
    # once immediately — the reference's JobScheduleAdd split.
    recurrent = [j for j in ctx.jobs if j.recurrence is not None]
    if recurrent:
        from batch_shipyard_tpu.jobs import schedules
        registered = schedules.register_schedules(
            ctx.store, pool.id, ctx.configs["jobs"])
        logger.info("registered schedules %s", registered)
    regular = [j for j in ctx.jobs
               if not j.auto_pool and j.recurrence is None]
    submitted = {}
    for job in ctx.jobs:
        if job.auto_pool and job.recurrence is None:
            submitted.update(_submit_auto_pool_job(ctx, job))
    if regular:
        ctx.substrate().ensure_attached(pool)
        submitted.update(jobs_mgr.add_jobs(ctx.store, pool, regular))
    logger.info("submitted %s", submitted)
    if tail:
        job = ctx.jobs[-1]
        tail_pool = (f"{job.id}-autopool" if job.auto_pool
                     else pool.id)
        tasks = jobs_mgr.list_tasks(ctx.store, tail_pool, job.id)
        if tasks:
            last = sorted(t["_rk"] for t in tasks)[-1]
            for chunk in jobs_mgr.stream_task_output(
                    ctx.store, tail_pool, job.id, last, filename=tail):
                sys.stdout.write(chunk.decode(errors="replace"))
                sys.stdout.flush()
    return submitted


def action_jobs_list(ctx: Context, raw: bool = False) -> None:
    jobs = [{"id": j["_rk"], "state": j.get("state")}
            for j in jobs_mgr.list_jobs(ctx.store, ctx.pool.id)]
    _emit({"jobs": jobs}, raw)


def action_jobs_tasks_list(ctx: Context, job_id: str,
                           raw: bool = False) -> None:
    from batch_shipyard_tpu.trace import context as trace_ctx
    from batch_shipyard_tpu.trace import profiling as trace_prof
    tasks = []
    for t in jobs_mgr.list_tasks(ctx.store, ctx.pool.id, job_id):
        row = {"id": t["_rk"], "state": t.get("state"),
               "exit_code": t.get("exit_code"),
               "node_id": t.get("node_id")}
        # The submission's trace id: the handle `shipyard trace
        # show|export` takes (absent on legacy pre-trace rows).
        if t.get(trace_ctx.COL_TRACE_ID):
            row["trace_id"] = t.get(trace_ctx.COL_TRACE_ID)
        # On-demand profiling artifact, next to the diagnostics
        # column: the object-store prefix the capture uploaded to.
        if t.get(trace_prof.COL_PROFILE_ARTIFACT):
            row["profile_artifact"] = t.get(
                trace_prof.COL_PROFILE_ARTIFACT)
        if t.get("retries"):
            row["retries"] = t.get("retries")
        if t.get("wedged"):
            row["wedged"] = True
        # Poison quarantine surfaces its post-mortem right here: the
        # retry supervisor's diagnostics bundle (stderr tail, node /
        # exit-code history) so the operator never greps node logs.
        if t.get("state") == names.TASK_STATE_QUARANTINED:
            row["error"] = t.get("error")
            diag = dict(t.get("diagnostics") or {})
            history = diag.get("attempt_history") or []
            if history:
                # Operator-friendly projections of attempt_history
                # (the entity stores only the one source of truth).
                diag["node_history"] = [a.get("node_id")
                                        for a in history]
                diag["exit_codes"] = [a.get("exit_code")
                                      for a in history]
            row["diagnostics"] = diag
        tasks.append(row)
    _emit({"tasks": tasks}, raw)


def action_jobs_term(ctx: Context, job_id: Optional[str] = None,
                     wait: bool = False) -> None:
    for job in ([job_id] if job_id else [j.id for j in ctx.jobs]):
        jobs_mgr.terminate_job(ctx.store, ctx.pool.id, job, wait=wait)


def action_jobs_del(ctx: Context, job_id: Optional[str] = None) -> None:
    for job in ([job_id] if job_id else [j.id for j in ctx.jobs]):
        jobs_mgr.delete_job(ctx.store, ctx.pool.id, job)


def action_jobs_stats(ctx: Context, job_id: Optional[str] = None,
                      raw: bool = False) -> None:
    _emit(jobs_mgr.job_stats(ctx.store, ctx.pool.id, job_id), raw)


def action_jobs_wait(ctx: Context, job_id: str,
                     timeout: float = 600.0,
                     goodput_report: bool = False,
                     raw: bool = False) -> list[dict]:
    """Block until every task of a job is terminal; optionally follow
    with the job's goodput decomposition (--goodput-report)."""
    ctx.substrate().ensure_attached(ctx.pool)
    tasks = jobs_mgr.wait_for_tasks(ctx.store, ctx.pool.id, job_id,
                                    timeout=timeout)
    _emit({"tasks": [{"id": t["_rk"], "state": t.get("state"),
                      "exit_code": t.get("exit_code")}
                     for t in tasks]}, raw)
    if goodput_report:
        action_goodput(ctx, "job", job_id=job_id, raw=raw)
    return tasks


# ---------------------------- compile cache ----------------------------

def action_pool_cache_stats(ctx: Context, raw: bool = False) -> dict:
    """Seed-artifact state of the pool's warm-start compile cache
    (compilecache/seeding.py): latest identity/entries/bytes plus the
    stored artifact list."""
    from batch_shipyard_tpu.compilecache import seeding
    report = seeding.stats(ctx.store, ctx.pool.id)
    _emit(report, raw)
    return report


def action_pool_cache_seed(ctx: Context, cache_dir: str,
                           raw: bool = False) -> str:
    """Seed a LOCAL cache dir from the pool artifact (the node
    agents seed themselves before each task; this verb serves dev
    boxes and pre-bake pipelines). Refuses a mismatched identity."""
    from batch_shipyard_tpu.compilecache import seeding
    status = seeding.seed_cache(ctx.store, ctx.pool.id, cache_dir)
    _emit({"pool_id": ctx.pool.id, "cache_dir": cache_dir,
           "status": status,
           "seeded": status == seeding.SEEDED}, raw)
    return status


def action_pool_cache_prune(ctx: Context, raw: bool = False) -> int:
    """Drop the pool's cache artifacts (the stale-cache escape hatch:
    after a jax/jaxlib upgrade or model change the old seed can only
    miss — see docs/17-troubleshooting.md)."""
    from batch_shipyard_tpu.compilecache import seeding
    removed = seeding.prune(ctx.store, ctx.pool.id)
    _emit({"pool_id": ctx.pool.id, "removed": removed}, raw)
    return removed


# ------------------------------- tracing -------------------------------

def action_jobs_profile(ctx: Context, job_id: str,
                        steps: int = 10) -> dict:
    """`jobs profile`: stamp an on-demand profiling request on the
    job entity. Node agents forward it to the job's tasks (at launch
    and, via the heartbeat loop, to already-running ones); the train
    harness wraps the next N steps in jax.profiler.trace and the
    agent uploads the artifact next to the task's diagnostics."""
    from batch_shipyard_tpu.trace import profiling as trace_prof
    jobs_mgr.get_job(ctx.store, ctx.pool.id, job_id)  # must exist
    request = {"steps": int(steps),
               "requested_at": util.datetime_utcnow_iso()}
    ctx.store.merge_entity(
        names.TABLE_JOBS, ctx.pool.id, job_id,
        {trace_prof.COL_PROFILE_REQUEST: request})
    logger.info("profile request (%d steps) stamped on job %s",
                steps, job_id)
    _emit({"job_id": job_id, "profile_request": request})
    return request


def action_jobs_preempt(ctx: Context, job_id: str, task_id: str,
                        reason: str = "") -> bool:
    """`jobs preempt`: stamp a cooperative preempt request on a
    running task (the preempt sweep's manual override). The owning
    node delivers it over the heartbeat path; an instrumented
    workload drains to its next step boundary, forces a COMMITTED
    checkpoint, and exits with the distinct preempted status —
    requeued at FULL retry budget, node health untouched."""
    ok = jobs_mgr.request_preemption(
        ctx.store, ctx.pool.id, job_id, task_id,
        reason=reason or "operator request (jobs preempt)")
    _emit({"job_id": job_id, "task_id": task_id, "requested": ok})
    if not ok:
        logger.warning("task %s/%s is not in a preemptible state",
                       job_id, task_id)
    return ok


def action_trace_show(ctx: Context, trace_id: str,
                      raw: bool = False) -> dict:
    """`trace show <trace_id>`: terminal waterfall of one
    submission's spans (+ its goodput intervals)."""
    from batch_shipyard_tpu.trace import export as trace_export
    rows = trace_export.trace_rows(ctx.store, ctx.pool.id, trace_id)
    if raw:
        _emit(rows, raw=True)
    else:
        sys.stdout.write(trace_export.render_tree(rows) + "\n")
    return rows


def action_trace_export(ctx: Context, trace_id: str,
                        output: Optional[str] = None) -> dict:
    """`trace export <trace_id>`: Chrome trace-event JSON
    (chrome://tracing / ui.perfetto.dev loadable), to ``output`` or
    stdout."""
    from batch_shipyard_tpu.trace import export as trace_export
    chrome = trace_export.export_trace(ctx.store, ctx.pool.id,
                                       trace_id)
    if output:
        trace_export.write_chrome_trace(chrome, output)
        logger.info("trace %s exported to %s (%d events)", trace_id,
                    output, len(chrome["traceEvents"]))
    else:
        sys.stdout.write(json.dumps(chrome, indent=2) + "\n")
    return chrome


# ------------------------------- goodput -------------------------------

def action_goodput(ctx: Context, scope: str,
                   job_id: Optional[str] = None,
                   raw: bool = False,
                   trace_id: Optional[str] = None) -> dict:
    """Goodput decomposition + badput waterfall for a job, the pool,
    or the whole fleet (goodput/accounting.py over TABLE_GOODPUT).
    ``trace_id`` (job scope only) restricts the waterfall to one
    submission's trace."""
    from batch_shipyard_tpu.goodput import accounting
    if trace_id is not None and scope != "job":
        raise ValueError("--trace only applies to `goodput job`")
    if scope == "job":
        if not job_id:
            raise ValueError("goodput job requires a job id")
        report = accounting.job_report(ctx.store, ctx.pool.id, job_id,
                                       trace_id=trace_id)
    elif scope == "pool":
        report = accounting.pool_report(ctx.store, ctx.pool.id)
    elif scope == "fleet":
        report = accounting.fleet_report(ctx.store)
    else:
        raise ValueError(f"unknown goodput scope {scope!r}")
    if raw:
        _emit(report, raw=True)
    else:
        sys.stdout.write(accounting.waterfall_table(report) + "\n")
        if scope == "fleet":
            for pool_id in sorted(report.get("pools", {})):
                sys.stdout.write(
                    f"\n== pool {pool_id} ==\n"
                    + accounting.waterfall_table(
                        report["pools"][pool_id]) + "\n")
        elif scope == "pool":
            for jid in sorted(report.get("jobs", {})):
                sys.stdout.write(
                    f"\n== job {jid} ==\n"
                    + accounting.waterfall_table(
                        report["jobs"][jid]) + "\n")
    return report


# -------------------------------- chaos --------------------------------

def action_chaos_plan(ctx_or_none, seed: int, duration: float = 4.0,
                      num_nodes: int = 4,
                      kinds: Optional[tuple[str, ...]] = None,
                      injections_per_kind: int = 1,
                      raw: bool = False) -> dict:
    """Render a deterministic fault schedule (chaos/plan.py) without
    running it — same seed, same injection sequence, so operators can
    review exactly what a drill will do (and name a scenario by its
    seed + fingerprint). Needs no live pool or config context."""
    from batch_shipyard_tpu.chaos.plan import ChaosPlan
    plan = ChaosPlan.generate(
        seed, duration=duration, num_nodes=num_nodes, kinds=kinds,
        injections_per_kind=injections_per_kind)
    payload = plan.to_dict()
    _emit(payload, raw)
    return payload


def action_chaos_drill(ctx_or_none, seed: int, tasks: int = 16,
                       duration: float = 4.0,
                       kinds: Optional[tuple[str, ...]] = None,
                       injections_per_kind: int = 1,
                       preempt: bool = False,
                       victim: bool = False,
                       evict: bool = False,
                       resize: bool = False,
                       migrate: bool = False,
                       outage: bool = False,
                       partition: bool = False,
                       restart: bool = False,
                       serve_kill: bool = False,
                       serve_drain: bool = False,
                       serve_router: bool = False,
                       raw: bool = False) -> dict:
    """Run a seeded chaos drill against a self-contained fakepod pool
    (chaos/drill.py) and report the recovery invariants: every task
    completed exactly once, no orphaned gang rows or queue messages,
    goodput partition exact. Raises on any violated invariant, so a
    nonzero exit IS the regression signal.

    ``preempt=True`` runs the PREEMPTION drill instead: a seeded
    node_preempt_notice schedule against a running 4-node gang —
    cooperative drain, forced COMMITTED checkpoint, zero lost steps,
    retry budget + node health untouched, preemption_recovery
    populated. ``victim=True`` runs the victim-SELECTION drill: two
    eligible victims (a warm-cache never-committer vs a per-step
    committer), a strictly higher-priority starver — the sweep's
    goodput-cost ordering (sched/policy.py) must elect the cheap
    victim even though the id tie-break points at the costly one.

    The fleet-elasticity drills (one flag each, ISSUE 12):
    ``evict=True`` — an --ignore-notice victim burns its grace
    window, is hard-killed by the escalation ladder, classified
    evicted (full budget, neutral health) and resumes from the
    pre-notice COMMITTED barrier, with the ``eviction`` leg priced;
    ``resize=True`` — a 2-host sharded gang loses a host permanently,
    re-forms at 1 host and restores bit-exactly through the per-host
    reshard plan; ``migrate=True`` — a two-pool federation loses ALL
    capacity under a gang, which migrates to the sibling pool with
    one trace spanning the move and the ``migration`` leg priced.

    The control-plane drills (one flag each, ISSUE 13):
    ``outage=True`` — the state store goes DOWN for a sustained
    window; resilient-store agents ride it out (zero retries, zero
    lost advisory events, journals drained, the ``store_outage`` leg
    priced with the exact window); ``partition=True`` — the preempt-
    sweep leader's heartbeats/lease renewals stall while its sweep
    keeps running: exactly one preemption stamp fires, carrying the
    successor term's fencing epoch, with exactly one live lease at
    the end; ``restart=True`` — the agent process dies under a
    running task and the revived agent re-adopts it from the slot
    ledger (one start, retries==0, the ``adoption`` leg priced).

    The serving-tier drills (one flag each, chaos/serving_drill.py):
    ``serve_kill=True`` — a serving replica dies SIGKILL-style under
    live token streams; the router resumes every stream on the
    sibling, exactly-once and byte-identical to a clean greedy
    decode; ``serve_drain=True`` — a preempt notice drains a replica
    through the full ladder (healthz 503+marker, 503+Retry-After
    admissions, router routes around it as cooperative-not-fault,
    grace-deadline abandons resumed elsewhere); ``serve_router=True``
    — the serving router itself crashes mid-stream and clients
    cancel-then-resume through a successor, the replicas' duplicate
    gates keeping delivery exactly-once. All three price their
    recoveries into the ``serving_recovery`` goodput leg."""
    from batch_shipyard_tpu.chaos import drill
    picked = [flag for flag, on in (("preempt", preempt),
                                    ("victim", victim),
                                    ("evict", evict),
                                    ("resize", resize),
                                    ("migrate", migrate),
                                    ("outage", outage),
                                    ("partition", partition),
                                    ("restart", restart),
                                    ("serve-kill", serve_kill),
                                    ("serve-drain", serve_drain),
                                    ("serve-router", serve_router),
                                    ) if on]
    if len(picked) > 1:
        raise ValueError(
            f"pick at most one drill flag, got {picked}")
    if preempt:
        report = drill.run_preemption_drill(seed=seed,
                                            duration=duration)
    elif victim:
        report = drill.run_victim_selection_drill(seed=seed)
    elif evict:
        report = drill.run_eviction_drill(seed=seed,
                                          duration=duration)
    elif resize:
        report = drill.run_host_resize_drill(seed=seed,
                                             duration=duration)
    elif migrate:
        report = drill.run_migration_drill(seed=seed,
                                           duration=duration)
    elif outage:
        report = drill.run_store_outage_drill(seed=seed)
    elif partition:
        report = drill.run_leader_partition_drill(seed=seed)
    elif restart:
        report = drill.run_agent_restart_drill(seed=seed)
    elif serve_kill or serve_drain or serve_router:
        from batch_shipyard_tpu.chaos import serving_drill
        if serve_kill:
            report = serving_drill.run_replica_kill_drill(seed=seed)
        elif serve_drain:
            report = serving_drill.run_replica_drain_drill(seed=seed)
        else:
            report = serving_drill.run_router_restart_drill(seed=seed)
    else:
        report = drill.run_drill(
            seed=seed, tasks=tasks, duration=duration, kinds=kinds,
            injections_per_kind=injections_per_kind)
    _emit({"seed": report["seed"],
           "fingerprint": report["fingerprint"],
           "invariants": report["invariants"],
           "applied": report["applied"],
           "goodput": report.get("goodput", {})}, raw)
    return report


# ------------------------------ fleet sim ------------------------------

def action_sim_run(ctx_or_none, scenario: str = "steady",
                   policy: str = "baseline", seed: int = 0,
                   nodes: int = 200, tasks: int = 2000,
                   raw: bool = False) -> dict:
    """One discrete-event fleet simulation (sim/simulator.py): a named
    scenario (sim/scenarios.py) at ``nodes`` virtual nodes under one
    policy bundle (sched/policy.py POLICIES), priced by the real
    goodput engine. Deterministic: same (seed, scenario, shape,
    policy) ⇒ byte-identical report (the fingerprint pins it). Needs
    no live pool or config context."""
    from batch_shipyard_tpu.sim import scenarios as sim_scenarios
    from batch_shipyard_tpu.sim import simulator as sim_mod
    kwargs = sim_scenarios.build(scenario, seed, nodes, tasks)
    report = sim_mod.run_sim(policy=policy, **kwargs)
    report["scenario"] = scenario
    report["seed"] = seed
    _emit(report, raw)
    return report


def action_sim_scenarios(ctx_or_none, raw: bool = False) -> dict:
    """List the scenario registry (sim/scenarios.py) and the policy
    bundles it can be run under."""
    from batch_shipyard_tpu.sched import policy as sched_policy
    from batch_shipyard_tpu.sim import scenarios as sim_scenarios
    payload = {
        "scenarios": dict(sorted(sim_scenarios.DESCRIPTIONS.items())),
        "policies": {
            name: {"claim_scoring": cfg.claim_scoring,
                   "victim_by_cost": cfg.victim_by_cost,
                   "autoscale_goodput": cfg.autoscale_goodput}
            for name, cfg in sched_policy.POLICIES.items()},
    }
    _emit(payload, raw)
    return payload


def action_sim_compare(ctx_or_none, scenario: str = "steady",
                       policies: Optional[tuple[str, ...]] = None,
                       seed: int = 0, nodes: int = 200,
                       tasks: int = 2000, raw: bool = False) -> dict:
    """Run one scenario under several policy bundles (always including
    ``baseline``) and report each policy's goodput delta vs baseline —
    the before/after partition the fleet simulator exists to produce.
    The summary keeps the full per-policy reports under ``runs``."""
    from batch_shipyard_tpu.sched import policy as sched_policy
    from batch_shipyard_tpu.sim import scenarios as sim_scenarios
    from batch_shipyard_tpu.sim import simulator as sim_mod
    names_list = list(policies) if policies else \
        list(sched_policy.POLICIES)
    if "baseline" not in names_list:
        names_list.insert(0, "baseline")
    reports = {}
    for name in names_list:
        kwargs = sim_scenarios.build(scenario, seed, nodes, tasks)
        reports[name] = sim_mod.run_sim(policy=name, **kwargs)
    compared = sim_mod.compare(reports)
    summary = {"scenario": scenario, "seed": seed, "nodes": nodes,
               "tasks": tasks, "policies": {}}
    for name, entry in compared.items():
        rep = entry["report"]
        row = {"goodput_ratio": rep["goodput"]["goodput_ratio"],
               "fingerprint": rep["fingerprint"]}
        if "delta_vs_baseline" in entry:
            row["goodput_ratio_delta"] = \
                entry["delta_vs_baseline"]["goodput_ratio_delta"]
            row["badput_seconds_delta"] = \
                entry["delta_vs_baseline"]["badput_seconds_delta"]
            row["queue_wait_mean_delta"] = \
                entry["queue_wait_mean_delta"]
        summary["policies"][name] = row
    _emit(summary, raw)
    summary["runs"] = reports
    return summary


def action_data_stream(ctx: Context, job_id: str, task_id: str,
                       filename: str = "stdout.txt") -> None:
    """data files stream (fleet.py action analog of batch.py:3243)."""
    ctx.substrate().ensure_attached(ctx.pool)
    for chunk in jobs_mgr.stream_task_output(
            ctx.store, ctx.pool.id, job_id, task_id, filename=filename):
        sys.stdout.write(chunk.decode(errors="replace"))
        sys.stdout.flush()


# ----------------------------- diagnostics -----------------------------

def action_lint(ctx_or_none, baseline_update: bool = False,
                rules: Optional[tuple[str, ...]] = None,
                list_rules: bool = False,
                raw: bool = False) -> dict:
    """Run the distributed-invariant static analyzer (analysis/) over
    this source tree and report findings against the checked-in
    baseline. Needs no live pool or config context — it is the same
    gate tests/test_analysis.py runs in tier-1.

    ``baseline_update=True`` rewrites .shipyard-lint-baseline.json
    deterministically (sorted, path-relative, line numbers omitted)
    from the current findings, so triage diffs review like code.
    Returns the report dict; callers exit nonzero on new findings."""
    from batch_shipyard_tpu import analysis
    if list_rules:
        rows = [{"rule": r.id, "family": r.family,
                 "doc": " ".join(r.doc.split())}
                for r in sorted(analysis.RULES.values(),
                                key=lambda r: (r.family, r.id))]
        _emit({"rules": rows}, raw)
        return {"rules": rows}
    if baseline_update and rules:
        # The baseline is rewritten WHOLE from the run's findings: a
        # partial-rule run would silently drop every other rule's
        # triaged entries.
        raise ValueError(
            "--baseline-update requires a full-rule run; drop "
            "--rules")
    root = analysis.repo_root()
    report = analysis.analyze(root=root, rule_ids=rules)
    if baseline_update:
        analysis.write_baseline(
            root / analysis.BASELINE_FILENAME, report.all_active)
        payload = {"baseline": analysis.BASELINE_FILENAME,
                   "recorded": len(report.all_active)}
        _emit(payload, raw)
        return payload
    payload = report.to_dict()
    # Stale entries fail here too, exactly like the tier-1 pytest
    # gate — the two surfaces must agree or triage debt stops
    # shrinking.
    payload["clean"] = not report.new and not report.stale_baseline
    _emit(payload, raw)
    return payload


def action_perf_events(ctx: Context, raw: bool = False) -> None:
    from batch_shipyard_tpu.agent import perf
    events = [{"t": e["timestamp"], "node": e["node_id"],
               "source": e["source"], "event": e["event"]}
              for e in perf.query(ctx.store, ctx.pool.id)]
    _emit({"events": events}, raw)

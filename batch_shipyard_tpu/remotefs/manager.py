"""RemoteFS: standalone shared-filesystem clusters for pools.

Reference analog: convoy/remotefs.py (2040 LoC — managed disks, NFS or
GlusterFS storage-cluster VMs with mdadm RAID-0 via
shipyard_remotefs_bootstrap.sh, mount-args generation for compute
pools :56) and scripts/shipyard_remotefs_bootstrap.sh.

TPU-native mapping: the common shared-FS for TPU pods is either (a) a
GCS bucket via gcsfuse (serverless, preferred — replaces most
GlusterFS use), or (b) an NFS server VM with striped persistent disks
(the direct remotefs analog). This module keeps cluster records in the
state store, generates the NFS server bootstrap script + fstab mount
args for pool nodes, and provisions the server VM through gcloud when
available (gated; records/plans always work for tests).
"""

from __future__ import annotations

import shutil
from typing import Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, NotFoundError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_TABLE = names.TABLE_REMOTEFS
_NODES_TABLE = names.TABLE_REMOTEFS_NODES


def create_storage_cluster_record(
        store: StateStore, cluster_id: str, fs_type: str = "nfs",
        disk_count: int = 2, disk_size_gb: int = 256,
        disk_type: str = "pd-ssd", vm_size: str = "n2-standard-8",
        export_path: str = "/export/shipyard") -> dict:
    """Register a storage cluster (create_storage_cluster :623 analog;
    actual VM provisioning is provision_nfs_server)."""
    record = {
        "fs_type": fs_type, "disk_count": disk_count,
        "disk_size_gb": disk_size_gb, "disk_type": disk_type,
        "vm_size": vm_size, "export_path": export_path,
        "state": "defined",
        "created_at": util.datetime_utcnow_iso(),
    }
    try:
        store.insert_entity(_TABLE, "remotefs", cluster_id, record)
    except EntityExistsError:
        raise ValueError(f"storage cluster {cluster_id} exists")
    return record


def get_storage_cluster(store: StateStore, cluster_id: str) -> dict:
    try:
        return store.get_entity(_TABLE, "remotefs", cluster_id)
    except NotFoundError:
        raise ValueError(f"storage cluster {cluster_id} not found")


def delete_storage_cluster(store: StateStore, cluster_id: str) -> None:
    get_storage_cluster(store, cluster_id)
    for row in list(store.query_entities(_NODES_TABLE,
                                         partition_key=cluster_id)):
        store.delete_entity(_NODES_TABLE, cluster_id, row["_rk"])
    store.delete_entity(_TABLE, "remotefs", cluster_id)


def expand_storage_cluster(store: StateStore, cluster_id: str,
                           additional_disks: int) -> dict:
    """Record additional data disks (expand_storage_cluster :1171
    analog; on a live server this triggers mdadm --grow via ssh)."""
    cluster = get_storage_cluster(store, cluster_id)
    store.merge_entity(_TABLE, "remotefs", cluster_id, {
        "disk_count": int(cluster["disk_count"]) + additional_disks},
        if_match=cluster["_etag"])
    return get_storage_cluster(store, cluster_id)


def generate_nfs_bootstrap_script(cluster: dict) -> str:
    """NFS server first-boot script: stripe the data disks with mdadm,
    mkfs, export (shipyard_remotefs_bootstrap.sh setup_nfs :49
    analog, re-written for GCE device naming)."""
    export = cluster.get("export_path", "/export/shipyard")
    disks = int(cluster.get("disk_count", 2))
    dev_list = " ".join(
        f"/dev/disk/by-id/google-data{i}" for i in range(disks))
    return f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu remotefs NFS bootstrap
if [ ! -e /dev/md0 ]; then
  mdadm --create /dev/md0 --level=0 --raid-devices={disks} {dev_list}
  mkfs.ext4 -F /dev/md0
fi
mkdir -p {export}
grep -q '/dev/md0' /etc/fstab || \\
  echo '/dev/md0 {export} ext4 defaults,noatime 0 0' >> /etc/fstab
mountpoint -q {export} || mount {export}
apt-get update && apt-get install -y nfs-kernel-server
grep -q '{export}' /etc/exports || \\
  echo '{export} *(rw,sync,no_subtree_check,no_root_squash)' \\
    >> /etc/exports
exportfs -ra
systemctl enable --now nfs-kernel-server
"""


def create_storage_cluster_mount_args(
        store: StateStore, cluster_id: str,
        mount_point: str = "/mnt/shipyard") -> list[str]:
    """fstab mount lines for compute-pool nodes
    (create_storage_cluster_mount_args remotefs.py:56 analog)."""
    cluster = get_storage_cluster(store, cluster_id)
    nodes = list(store.query_entities(_NODES_TABLE,
                                      partition_key=cluster_id))
    if not nodes:
        raise ValueError(
            f"storage cluster {cluster_id} has no provisioned nodes")
    server_ip = nodes[0].get("internal_ip")
    export = cluster.get("export_path", "/export/shipyard")
    if cluster.get("fs_type") == "nfs":
        return [f"{server_ip}:{export} {mount_point} nfs4 "
                f"defaults,_netdev,noatime,hard,proto=tcp 0 0"]
    raise ValueError(
        f"unsupported fs_type {cluster.get('fs_type')!r} "
        f"(gcsfuse mounts are configured via pool shared volumes)")


def gcsfuse_mount_args(bucket: str,
                       mount_point: str = "/mnt/gcs") -> list[str]:
    """GCS-FUSE shared volume mount (the serverless GlusterFS
    replacement for TPU pods)."""
    return [f"{bucket} {mount_point} gcsfuse "
            f"rw,_netdev,allow_other,implicit_dirs 0 0"]


def provision_nfs_server(store: StateStore, cluster_id: str,
                         project: str, zone: Optional[str] = None,
                         network: Optional[str] = None) -> None:
    """Create the NFS server VM + striped disks with gcloud
    (create_storage_cluster :623 + resource.py:680 analog; gated)."""
    if shutil.which("gcloud") is None:
        raise RuntimeError(
            "gcloud CLI is required to provision a remotefs server")
    cluster = get_storage_cluster(store, cluster_id)
    name = f"shipyard-fs-{cluster_id}"
    disks = int(cluster["disk_count"])
    create_disk_args = []
    for i in range(disks):
        rc, _out, err = util.subprocess_capture([
            "gcloud", "compute", "disks", "create",
            f"{name}-data{i}",
            f"--size={cluster['disk_size_gb']}GB",
            f"--type={cluster['disk_type']}",
            f"--project={project}",
            *([f"--zone={zone}"] if zone else [])])
        if rc != 0:
            raise RuntimeError(f"disk create failed: {err.strip()}")
        create_disk_args += [
            "--disk",
            f"name={name}-data{i},device-name=data{i},mode=rw"]
    import tempfile
    with tempfile.NamedTemporaryFile(
            "w", suffix=".sh", delete=False) as fh:
        fh.write(generate_nfs_bootstrap_script(cluster))
        startup = fh.name
    rc, _out, err = util.subprocess_capture([
        "gcloud", "compute", "instances", "create", name,
        f"--machine-type={cluster['vm_size']}",
        f"--project={project}",
        *([f"--zone={zone}"] if zone else []),
        *([f"--network={network}"] if network else []),
        f"--metadata-from-file=startup-script={startup}",
        *create_disk_args])
    if rc != 0:
        raise RuntimeError(f"instance create failed: {err.strip()}")
    rc, out, err = util.subprocess_capture([
        "gcloud", "compute", "instances", "describe", name,
        f"--project={project}",
        *([f"--zone={zone}"] if zone else []),
        "--format=value(networkInterfaces[0].networkIP)"])
    store.upsert_entity(_NODES_TABLE, cluster_id, name, {
        "internal_ip": out.strip(), "state": "running"})
    store.merge_entity(_TABLE, "remotefs", cluster_id,
                       {"state": "provisioned"})


def register_server_node(store: StateStore, cluster_id: str,
                         node_name: str, internal_ip: str) -> None:
    """Record a server node (used by tests and external provisioning)."""
    store.upsert_entity(_NODES_TABLE, cluster_id, node_name, {
        "internal_ip": internal_ip, "state": "running"})
    store.merge_entity(_TABLE, "remotefs", cluster_id,
                       {"state": "provisioned"})

"""Data movement: ingress/egress between local paths, the object store,
and pool nodes.

Reference analog: convoy/data.py — ingress_data(:981) dispatching to
blobxfer (azure_storage) or scp/rsync (_singlenode_transfer :492 /
_multinode_transfer :567 with round-robin size-balanced file sharding
and optional byte-offset splits), plus task-level process_input_data
(:219) and process_output_data (:447).

TPU-native mapping:
  - azure_storage/blobxfer  -> the state store's object space (GCS in
    production) via put/get_object_stream — every transfer is chunked
    (STREAM_CHUNK_BYTES), so a multi-GB ingress never materializes a
    file in memory (the blobxfer streaming role, data.py:62);
  - shared-fs scp/rsync     -> same ssh-based sharded transfer,
    synthesized as command lines (testable dry-run; executed via
    subprocess when live), including byte-offset splits of large
    single files across nodes (reference _multinode_transfer
    data.py:567-739 + piece reassembly :850-875);
  - task input_data/output_data -> handled by the node agent around
    task execution using statestore keys (kind: statestore) or local
    paths.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import math
import os
import subprocess
import threading
from typing import Iterator, Optional

from batch_shipyard_tpu.config.settings import GlobalSettings
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError, StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


# --------------------------- object ingress ----------------------------

def _iter_files(source: str, include: Optional[list[str]] = None,
                exclude: Optional[list[str]] = None):
    if os.path.isfile(source):
        yield source, os.path.basename(source)
        return
    for root, _dirs, files in os.walk(source):
        for name in files:
            path = os.path.join(root, name)
            rel = os.path.relpath(path, source)
            if include and not any(
                    fnmatch.fnmatch(rel, pat) for pat in include):
                continue
            if exclude and any(
                    fnmatch.fnmatch(rel, pat) for pat in exclude):
                continue
            yield path, rel


def _file_chunks(path: str, begin: int = 0,
                 end: Optional[int] = None,
                 chunk_size: int = StateStore.STREAM_CHUNK_BYTES,
                 ) -> Iterator[bytes]:
    """Yield a file's bytes (optionally a [begin, end) range) in
    bounded chunks, so callers never hold a whole file in memory."""
    with open(path, "rb") as fh:
        if begin:
            fh.seek(begin)
        left = None if end is None else end - begin
        while left is None or left > 0:
            want = chunk_size if left is None else min(chunk_size, left)
            buf = fh.read(want)
            if not buf:
                return
            if left is not None:
                left -= len(buf)
            yield buf


def ingress_to_storage(store: StateStore, source: str, dest_prefix: str,
                       include: Optional[list[str]] = None,
                       exclude: Optional[list[str]] = None) -> int:
    """Upload local file(s) into the object space, streamed in
    STREAM_CHUNK_BYTES chunks. Returns file count."""
    count = 0
    for path, rel in _iter_files(source, include, exclude):
        key = f"{dest_prefix.rstrip('/')}/{rel}".lstrip("/")
        store.put_object_stream(key, _file_chunks(path))
        count += 1
    logger.info("ingressed %d files from %s to %s", count, source,
                dest_prefix)
    return count


def _prefix_children(store: StateStore, prefix: str) -> list[str]:
    """Keys strictly under prefix treated as a directory (never keys
    that merely share a string prefix, e.g. 'v10' under 'v1')."""
    base = prefix.rstrip("/")
    return [k for k in store.list_objects(base)
            if k == base or k.startswith(base + "/")]


def egress_from_storage(store: StateStore, prefix: str,
                        dest_dir: str) -> int:
    """Download an object-prefix tree into a local directory."""
    count = 0
    base = prefix.rstrip("/")
    for key in _prefix_children(store, base):
        rel = key[len(base):].lstrip("/")
        if not rel:
            rel = os.path.basename(base)
        path = os.path.join(dest_dir, rel)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as fh:
            for chunk in store.get_object_stream(key):
                fh.write(chunk)
        count += 1
    return count


def ingress_to_shared(spec: dict,
                      node_logins: list[tuple[str, str, int]],
                      ssh_username: str = "shipyard",
                      ssh_private_key: Optional[str] = None,
                      run: bool = True):
    """Direct-to-node ingress of one files spec onto a pool's shared
    filesystem (reference ingress_data dest=shared, data.py:981 →
    _multinode_transfer). destination.data_transfer options:
    method (scp|rsync), split_files_megabytes, destination.path (the
    mount point on the nodes), relative_destination_path.

    Returns the transfer plan when run=False, else the rc list."""
    source = spec.get("source", {})
    dest = spec.get("destination", {})
    dt = dest.get("data_transfer", {}) or {}
    dest_path = (dest.get("path") or
                 dest.get("shared_data_volume") or "/mnt/shared")
    rel = dest.get("relative_destination_path")
    if rel:
        dest_path = f"{dest_path.rstrip('/')}/{rel}"
    files = [(path, os.path.getsize(path)) for path, _rel in
             _iter_files(source.get("path", "."),
                         include=source.get("include"),
                         exclude=source.get("exclude"))]
    split_mb = dt.get("split_files_megabytes")
    plan = plan_multinode_transfer(
        files, node_logins, dest_path,
        method=dt.get("method", "scp"),
        ssh_username=ssh_username,
        ssh_private_key=ssh_private_key,
        split_bytes=(int(split_mb) * 1024 * 1024
                     if split_mb else None))
    if not run:
        return plan
    rcs = run_transfers(plan,
                        max_parallel=int(dt.get(
                            "max_parallel_transfers_per_node", 4)))
    return {"files": len(files), "rcs": rcs}


def ingress_data(store: StateStore, global_conf: GlobalSettings,
                 pool_id: Optional[str] = None,
                 node_logins: Optional[list[tuple[str, str, int]]] = None,
                 ssh_username: str = "shipyard",
                 ssh_private_key: Optional[str] = None) -> int:
    """Process global_resources.files ingress specs (data ingress verb,
    fleet.py:4496 analog). Storage-destined specs stream into the
    object space; shared-fs specs shard over the pool's nodes (pass
    ``node_logins`` = [(node_id, ip, port)] from the live pool)."""
    total = 0
    for spec in global_conf.files:
        source = spec.get("source", {})
        dest = spec.get("destination", {})
        if "storage" in dest or "prefix" in dest:
            prefix = (dest.get("storage", {}).get("prefix")
                      or dest.get("prefix", "ingress"))
            total += ingress_to_storage(
                store, source.get("path", "."), prefix,
                include=source.get("include"),
                exclude=source.get("exclude"))
        elif "shared_data_volume" in dest or "relative_destination_path" \
                in dest or "path" in dest:
            if not node_logins:
                raise ValueError(
                    "direct-to-node ingress requires a live pool's "
                    "node logins (data ingress with a pool config)")
            result = ingress_to_shared(
                spec, node_logins, ssh_username=ssh_username,
                ssh_private_key=ssh_private_key)
            if any(result["rcs"]):
                raise RuntimeError(
                    f"shared-fs ingress failed (rcs={result['rcs']})")
            total += result["files"]
    return total


# ------------------------ node (ssh) transfers -------------------------

# Suffix for byte-range pieces of a split file (reference
# _FILE_SPLIT_PREFIX '_shipyard-', data.py:65). Piece 0 keeps the
# final name; pieces 1..N-1 get '.{prefix}{n}' zero-padded so a shell
# glob reassembles them in order.
_SPLIT_PREFIX = "_shipyard-"


@dataclasses.dataclass(frozen=True)
class TransferPiece:
    """One byte range of a split file bound for one node. ``dst`` is
    the remote piece path; ``final_dst`` the file all sibling pieces
    reassemble into (on a SHARED destination filesystem — split
    ingress targets shared volumes, like the reference)."""
    src: str
    dst: str
    begin: int
    end: int
    final_dst: str


@dataclasses.dataclass(frozen=True)
class TransferCommand:
    node_id: str
    argv: tuple[str, ...]
    files: tuple[str, ...]
    total_bytes: int
    # Split-file byte ranges for this node; sent via `ssh 'cat > dst'`
    # with stdin fed from the local range (reference data.py:760-799).
    pieces: tuple[TransferPiece, ...] = ()
    # ssh invocation prefix for piece + reassembly commands.
    ssh_argv: tuple[str, ...] = ()


def plan_multinode_transfer(
        files: list[tuple[str, int]], nodes: list[tuple[str, str, int]],
        dest_path: str, method: str = "scp",
        ssh_username: str = "shipyard",
        ssh_private_key: Optional[str] = None,
        host_key_checking: str = "accept-new",
        split_bytes: Optional[int] = None,
        ) -> list[TransferCommand]:
    """Shard files across nodes round-robin balanced by size and emit
    per-node transfer command lines (reference _multinode_transfer
    data.py:567: largest-first onto least-loaded node).

    files: [(local_path, size)]; nodes: [(node_id, ip, port)].
    host_key_checking: OpenSSH StrictHostKeyChecking value. The
    'accept-new' default is trust-on-first-use; pass 'no' for
    throwaway/re-provisioned nodes whose IPs get recycled with fresh
    host keys (the reference's unconditional behavior).
    split_bytes: files larger than this are split into byte-range
    pieces distributed across nodes like independent files, so one
    huge file uses every node's NIC (reference split_files_megabytes,
    data.py:635-661). Requires method='scp' (the reference forces
    multinode_scp, :590) and a shared destination filesystem (pieces
    reassemble in place).
    """
    if method not in ("scp", "rsync"):
        raise ValueError(f"unknown transfer method {method!r}")
    if not nodes:
        raise ValueError("no nodes to transfer to")
    if split_bytes is not None and method != "scp":
        logger.warning("forcing transfer method to scp with split "
                       "(reference data.py:590)")
        method = "scp"
    loads: list[int] = [0] * len(nodes)
    shards: list[list[str]] = [[] for _ in nodes]
    piece_shards: list[list[TransferPiece]] = [[] for _ in nodes]

    def _least_loaded() -> int:
        return loads.index(min(loads))

    for path, size in sorted(files, key=lambda fs: -fs[1]):
        if split_bytes is not None and size > split_bytes:
            nsplits = int(math.ceil(size / split_bytes))
            lpad = int(math.log10(nsplits)) + 1
            final_dst = (f"{dest_path.rstrip('/')}/"
                         f"{os.path.basename(path)}")
            begin = 0
            n = 0
            while begin < size:
                end = min(begin + split_bytes, size)
                dst = (final_dst if n == 0 else
                       f"{final_dst}.{_SPLIT_PREFIX}{str(n).zfill(lpad)}")
                idx = _least_loaded()
                piece_shards[idx].append(TransferPiece(
                    src=path, dst=dst, begin=begin, end=end,
                    final_dst=final_dst))
                loads[idx] += end - begin
                begin = end
                n += 1
        else:
            idx = _least_loaded()
            shards[idx].append(path)
            loads[idx] += size
    out: list[TransferCommand] = []
    for (node_id, ip, port), shard, pieces, load in zip(
            nodes, shards, piece_shards, loads):
        if not shard and not pieces:
            continue
        key_args = (("-i", ssh_private_key) if ssh_private_key else ())
        hk = (("-o", f"StrictHostKeyChecking={host_key_checking}") +
              (("-o", "UserKnownHostsFile=/dev/null")
               if host_key_checking == "no" else ()))
        ssh_argv = ("ssh", "-T", "-x", *hk, *key_args,
                    "-p", str(port), f"{ssh_username}@{ip}")
        argv: tuple[str, ...] = ()
        if shard:
            if method == "scp":
                argv = ("scp", *hk,
                        "-P", str(port), *key_args, "-p", *shard,
                        f"{ssh_username}@{ip}:{dest_path}")
            else:
                ssh_cmd = " ".join((
                    "ssh", *hk,
                    *key_args, "-p", str(port)))
                argv = ("rsync", "-az", "-e", ssh_cmd, *shard,
                        f"{ssh_username}@{ip}:{dest_path}")
        out.append(TransferCommand(
            node_id=node_id, argv=argv, files=tuple(shard),
            total_bytes=load, pieces=tuple(pieces),
            ssh_argv=ssh_argv))
    return out


def _send_piece(ssh_argv: tuple[str, ...],
                piece: TransferPiece) -> int:
    """Stream one byte range to the node over `ssh 'cat > dst'`
    (reference _spawn_next_transfer stdin feed, data.py:787-798)."""
    proc = subprocess.Popen(
        [*ssh_argv, f'cat > "{piece.dst}"'], stdin=subprocess.PIPE)
    try:
        try:
            for buf in _file_chunks(piece.src, piece.begin, piece.end,
                                    chunk_size=1 << 20):
                proc.stdin.write(buf)
        finally:
            try:
                proc.stdin.close()
            except OSError:
                pass
    except BrokenPipeError:
        pass
    except OSError:
        # Local read failed (source truncated/removed mid-transfer):
        # the piece did NOT land whole — report failure and reap the
        # remote cat rather than leaving it half-fed.
        proc.kill()
        proc.wait()
        return 1
    return proc.wait()


def _join_pieces(ssh_argv: tuple[str, ...], final_dst: str) -> int:
    """Reassemble a split file on the (shared) destination filesystem
    (reference join, data.py:858-869): suffixed pieces glob-sort in
    order and append onto piece 0."""
    cmd = (f'cat "{final_dst}".{_SPLIT_PREFIX}* >> "{final_dst}" && '
           f'rm -f "{final_dst}".{_SPLIT_PREFIX}*')
    return subprocess.call([*ssh_argv, cmd])


def run_transfers(commands: list[TransferCommand],
                  max_parallel: int = 4) -> list[int]:
    """Execute planned transfers with bounded parallelism: whole-file
    scp/rsync batches first, then split pieces (each an ssh-cat with a
    ranged stdin feed), then one reassembly join per split file."""
    results: list[int] = []
    whole = [c for c in commands if c.argv]
    for batch in util.chunked(whole, max_parallel):
        procs = [util.subprocess_nowait(list(c.argv)) for c in batch]
        results.extend(util.subprocess_wait_all(procs))
    work = [(c, p) for c in commands for p in c.pieces]
    if not work:
        return results
    # Per-NODE parallelism (max_parallel is per node, matching the
    # reference's max_parallel_transfers_per_node): each node gets up
    # to max_parallel worker threads draining ITS piece list, so an
    # 8-node split drives all 8 NICs concurrently while total thread
    # count stays bounded by nodes x max_parallel.
    piece_rcs: list[int] = [1] * len(work)  # failure until proven sent
    by_node: dict[str, list[int]] = {}
    for k, (c, _p) in enumerate(work):
        by_node.setdefault(c.node_id, []).append(k)
    threads = []
    for node_id, indices in by_node.items():
        cursor = iter(indices)
        lock = threading.Lock()

        def _worker(cursor=cursor, lock=lock) -> None:
            while True:
                with lock:
                    k = next(cursor, None)
                if k is None:
                    return
                cmd, piece = work[k]
                try:
                    piece_rcs[k] = _send_piece(cmd.ssh_argv, piece)
                except Exception:
                    logger.exception("piece transfer failed: %s",
                                     piece.dst)
                    piece_rcs[k] = 1
        for _ in range(min(max_parallel, len(indices))):
            thread = threading.Thread(target=_worker, daemon=True)
            thread.start()
            threads.append(thread)
    for t in threads:
        t.join()
    results.extend(piece_rcs)
    # Reassemble each split file once, only if every piece landed.
    by_final: dict[str, list[int]] = {}
    joiner: dict[str, tuple[str, ...]] = {}
    for k, (c, p) in enumerate(work):
        by_final.setdefault(p.final_dst, []).append(piece_rcs[k])
        joiner[p.final_dst] = c.ssh_argv
    for final_dst, rcs in by_final.items():
        if any(rcs):
            logger.error("split pieces failed for %s; skipping join",
                         final_dst)
            results.append(1)
            continue
        results.append(_join_pieces(joiner[final_dst], final_dst))
    return results


# ---------------------- task-level input/output ------------------------

def stage_task_inputs(store: StateStore, input_data: list[dict],
                      task_dir: str) -> None:
    """Materialize input_data specs into the task dir before execution
    (process_input_data analog, data.py:219)."""
    for spec in input_data:
        kind = spec.get("kind", "statestore")
        if kind == "task_output":
            # Pull another task's uploaded outputs (the reference's
            # cargo/task_file_mover.py input_data:azure_batch path,
            # trivially storage-mediated here).
            key = names.task_output_key(
                spec["pool_id"], spec["job_id"], spec["task_id"],
                spec.get("filename", "outputs"))
            spec = {"kind": "statestore", "key": key,
                    "file_path": spec.get("file_path",
                                          spec["task_id"])}
            kind = "statestore"
        if kind == "statestore":
            key = spec["key"]
            rel = spec.get("file_path") or key.rsplit("/", 1)[-1]
            dest = os.path.join(task_dir, rel)
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            try:
                meta_exists = store.object_exists(key)
                if not meta_exists:
                    raise NotFoundError(key)
            except NotFoundError:
                # Prefix fetch: key may name a directory-like prefix.
                base = key.rstrip("/")
                sub = _prefix_children(store, base)
                if not sub:
                    raise
                for skey in sub:
                    srel = skey[len(base):].lstrip("/")
                    spath = os.path.join(dest, srel)
                    os.makedirs(os.path.dirname(spath) or ".",
                                exist_ok=True)
                    with open(spath, "wb") as fh:
                        for chunk in store.get_object_stream(skey):
                            fh.write(chunk)
                continue
            with open(dest, "wb") as fh:
                for chunk in store.get_object_stream(key):
                    fh.write(chunk)
        elif kind == "local":
            continue  # already on the node filesystem
        else:
            raise ValueError(f"unknown input_data kind {kind!r}")


def collect_task_outputs(store: StateStore, output_data: list[dict],
                         task_dir: str, pool_id: str, job_id: str,
                         task_id: str,
                         exclude_rels: Optional[set[str]] = None) -> int:
    """Upload output_data globs after execution (process_output_data
    analog, data.py:447). exclude_rels: relative paths staged as
    inputs, which must not be re-uploaded as outputs. Returns count."""
    count = 0
    exclude_rels = exclude_rels or set()
    for spec in output_data:
        pattern = spec.get("include")
        prefix = spec.get("prefix") or names.task_output_key(
            pool_id, job_id, task_id, "outputs")
        for root, _dirs, files in os.walk(task_dir):
            for name in files:
                path = os.path.join(root, name)
                rel = os.path.relpath(path, task_dir)
                if rel in ("stdout.txt", "stderr.txt"):
                    continue
                if rel in exclude_rels:
                    continue
                # fnmatch has no '**' semantics: treat missing/match-all
                # patterns explicitly, else match rel then basename.
                if pattern not in (None, "*", "**/*") and not (
                        fnmatch.fnmatch(rel, pattern) or
                        fnmatch.fnmatch(name, pattern)):
                    continue
                store.put_object_stream(f"{prefix}/{rel}",
                                        _file_chunks(path))
                count += 1
    return count


def staged_input_rels(store: StateStore,
                      input_data: list[dict]) -> set[str]:
    """Relative paths that stage_task_inputs materializes, for output
    exclusion."""
    rels: set[str] = set()
    for spec in input_data:
        kind = spec.get("kind", "statestore")
        if kind == "task_output":
            key = names.task_output_key(
                spec["pool_id"], spec["job_id"], spec["task_id"],
                spec.get("filename", "outputs"))
            rel = spec.get("file_path", spec["task_id"])
        elif kind == "statestore":
            key = spec["key"]
            rel = spec.get("file_path") or key.rsplit("/", 1)[-1]
        else:
            continue
        if store.object_exists(key):
            rels.add(rel)
        else:
            base = key.rstrip("/")
            for skey in _prefix_children(store, base):
                srel = skey[len(base):].lstrip("/")
                rels.add(os.path.join(rel, srel) if srel else rel)
    return rels

"""Device mesh construction for dp/fsdp/sp/tp parallelism.

This is the compute-side counterpart of the orchestrator's topology
oracle: recipes ask for logical parallelism axes and this module maps
them onto the physical device list (one pod slice's ICI torus, a
multi-slice DCN super-mesh, or the virtual CPU devices used in tests).

Axis convention (orderings chosen so the innermost, most
communication-hungry axis lands on adjacent ICI neighbors):

  dp    data parallel (gradient psum; outermost, cheapest)
  fsdp  fully-sharded data parallel (param all-gather + reduce-scatter)
  ep    expert parallel (MoE all-to-all dispatch)
  sp    sequence/context parallel (ring attention ppermute ring)
  tp    tensor parallel (activation all-reduce; innermost)

Reference analog: none — the reference has no compute path (SURVEY.md
section 2.3); this is the net-new TPU-native design space.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "ep", "sp", "tp")


def make_mesh(axis_sizes: dict[str, int],
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh with the canonical axis order.

    Missing axes get size 1; the product must equal the device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = tuple(int(axis_sizes.get(a, 1)) for a in AXES)
    total = math.prod(sizes)
    if total != len(devices):
        raise ValueError(
            f"axis sizes {dict(zip(AXES, sizes))} produce {total} "
            f"devices but {len(devices)} are available")
    grid = np.array(devices, dtype=object).reshape(sizes)
    return Mesh(grid, AXES)


def auto_axis_sizes(n_devices: int, tp: int = 1, sp: int = 1,
                    fsdp: int = 1, ep: int = 1) -> dict[str, int]:
    """Fill dp with whatever remains after the requested inner axes."""
    inner = tp * sp * fsdp * ep
    if n_devices % inner:
        raise ValueError(
            f"{n_devices} devices not divisible by "
            f"tp*sp*fsdp*ep={inner}")
    return {"dp": n_devices // inner, "fsdp": fsdp, "ep": ep,
            "sp": sp, "tp": tp}


def batch_spec() -> P:
    """Activation batch sharding: batch over dp+fsdp, sequence over
    sp."""
    return P(("dp", "fsdp"), "sp")


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))

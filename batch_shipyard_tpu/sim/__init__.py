"""Discrete-event fleet simulator (virtual clock, real policies).

Thousands of virtual nodes, synthetic/replayed arrival traces, and
the chaos inventory expressed as scenario schedules — priced by the
REAL goodput engine (goodput/accounting.py) and decided by the REAL
scheduling policies (sched/policy.py), so a simulated policy delta is
evidence about production decision code.

Wall-clock reads are banned in this package outside ``clock.py`` —
enforced by the ``sim-wall-clock`` analyzer rule (shipyard lint): a
single ``time.time()`` silently corrupts virtual-time determinism.
"""

"""TPU quota / capacity preflight (the `account quota` surface).

Reference analog: `shipyard account quota` / `account images`
(shipyard.py:1009-1078) — Azure Batch exposes a first-class quota API;
Cloud TPU splits the answer across two gcloud surfaces:

  - ``gcloud compute tpus accelerator-types list --zone=Z``: what the
    zone OFFERS (the `account images` analog — can this type even be
    requested here?);
  - ``gcloud alpha services quota list --service=tpu.googleapis.com``:
    what the PROJECT may consume (per-metric chip limits).

Both ride an injectable runner (tests pin captured payloads, the same
seam style as substrate/gcp_tpu.py). Everything here is advisory:
quota metric naming drifts across TPU generations, so the preflight
warns on what it can prove and stays silent on what it cannot — a
wrong "you will be blocked" is worse than none. The reactive half
(classifying the actual allocation failure) lives in
substrate/gcloud_errors.py; pool add calls preflight_pool first so the
operator hears about a doomed request before the substrate burns
minutes discovering it (VERDICT r4 next #4)."""

from __future__ import annotations

import json
from typing import Optional

from batch_shipyard_tpu.parallel import topology
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class TpuQuotaClient:
    """Thin gcloud wrapper; ``runner`` injects a fake
    (argv -> (rc, out, err)) for tests."""

    def __init__(self, project: str, runner=None) -> None:
        self.project = project
        self._runner = runner or util.subprocess_capture

    def _run(self, argv: list[str]):
        rc, out, err = self._runner(argv)
        if rc != 0:
            raise RuntimeError(
                f"{' '.join(argv[:4])}... failed ({rc}): "
                f"{str(err).strip()}")
        return out

    def accelerator_types(self, zone: str) -> list[str]:
        """Accelerator types offered in a zone (e.g. v5litepod-16)."""
        out = self._run([
            "gcloud", "compute", "tpus", "accelerator-types", "list",
            f"--zone={zone}", f"--project={self.project}",
            "--format=json"])
        rows = json.loads(out) if out.strip() else []
        types = []
        for row in rows:
            # Full resource name or bare type, depending on gcloud
            # version: projects/p/locations/z/acceleratorTypes/v4-8.
            t = (row.get("acceleratorType")
                 or row.get("type")
                 or row.get("name", "").rsplit("/", 1)[-1])
            if t:
                types.append(t)
        return sorted(set(types))

    def quota_limits(self, region: Optional[str] = None) -> list[dict]:
        """Project TPU quota metrics as
        {metric, region, limit, usage?} rows (limit -1 = unlimited).
        Parses the services-quota shape defensively: unknown layouts
        yield [] rather than raising."""
        out = self._run([
            "gcloud", "alpha", "services", "quota", "list",
            "--service=tpu.googleapis.com",
            f"--consumer=projects/{self.project}",
            "--format=json"])
        rows = json.loads(out) if out.strip() else []
        limits = []
        for svc in rows:
            metric = svc.get("metric", "")
            for cql in svc.get("consumerQuotaLimits", []) or []:
                for bucket in cql.get("quotaBuckets", []) or []:
                    dims = bucket.get("dimensions", {}) or {}
                    row_region = dims.get("region") or dims.get(
                        "zone") or ""
                    if region and row_region and \
                            not region.startswith(row_region) and \
                            row_region != region:
                        continue
                    limits.append({
                        "metric": metric,
                        "unit": cql.get("unit", ""),
                        "region": row_region,
                        "limit": int(bucket.get(
                            "effectiveLimit",
                            bucket.get("defaultLimit", -1))),
                    })
        return limits

    def zones_with_accelerator(self, accelerator_type: str,
                               zones: list[str]) -> list[str]:
        """Which of the candidate zones offer the type — the
        'try zone X' advisory attached to stockout errors."""
        offering = []
        for zone in zones:
            try:
                if accelerator_type in self.accelerator_types(zone):
                    offering.append(zone)
            except RuntimeError:
                continue
        return offering


def _zone_region(zone: str) -> str:
    """us-central1-a -> us-central1."""
    return zone.rsplit("-", 1)[0] if zone.count("-") >= 2 else zone


def quota_report(client: TpuQuotaClient, zone: str) -> dict:
    """The `account quota` verb's payload: what the zone offers and
    what the project may consume there."""
    report: dict = {"project": client.project, "zone": zone}
    try:
        report["accelerator_types"] = client.accelerator_types(zone)
    except RuntimeError as exc:
        report["accelerator_types_error"] = str(exc)
    try:
        report["quota_limits"] = client.quota_limits(
            region=_zone_region(zone))
    except RuntimeError as exc:
        report["quota_limits_error"] = str(exc)
    return report


def preflight_pool(pool, client: TpuQuotaClient,
                   zone: Optional[str] = None) -> list[str]:
    """Advisory warnings for a pool request: type not offered in the
    zone, or requested chips exceeding a matching quota limit.
    Never raises — preflight unavailability must not block pool add."""
    warnings: list[str] = []
    if pool.tpu is None:
        return warnings
    zone = zone or pool.zone
    if not zone:
        return warnings
    accel = pool.tpu.accelerator_type
    try:
        topo = topology.lookup(accel)
        chips = topo.num_chips * pool.tpu.num_slices
        gen_token = topo.generation.name
    except ValueError:
        return [f"accelerator type {accel!r} is not recognized; "
                f"skipping quota preflight"]
    try:
        offered = client.accelerator_types(zone)
        if accel not in offered:
            warnings.append(
                f"accelerator type {accel} is not offered in zone "
                f"{zone} (offered: {', '.join(offered) or 'none'})")
    except RuntimeError as exc:
        warnings.append(f"capacity preflight unavailable: {exc}")
        return warnings
    try:
        # Per metric, a region-matching bucket overrides the
        # dimensionless project default — only the effective one may
        # warn.
        by_metric: dict[str, dict] = {}
        for row in client.quota_limits(region=_zone_region(zone)):
            if gen_token not in row["metric"].lower():
                continue
            cur = by_metric.get(row["metric"])
            if cur is None or (row["region"] and not cur["region"]):
                by_metric[row["metric"]] = row
        for row in by_metric.values():
            if 0 <= row["limit"] < chips:
                warnings.append(
                    f"request needs {chips} {gen_token} chips but "
                    f"quota {row['metric']} in "
                    f"{row['region'] or 'project'} is {row['limit']} "
                    f"— the allocation will be rejected; request a "
                    f"quota increase or shrink the pool")
    except RuntimeError as exc:
        warnings.append(f"quota preflight unavailable: {exc}")
    return warnings


def stockout_advisory(client: TpuQuotaClient, accelerator_type: str,
                      failed_zone: str,
                      candidate_zones: list[str]) -> Optional[str]:
    """After a stockout, name zones that still offer the type
    (folded into the pool entity's allocation error record)."""
    try:
        zones = client.zones_with_accelerator(
            accelerator_type,
            [z for z in candidate_zones if z != failed_zone])
    except Exception:  # noqa: BLE001 - advisory only
        return None
    if not zones:
        return None
    return (f"zone {failed_zone} is out of {accelerator_type} "
            f"capacity; these zones offer the type: "
            f"{', '.join(zones)}")

"""Env-contract rules: the $SHIPYARD_* surface is a typed interface.

The task env contract (agent/task_runner.py module docstring) is how
every workload talks to the scheduler: goodput sinks, progress beats,
preempt requests, trace context, compile-cache dirs. It has three
legs that must agree:

  1. every variable a workload READS must be exported by the agent
     (or be a declared operator knob),
  2. every variable the agent EXPORTS must have a reader or be part
     of the documented task contract,
  3. every variable set by build_task_env must survive the docker
     boundary (docker run starts from an empty env: anything not
     forwarded with -e silently vanishes inside the container).

Before this PR the ~25-variable contract was maintained by hand —
and leg 3 had already drifted: SHIPYARD_TASK_DIR and
SHIPYARD_TASK_SLOT were set for subprocess tasks but missing from
the docker forward list (fixed in this PR).
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, call_name, const_str, rule)

_VAR_RE = re.compile(r"SHIPYARD_[A-Z0-9_]+")

# Operator/process-level knobs: read from the OPERATOR's environment
# (CLI, tools, kernel selection), never part of the task env the
# agent synthesizes — so "read but not exported" is their correct
# steady state. Adding a var here is a reviewed statement that it is
# operator surface, not task contract.
OPERATOR_ENV_VARS = frozenset({
    "SHIPYARD_CONFIGDIR",           # cli/main.py --configdir envvar
    "SHIPYARD_SECRETS_FILE",        # agent bootstrap secret source
    "SHIPYARD_RING_IMPL",           # kernel tier override (docs/31)
    "SHIPYARD_XLA_TUNING",          # XLA flag profile (parallel/tuning)
    "SHIPYARD_KERNEL_VALIDATION",   # tpu_checks marker path override
    "SHIPYARD_FORCE_TPU_PASSTHROUGH",  # docker device passthrough
})

_ENVISH_NAME_RE = re.compile(r"(^env$|_env$|^environ$|^env_)")


def _envish(node: ast.expr) -> bool:
    """Heuristic: is this expression an environment mapping? Matches
    os.environ and the agent's env/jp_env/jr_env dict idioms."""
    if isinstance(node, ast.Attribute):
        return node.attr == "environ"
    if isinstance(node, ast.Name):
        return bool(_ENVISH_NAME_RE.search(node.id))
    return False


def _env_const_table(ctx: AnalysisContext) -> dict[str, str]:
    """Bare-name -> value for every module-level *_ENV = "SHIPYARD_*"
    constant in the package (GOODPUT_FILE_ENV, TRACE_FILE_ENV, ...),
    so exports written through constants resolve."""
    table: dict[str, str] = {}
    for src in ctx.python_files:
        for node in ast.iter_child_nodes(src.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str) and \
                    node.value.value.startswith("SHIPYARD_"):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        table[target.id] = node.value.value
    return table


def _resolve_var(node: Optional[ast.expr],
                 consts: dict[str, str]) -> Optional[str]:
    if node is None:
        return None
    value = const_str(node)
    if value is not None:
        return value if value.startswith("SHIPYARD_") else None
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.Attribute):
        return consts.get(node.attr)
    return None


def _collect_reads(ctx: AnalysisContext, consts: dict[str, str],
                   ) -> dict[str, tuple[str, int]]:
    """var -> first (path, line) that reads it via os.environ.get /
    os.getenv / os.environ[...] / env.get(...)."""
    reads: dict[str, tuple[str, int]] = {}

    def note(var, src, line):
        if var:
            reads.setdefault(var, (src.rel, line))

    for src in ctx.python_files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name == "getenv" and node.args:
                    note(_resolve_var(node.args[0], consts), src,
                         node.lineno)
                elif name == "get" and node.args and \
                        isinstance(node.func, ast.Attribute) and \
                        _envish(node.func.value):
                    note(_resolve_var(node.args[0], consts), src,
                         node.lineno)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    _envish(node.value):
                note(_resolve_var(node.slice, consts), src,
                     node.lineno)
    return reads


def _collect_exports(ctx: AnalysisContext, consts: dict[str, str],
                     ) -> dict[str, tuple[str, int]]:
    """var -> first (path, line) that exports it into a task/process
    env: env["X"]=..., env.setdefault(X,...), env.update({...}),
    and dict literals with SHIPYARD_* keys inside *env* functions
    (build_task_env, TraceContext.env, the jp_env/jr_env blocks)."""
    exports: dict[str, tuple[str, int]] = {}

    def note(var, src, line):
        if var:
            exports.setdefault(var, (src.rel, line))

    for src in ctx.python_files:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Subscript) and \
                            _envish(target.value):
                        note(_resolve_var(target.slice, consts),
                             src, target.lineno)
                    # jp_env = {"SHIPYARD_X": ...} dict-literal
                    # exports.
                    if isinstance(target, ast.Name) and \
                            _envish(target) and \
                            isinstance(node.value, ast.Dict):
                        for key in node.value.keys:
                            note(_resolve_var(key, consts), src,
                                 node.lineno)
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name == "setdefault" and node.args and \
                        isinstance(node.func, ast.Attribute) and \
                        _envish(node.func.value):
                    note(_resolve_var(node.args[0], consts), src,
                         node.lineno)
                elif name == "update" and node.args and \
                        isinstance(node.func, ast.Attribute) and \
                        _envish(node.func.value) and \
                        isinstance(node.args[0], ast.Dict):
                    for key in node.args[0].keys:
                        note(_resolve_var(key, consts), src,
                             node.lineno)
        # Dict literals returned by env-building functions
        # (TraceContext.env, launcher env synthesis).
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)
                   and "env" in n.name]:
            for node in ast.walk(fn):
                if isinstance(node, ast.Dict):
                    for key in node.keys:
                        note(_resolve_var(key, consts), src,
                             node.lineno)
    return exports


def _documented_contract(ctx: AnalysisContext) -> frozenset:
    """Vars named in agent/task_runner.py's module docstring — the
    published task contract; exported-but-unread is legal for these
    (user task commands outside this repo are the readers)."""
    src = ctx.get("batch_shipyard_tpu/agent/task_runner.py")
    if src is None or not isinstance(src.tree, ast.Module):
        return frozenset()
    doc = ast.get_docstring(src.tree) or ""
    return frozenset(_VAR_RE.findall(doc))


@rule("env-read-unexported", family="env")
def check_read_unexported(ctx: AnalysisContext) -> list[Finding]:
    """A $SHIPYARD_* variable is read somewhere in the package but no
    agent code path ever exports it and it is not a declared operator
    knob (OPERATOR_ENV_VARS): the reader's branch is dead — it will
    see the default forever, silently.

    Provenance: the adaptive progress-beat throttle (PR 5 review)
    shipped reading $SHIPYARD_PROGRESS_DEADLINE before the agent
    export existed; only review caught that the throttle could starve
    a tight deadline. This rule makes the export a build error."""
    consts = _env_const_table(ctx)
    reads = _collect_reads(ctx, consts)
    exports = _collect_exports(ctx, consts)
    findings = []
    for var, (path, line) in sorted(reads.items()):
        if var in exports or var in OPERATOR_ENV_VARS:
            continue
        findings.append(Finding(
            rule="env-read-unexported", path=path, line=line,
            message=(f"${var} is read but never exported by "
                     f"node_agent/task_runner and is not a declared "
                     f"operator knob (rules_env.OPERATOR_ENV_VARS)")))
    return findings


@rule("env-export-unread", family="env")
def check_export_unread(ctx: AnalysisContext) -> list[Finding]:
    """A $SHIPYARD_* variable is exported into task envs but nothing
    in the package reads it and the task_runner docstring (the
    published contract user commands rely on) does not document it:
    dead surface, or — worse — a typo'd twin of the var the reader
    actually polls.

    Provenance: the 25+-variable contract audit this analyzer
    replaced; a renamed export with a stale reader is invisible to
    every runtime test because os.environ.get defaults paper over
    it."""
    consts = _env_const_table(ctx)
    reads = _collect_reads(ctx, consts)
    exports = _collect_exports(ctx, consts)
    documented = _documented_contract(ctx)
    findings = []
    for var, (path, line) in sorted(exports.items()):
        if var in reads or var in documented:
            continue
        findings.append(Finding(
            rule="env-export-unread", path=path, line=line,
            message=(f"${var} is exported but has no in-package "
                     f"reader and is not documented in the "
                     f"task_runner env contract")))
    return findings


@rule("env-docker-unmapped", family="env")
def check_docker_unmapped(ctx: AnalysisContext) -> list[Finding]:
    """A variable set by build_task_env (the core per-task identity
    contract) does not appear anywhere in synthesize_command's docker
    branch: `docker run` starts from an empty environment, so the
    variable exists for runtime=none tasks and silently vanishes for
    containerized ones — the contract forks by runtime.

    Provenance: found BY this rule in this PR — SHIPYARD_TASK_DIR
    and SHIPYARD_TASK_SLOT were missing from the docker forward
    list since the runner was written (fixed alongside)."""
    findings = []
    for src in ctx.python_files:
        build_fn = None
        synth_fn = None
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.FunctionDef)]:
            if fn.name == "build_task_env":
                build_fn = fn
            elif fn.name == "synthesize_command":
                synth_fn = fn
        if build_fn is None or synth_fn is None:
            continue
        # Docker-visible vars: every SHIPYARD_* token inside the
        # function's STRING CONSTANTS (the -e lists, tuple
        # constants, and "-e VAR=value" remap f-string parts).
        # AST constants only, docstring excluded — a variable named
        # in a comment or in prose must not count as forwarded.
        doc_const = None
        if synth_fn.body and isinstance(synth_fn.body[0], ast.Expr) \
                and isinstance(synth_fn.body[0].value, ast.Constant):
            doc_const = synth_fn.body[0].value
        forwarded: set[str] = set()
        for node in ast.walk(synth_fn):
            if isinstance(node, ast.Constant) and \
                    node is not doc_const and \
                    isinstance(node.value, str):
                forwarded.update(_VAR_RE.findall(node.value))
        for node in ast.walk(build_fn):
            if not isinstance(node, ast.Dict):
                continue
            for key in node.keys:
                var = const_str(key)
                if var and var.startswith("SHIPYARD_") and \
                        var not in forwarded:
                    findings.append(Finding(
                        rule="env-docker-unmapped", path=src.rel,
                        line=key.lineno,
                        message=(f"${var} is set by build_task_env "
                                 f"but never forwarded across the "
                                 f"docker boundary in "
                                 f"synthesize_command (-e or remap)")))
    return findings

"""Chaos injectors: apply one Injection through a framework seam.

Each injector exercises a failure mode the recovery layer claims to
survive, through surfaces the framework ALREADY exposes (no
monkey-patching):

  * ChaosStore wraps a StateStore and adds windowed latency or a
    bounded burst of op errors — the agent's worker/heartbeat/control
    loops must absorb them (requeue, retry next tick).
  * heartbeat blackout flips the agent's blackout attribute — node
    keeps running, looks partitioned.
  * task kill / task wedge signal a live task's process group —
    SIGKILL exercises the retry supervisor, SIGSTOP the progress
    watchdog (alive, zero progress: the TPU-wedge shape).
  * node preempt crash-kills the fakepod agent and revives it later —
    orphan reclaim + gang recovery territory.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

from batch_shipyard_tpu.chaos.plan import Injection
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Store methods subject to delay/error injection: the coordination hot
# path. Mutators and readers both — a flaky store is flaky everywhere.
_FAULTED_OPS = frozenset({
    "put_object", "get_object", "get_object_meta", "delete_object",
    "insert_entity", "upsert_entity", "merge_entity", "get_entity",
    "query_entities", "delete_entity", "insert_entities",
    "count_entities_by",
    "put_message", "put_messages", "get_messages", "delete_message",
    "update_message",
    # Stream ops fault at CALL time (before any chunk moves) so the
    # outage drill proves output uploads ride through too — the
    # resilient wrapper spools-and-retries put, opens-and-retries get.
    "put_object_stream", "get_object_stream",
})


class ChaosError(RuntimeError):
    """An injected state-store failure."""


class ChaosStore:
    """StateStore wrapper with windowed fault injection.

    Delegates everything to the wrapped store; ops named in
    _FAULTED_OPS first pass the fault gate: an active delay window
    sleeps them, an armed error budget raises ChaosError and
    decrements. Thread-safe — agents hit this from many threads."""

    def __init__(self, inner) -> None:
        self._inner = inner
        self._lock = threading.Lock()
        self._delay_until = 0.0
        self._delay_seconds = 0.0
        self._error_budget = 0
        self._outage_until = 0.0

    # -- fault control (called by the drill driver) --------------------

    def inject_delay(self, delay_seconds: float,
                     window_seconds: float) -> None:
        with self._lock:
            self._delay_seconds = delay_seconds
            self._delay_until = time.monotonic() + window_seconds

    def inject_errors(self, ops: int) -> None:
        with self._lock:
            self._error_budget += max(0, int(ops))

    def inject_outage(self, window_seconds: float) -> None:
        """Sustained outage: EVERY faulted op fails for the window —
        the store is down, not flaky. Only the resilient-store
        ride-through (state/resilient.py) survives this shape."""
        with self._lock:
            self._outage_until = time.monotonic() + window_seconds

    # -- delegation ----------------------------------------------------

    def _gate(self) -> None:
        with self._lock:
            outage = time.monotonic() < self._outage_until
            delay = (self._delay_seconds
                     if time.monotonic() < self._delay_until else 0.0)
            err = self._error_budget > 0 and not outage
            if err:
                self._error_budget -= 1
        if outage:
            raise ChaosError("chaos: store outage in progress")
        if err:
            raise ChaosError("chaos: injected store error")
        if delay:
            time.sleep(delay)

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in _FAULTED_OPS and callable(attr):
            def faulted(*args, **kwargs):
                self._gate()
                return attr(*args, **kwargs)
            return faulted
        return attr


def apply_injection(injection: Injection, substrate,
                    pool_id: str,
                    store: Optional[ChaosStore] = None) -> dict:
    """Apply one scheduled injection against a live fakepod pool.
    Returns an application record (what was actually hit) for the
    drill report. Node targets resolve by index modulo the live
    agent set, so a plan generated for N nodes applies to any pool."""
    record = {"kind": injection.kind, "at": injection.at,
              "node_index": injection.node_index, "applied": False}
    if injection.kind == "store_delay":
        if store is not None:
            store.inject_delay(injection.param("delay", 0.02),
                               injection.param("window", 1.0))
            record["applied"] = True
        return record
    if injection.kind == "store_error":
        if store is not None:
            store.inject_errors(injection.param("ops", 3))
            record["applied"] = True
        return record
    if injection.kind == "store_outage":
        if store is not None:
            store.inject_outage(injection.param("window", 2.0))
            record["applied"] = True
        return record

    agents = _live_agents(substrate, pool_id)
    if not agents:
        return record
    agent = agents[injection.node_index % len(agents)]
    record["node_id"] = agent.identity.node_id

    if injection.kind == "heartbeat_blackout":
        agent.heartbeat_blackout_until = (
            time.time() + injection.param("window", 2.0))
        record["applied"] = True
    elif injection.kind == "leader_partition":
        # Partition exactly the CURRENT sweep leader from the
        # control plane: heartbeats AND lease renewals stall while
        # its sweep loop keeps running — the shape the old
        # heartbeat-freshness election double-fired under. The
        # leader is resolved from the preempt-sweep epoch object
        # (the observable record of the live term); fall back to
        # the scheduled target when no term exists yet.
        from batch_shipyard_tpu.state import leases as state_leases
        from batch_shipyard_tpu.state import names as names_mod
        target = agent
        leader = state_leases.read_leader(
            agents[0].store,
            names_mod.leader_epoch_key(
                pool_id, state_leases.ROLE_PREEMPT_SWEEP))
        if leader is not None:
            for candidate in agents:
                if candidate.identity.node_id == \
                        leader.get("owner"):
                    target = candidate
                    break
        window = injection.param("window", 3.0)
        target.heartbeat_blackout_until = time.time() + window
        target.lease_blackout_until = time.time() + window
        record["node_id"] = target.identity.node_id
        record["window"] = window
        record["leader_epoch"] = (leader or {}).get("epoch")
        record["applied"] = True
    elif injection.kind == "agent_restart":
        # The agent PROCESS dies — in-flight completion paths
        # abandoned, no offline write, no lease release — while its
        # task subprocesses (own sessions) keep running; the revived
        # agent on the SAME work_dir must re-adopt them from the
        # slot ledgers.
        victim = _pick_live_proc(agents, preferred=agent)
        deadline = time.monotonic() + 2.0
        while victim is None and time.monotonic() < deadline:
            time.sleep(0.05)
            victim = _pick_live_proc(
                _live_agents(substrate, pool_id), preferred=None)
        if victim is None:
            return record
        node, _proc = victim
        record["node_id"] = node.identity.node_id
        context = substrate.crash_agent_hard(pool_id,
                                             node.identity.node_id)
        if context is not None:
            record["applied"] = True
            revive_after = injection.param("revive_after", 0.5)

            def _revive_restart():
                time.sleep(revive_after)
                substrate.revive_node(pool_id, context)

            threading.Thread(target=_revive_restart, daemon=True,
                             name="chaos-agent-restart").start()
    elif injection.kind in ("task_kill", "task_wedge"):
        # Prefer the target node's live task; fall back to any node
        # actually running one (the schedule is deterministic, the
        # scheduler's placement is not). A scheduled kill landing in
        # a claim gap waits briefly for a victim — the drill's point
        # is to exercise the kill paths, not to miss by 100ms.
        victim = _pick_live_proc(agents, preferred=agent)
        deadline = time.monotonic() + 2.0
        while victim is None and time.monotonic() < deadline:
            time.sleep(0.05)
            victim = _pick_live_proc(
                _live_agents(substrate, pool_id), preferred=None)
        if victim is None:
            return record
        node, proc = victim
        record["node_id"] = node.identity.node_id
        sig = (signal.SIGKILL if injection.kind == "task_kill"
               else signal.SIGSTOP)
        try:
            os.killpg(os.getpgid(proc.pid), sig)
            record["applied"] = True
            record["pid"] = proc.pid
        except (ProcessLookupError, PermissionError, OSError):
            pass
    elif injection.kind == "node_preempt":
        context = substrate.crash_node(pool_id,
                                       agent.identity.node_id)
        if context is not None:
            record["applied"] = True
            revive_after = injection.param("revive_after", 0.5)

            def _revive():
                time.sleep(revive_after)
                substrate.revive_node(pool_id, context)

            threading.Thread(target=_revive, daemon=True,
                             name="chaos-revive").start()
    elif injection.kind == "victim_ignore_notice":
        # Forcible-eviction shape: stamp the cooperative request on a
        # RUNNING task and stop there. The victim (an
        # --ignore-notice probe) squats past preempt_grace_seconds;
        # the sweep's escalation + the owning agent's enforcement —
        # the code under test — must do the killing, so unlike
        # node_preempt_notice there is NO injector follow-through.
        victim = _pick_live_proc(agents, preferred=agent)
        deadline = time.monotonic() + 2.0
        while victim is None and time.monotonic() < deadline:
            time.sleep(0.05)
            victim = _pick_live_proc(
                _live_agents(substrate, pool_id), preferred=None)
        if victim is None:
            return record
        node, _proc = victim
        live = list(node._live_procs.items())
        if not live:
            return record
        (job_id, task_id), _proc = live[0]
        record["node_id"] = node.identity.node_id
        record["job_id"] = job_id
        record["task_id"] = task_id
        from batch_shipyard_tpu.jobs import manager as jobs_mgr
        record["applied"] = bool(jobs_mgr.request_preemption(
            node.store, pool_id, job_id, task_id,
            reason="chaos victim_ignore_notice"))
    elif injection.kind == "host_loss_resize":
        # Permanent capacity loss: crash `count` nodes with NO
        # revive — the elastic gang must re-form smaller and
        # reshard-on-restore across the size change.
        count = max(1, int(injection.param("count", 1)))
        crashed = []
        for k in range(count):
            target = agents[(injection.node_index + k) % len(agents)]
            if _crash_host(substrate, pool_id, target):
                crashed.append(target.identity.node_id)
        record["crashed"] = crashed
        record["applied"] = bool(crashed)
    elif injection.kind == "pool_capacity_loss":
        # Total capacity loss: crash EVERY node of the pool, no
        # revive. Nothing inside the pool can finish the job —
        # recovery is the federation's cross-pool migration.
        crashed = []
        for target in agents:
            if _crash_host(substrate, pool_id, target):
                crashed.append(target.identity.node_id)
        record["crashed"] = crashed
        record["applied"] = bool(crashed)
    elif injection.kind == "node_preempt_notice":
        # Advance-notice preemption (the cloud spot shape): stamp a
        # cooperative preempt request on a RUNNING task, give the
        # workload the notice window to drain + commit + exit
        # EXIT_PREEMPTED, then follow through with the hard node
        # crash only if the task is still live — exactly what a
        # provider does when the notice lapses.
        victim = _pick_live_proc(agents, preferred=agent)
        deadline = time.monotonic() + 2.0
        while victim is None and time.monotonic() < deadline:
            time.sleep(0.05)
            victim = _pick_live_proc(
                _live_agents(substrate, pool_id), preferred=None)
        if victim is None:
            return record
        node, _proc = victim
        # Resolve the (job, task) of the victim's live proc.
        live = list(node._live_procs.items())
        if not live:
            return record
        (job_id, task_id), proc = live[0]
        record["node_id"] = node.identity.node_id
        record["job_id"] = job_id
        record["task_id"] = task_id
        from batch_shipyard_tpu.jobs import manager as jobs_mgr
        stamped = jobs_mgr.request_preemption(
            node.store, pool_id, job_id, task_id,
            reason="chaos node_preempt_notice")
        record["applied"] = bool(stamped)
        if not stamped:
            return record
        notice = injection.param("notice", 0.6)
        revive_after = injection.param("revive_after", 0.5)

        def _follow_through():
            # The notice is about THIS attempt's process vacating:
            # once the stamped proc exits (cooperative drain), the
            # kill is withheld — even if a requeued rerun has already
            # reclaimed the same (job, task) key on this node.
            deadline = time.monotonic() + notice
            while time.monotonic() < deadline:
                if node._live_procs.get((job_id, task_id)) is not \
                        proc:
                    return  # drained cooperatively: no hard kill
                time.sleep(0.05)
            if node._live_procs.get((job_id, task_id)) is not proc:
                return
            context = substrate.crash_node(pool_id,
                                           node.identity.node_id)
            if context is not None:
                time.sleep(revive_after)
                substrate.revive_node(pool_id, context)

        threading.Thread(target=_follow_through, daemon=True,
                         name="chaos-preempt-notice").start()
    return record


def _crash_host(substrate, pool_id: str, agent) -> bool:
    """Kill a whole fakepod 'host': its task processes die WITH it
    (a real host loss takes the workload down too — crash_node alone
    only stops the agent threads), then the agent is crashed with no
    offline write and no revival."""
    for proc in list(agent._live_procs.values()):
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass
    return substrate.crash_node(pool_id,
                                agent.identity.node_id) is not None


def _live_agents(substrate, pool_id: str) -> list:
    with substrate._lock:
        agents = list(substrate._agents.get(pool_id, {}).values())
    return sorted(agents, key=lambda a: a.identity.node_index)


def _pick_live_proc(agents: list, preferred=None):
    ordered = ([preferred] if preferred is not None else []) + [
        a for a in agents if a is not preferred]
    for agent in ordered:
        # The agent's worker threads mutate _live_procs without a
        # lock; retry the snapshot instead of letting a concurrent
        # pop turn a scheduled injection into a silent skip.
        procs = []
        for _ in range(3):
            try:
                procs = list(agent._live_procs.items())
                break
            except RuntimeError:
                continue
        if procs:
            return agent, procs[0][1]
    return None

"""JAX version compatibility shims.

The package is written against the current JAX surface; this module
absorbs the differences so every other file imports ONE spelling:

  - ``shard_map``: new JAX exports it as ``jax.shard_map`` with a
    ``check_vma`` kwarg; the 0.4.x line only has
    ``jax.experimental.shard_map.shard_map`` with the older
    ``check_rep`` spelling. The wrapper translates the kwarg so call
    sites stay written against the new API.
  - ``pltpu.force_tpu_interpret_mode``: newer JAX ships a context
    manager that forces Pallas TPU kernels through the interpreter
    (the CPU CI path). Where absent, install a polyfill that patches
    ``pl.pallas_call`` to inject ``interpret=True`` for calls TRACED
    inside the context. Functions jitted (and cached) outside the
    context keep their compiled form — matching how every test here
    uses it (fresh closures traced under the context).

Import sites: ops/collectives.py, ops/ring_attention.py,
ops/ring_collectives.py, parallel/pipeline.py, workloads/p2p_bench.py
and the shard_map-using tests all route through this module.
"""

from __future__ import annotations

import contextlib
import functools
import inspect

import jax

try:  # new JAX (>= 0.6): top-level export
    from jax import shard_map as _shard_map
except ImportError:  # 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map


@contextlib.contextmanager
def threefry_partitionable():
    """Scoped jax_threefry_partitionable=True.

    On JAX versions that default this False, sharded-output RNG under
    jit draws DIFFERENT values per sharding — a dp-only and a tp/sp
    parameter init from the same seed disagree, exactly what the
    parallelism-equivalence tests assert against. The partitionable
    implementation is sharding-invariant but is a DIFFERENT stream
    than the legacy one, so flipping it globally would change every
    existing sampling/quantization draw; scope it to the sharded init
    sites instead (parallel/train.py)."""
    try:
        prev = jax.config.jax_threefry_partitionable
    except AttributeError:  # pragma: no cover - option removed
        yield
        return
    jax.config.update("jax_threefry_partitionable", True)
    try:
        yield
    finally:
        jax.config.update("jax_threefry_partitionable", prev)

_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map).parameters)


@functools.wraps(_shard_map)
def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    """shard_map with the new-API kwarg surface on any JAX.

    ``check_vma`` (the current name for replication/varying-manual-axes
    checking) is forwarded as ``check_rep`` on JAX versions that
    predate the rename.
    """
    if "check_vma" in kwargs and "check_vma" not in _SHARD_MAP_PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def _install_force_tpu_interpret_mode() -> None:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if hasattr(pltpu, "force_tpu_interpret_mode"):
        return

    @contextlib.contextmanager
    def force_tpu_interpret_mode():
        original = pl.pallas_call

        @functools.wraps(original)
        def interpreted(*args, **kwargs):
            kwargs["interpret"] = True
            return original(*args, **kwargs)

        pl.pallas_call = interpreted
        try:
            yield
        finally:
            pl.pallas_call = original

    pltpu.force_tpu_interpret_mode = force_tpu_interpret_mode


_install_force_tpu_interpret_mode()

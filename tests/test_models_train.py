"""Model + distributed train-step tests on the virtual 8-device CPU
mesh: dp/fsdp/sp/tp transformer training and dp ResNet training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import resnet as resnet_mod
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import sharding as shard_rules
from batch_shipyard_tpu.parallel import train as train_mod


def small_config(**kw):
    defaults = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    d_head=16, d_ff=128, max_seq_len=128,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    defaults.update(kw)
    return defaults


def test_transformer_forward_shapes():
    config = tfm.TransformerConfig(**small_config())
    model = tfm.TransformerLM(config)
    tokens = jnp.zeros((2, 32), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 32, 256)


def test_transformer_causality():
    """Changing a future token must not affect earlier logits."""
    config = tfm.TransformerConfig(**small_config())
    model = tfm.TransformerLM(config)
    tokens = jnp.ones((1, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    base = model.apply({"params": params}, tokens)
    perturbed_tokens = tokens.at[0, 10].set(5)
    perturbed = model.apply({"params": params}, perturbed_tokens)
    np.testing.assert_allclose(base[0, :10], perturbed[0, :10],
                               atol=1e-5)
    assert not np.allclose(base[0, 10:], perturbed[0, 10:])


def test_param_sharding_rules():
    config = tfm.TransformerConfig(**small_config())
    model = tfm.TransformerLM(config)
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    params = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), tokens)["params"])
    specs = shard_rules.transformer_param_specs(params)
    flat = {shard_rules._path_str(p): s for p, s in
            jax.tree_util.tree_flatten_with_path(specs)[0]}
    assert str(flat["layer_0/attn/q_proj/kernel"]) == (
        "PartitionSpec('fsdp', 'tp')")
    assert str(flat["layer_0/attn/o_proj/kernel"]) == (
        "PartitionSpec('tp', 'fsdp')")
    assert str(flat["embed/embedding"]) == "PartitionSpec('tp', 'fsdp')"
    assert str(flat["final_norm/scale"]) == "PartitionSpec()"


@pytest.mark.parametrize("axes", [
    {"dp": 8},
    {"dp": 2, "tp": 4},
    {"dp": 2, "sp": 2, "tp": 2},
    {"fsdp": 4, "tp": 2},
])
@pytest.mark.slow
def test_transformer_train_step_parallelisms(axes):
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(
        8, tp=axes.get("tp", 1), sp=axes.get("sp", 1),
        fsdp=axes.get("fsdp", 1)))
    config = train_mod.make_transformer_config(
        mesh, **small_config())
    harness = train_mod.build_transformer_train(
        mesh, config, batch_size=8, seq_len=64, seed=0)
    rng = np.random.RandomState(0)
    batch = {
        "tokens": jnp.asarray(rng.randint(0, 256, (8, 64)), jnp.int32),
        "targets": jnp.asarray(rng.randint(0, 256, (8, 64)),
                               jnp.int32),
    }
    params, opt_state, metrics = harness.step(
        harness.params, harness.opt_state, batch)
    first_loss = float(metrics["loss"])
    assert np.isfinite(first_loss)
    for _ in range(3):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
    assert float(metrics["loss"]) < first_loss  # it learns


def test_parallelism_configs_agree():
    """dp-only and dp+tp+sp training must produce the same loss
    trajectory (same global batch, same init seed)."""
    rng = np.random.RandomState(1)
    batch = {
        "tokens": jnp.asarray(rng.randint(0, 256, (8, 64)), jnp.int32),
        "targets": jnp.asarray(rng.randint(0, 256, (8, 64)),
                               jnp.int32),
    }
    losses = {}
    for name, axes in (("dp", {}), ("tp_sp", {"tp": 2, "sp": 2})):
        mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(
            8, tp=axes.get("tp", 1), sp=axes.get("sp", 1)))
        config = train_mod.make_transformer_config(
            mesh, **small_config())
        harness = train_mod.build_transformer_train(
            mesh, config, batch_size=8, seq_len=64, seed=0)
        params, opt_state = harness.params, harness.opt_state
        run = []
        for _ in range(3):
            params, opt_state, metrics = harness.step(params, opt_state,
                                                      batch)
            run.append(float(metrics["loss"]))
        losses[name] = run
    np.testing.assert_allclose(losses["dp"], losses["tp_sp"],
                               rtol=2e-3)


@pytest.mark.slow
def test_resnet_forward_and_train_step():
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    config = resnet_mod.ResNetConfig(num_classes=10,
                                     stage_sizes=(1, 1, 1, 1),
                                     width=16, dtype=jnp.float32)
    harness = train_mod.build_resnet_train(
        mesh, config, batch_size=8, image_size=32)
    rng = np.random.RandomState(0)
    batch = {
        "images": jnp.asarray(rng.randn(8, 32, 32, 3), jnp.float32),
        "labels": jnp.asarray(rng.randint(0, 10, (8,)), jnp.int32),
    }
    params, opt_state, metrics = harness.step(
        harness.params, harness.opt_state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_mesh_validation():
    with pytest.raises(ValueError):
        mesh_mod.make_mesh({"dp": 3})  # 3 != 8 devices
    with pytest.raises(ValueError):
        mesh_mod.auto_axis_sizes(8, tp=3)


def test_moe_routing_mass_conservation():
    from batch_shipyard_tpu.models import moe as moe_mod
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(64, 8), jnp.float32)
    dispatch, combine, aux = moe_mod.top1_routing(logits, capacity=16)
    # Each token dispatched to at most one (expert, slot).
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert set(np.unique(per_token)) <= {0.0, 1.0}
    # No expert slot double-booked.
    per_slot = np.asarray(jnp.sum(dispatch, axis=0))
    assert per_slot.max() <= 1.0 + 1e-6
    assert float(aux) > 0.0


def test_moe_capacity_drops_overflow():
    from batch_shipyard_tpu.models import moe as moe_mod
    # All tokens prefer expert 0; capacity 4 keeps only 4.
    logits = jnp.tile(jnp.asarray([[10.0] + [0.0] * 7]), (32, 1))
    dispatch, _combine, _aux = moe_mod.top1_routing(logits, capacity=4)
    assert float(jnp.sum(dispatch)) == 4.0


@pytest.mark.slow
def test_moe_transformer_trains_with_ep():
    from batch_shipyard_tpu.models.moe import MoEConfig
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8, ep=4))
    config = train_mod.make_transformer_config(
        mesh, moe=MoEConfig(num_experts=8, d_model=64, d_ff=128,
                            dtype=jnp.float32,
                            param_dtype=jnp.float32),
        **small_config())
    harness = train_mod.build_transformer_train(
        mesh, config, batch_size=4, seq_len=64, seed=0)
    # Expert params actually sharded over ep.
    flat = {shard_rules._path_str(p): s.sharding.spec for p, s in
            jax.tree_util.tree_flatten_with_path(harness.params)[0]
            if "moe/w_gate" in shard_rules._path_str(p)}
    assert any("ep" in str(spec) for spec in flat.values()), flat
    rng = np.random.RandomState(0)
    batch = {
        "tokens": jnp.asarray(rng.randint(0, 256, (4, 64)), jnp.int32),
        "targets": jnp.asarray(rng.randint(0, 256, (4, 64)),
                               jnp.int32)}
    params, opt_state = harness.params, harness.opt_state
    first = None
    for _ in range(4):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
        if first is None:
            first = float(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < first


def test_moe_top2_routing_properties():
    from batch_shipyard_tpu.models import moe as moe_mod
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(64, 8), jnp.float32)
    dispatch, combine, aux = moe_mod.topk_routing(logits, capacity=64,
                                                  num_selected=2)
    # Each token lands in at most 2 slots; combine weights sum <= 1.
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert per_token.max() <= 2.0 + 1e-6
    weights = np.asarray(jnp.sum(combine, axis=(1, 2)))
    assert weights.max() <= 1.0 + 1e-5
    # With ample capacity every token gets both choices.
    assert per_token.min() == 2.0
    # No slot double-booked.
    assert np.asarray(jnp.sum(dispatch, axis=0)).max() <= 1.0 + 1e-6
    assert float(aux) > 0


def test_moe_top2_capacity_priority():
    from batch_shipyard_tpu.models import moe as moe_mod
    # Everyone's top-1 is expert 0, top-2 is expert 1; capacity 4.
    logits = jnp.tile(jnp.asarray([[5.0, 3.0] + [-5.0] * 6]), (16, 1))
    dispatch, _c, _a = moe_mod.topk_routing(logits, capacity=4,
                                            num_selected=2)
    per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
    assert per_expert[0] == 4.0  # first choices filled to capacity
    assert per_expert[1] == 4.0  # second choices too
    assert per_expert[2:].sum() == 0


@pytest.mark.slow
def test_moe_top2_transformer_trains():
    from batch_shipyard_tpu.models.moe import MoEConfig
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8, ep=2))
    config = train_mod.make_transformer_config(
        mesh, moe=MoEConfig(num_experts=4, d_model=64, d_ff=128,
                            num_selected=2, dtype=jnp.float32,
                            param_dtype=jnp.float32),
        **small_config())
    harness = train_mod.build_transformer_train(
        mesh, config, batch_size=8, seq_len=64, seed=0)
    rng = np.random.RandomState(0)
    batch = {
        "tokens": jnp.asarray(rng.randint(0, 256, (8, 64)), jnp.int32),
        "targets": jnp.asarray(rng.randint(0, 256, (8, 64)),
                               jnp.int32)}
    params, opt_state = harness.params, harness.opt_state
    first = None
    for _ in range(4):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first


def test_moe_expert_choice_routing_properties():
    from batch_shipyard_tpu.models import moe as moe_mod
    rng = np.random.RandomState(2)
    logits = jnp.asarray(rng.randn(64, 8), jnp.float32)
    dispatch, combine, aux = moe_mod.expert_choice_routing(
        logits, capacity=6)
    assert dispatch.shape == (64, 8, 6)
    # Perfect balance by construction: every expert takes exactly C
    # tokens, each buffer slot used exactly once.
    per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
    np.testing.assert_allclose(per_expert, 6.0)
    per_slot = np.asarray(jnp.sum(dispatch, axis=0))
    np.testing.assert_allclose(per_slot, 1.0)
    # Combine weights are the softmax affinities of selected pairs.
    probs = np.asarray(jax.nn.softmax(np.asarray(logits), axis=-1))
    sel = np.asarray(jnp.sum(combine, axis=2))   # [G, E]
    mask = np.asarray(jnp.sum(dispatch, axis=2))
    np.testing.assert_allclose(sel, probs * mask, atol=1e-6)
    # No auxiliary loss needed.
    assert float(aux) == 0.0


def test_moe_expert_choice_mlp_trains():
    from batch_shipyard_tpu.models.moe import MoEConfig, MoEMLP
    cfg = MoEConfig(num_experts=4, d_model=32, d_ff=64,
                    dtype=jnp.float32, param_dtype=jnp.float32,
                    routing="expert_choice")
    layer = MoEMLP(cfg)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 16, 32), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)["params"]

    def loss_fn(p):
        out, aux = layer.apply({"params": p}, x)
        return jnp.sum(out ** 2) + aux

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(jnp.isfinite(g).all() for g in leaves)
    # The routed experts actually receive gradient signal.
    assert float(jnp.abs(grads["w_gate"]).sum()) > 0

"""Wiring rules: surfaces that exist must be reachable and honest.

A fleet action with no CLI call site is dead surface nobody can
reach; a hardcoded help string listing chaos kinds goes stale the
day a kind is added; a train workload that skips the compile-cache
hooks silently pays a cold XLA compile on every node restart. These
were all hand-listed checks in tests/test_names_consistency.py —
now registered rules that cover the whole surface automatically.
"""

from __future__ import annotations

import ast

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, call_name, rule)

_CLI_PATH = "batch_shipyard_tpu/cli/main.py"
_FLEET_PATH = "batch_shipyard_tpu/fleet.py"


@rule("wiring-cli-action-unwired", family="wiring")
def check_cli_action_unwired(ctx: AnalysisContext) -> list[Finding]:
    """Every ``action_*`` function in fleet.py must have a call site
    in cli/main.py — an unwired action is surface nobody can reach
    from the shipyard CLI (the reference's fleet.py/shipyard.py
    pairing, where every action has exactly one CLI verb).

    Provenance: the PR 7 trace/profile wiring check
    (test_names_consistency), widened from the trace actions to the
    whole action surface."""
    fleet_src = ctx.get(_FLEET_PATH)
    cli_src = ctx.get(_CLI_PATH)
    if fleet_src is None or cli_src is None:
        return []
    actions = {
        (node.name, node.lineno)
        for node in ast.walk(fleet_src.tree)
        if isinstance(node, ast.FunctionDef)
        and node.name.startswith("action_")}
    called = {
        node.func.attr for node in ast.walk(cli_src.tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "fleet"}
    findings = []
    for name, line in sorted(actions):
        if name not in called:
            findings.append(Finding(
                rule="wiring-cli-action-unwired", path=_FLEET_PATH,
                line=line,
                message=(f"fleet.{name} has no cli/main.py call "
                         f"site; dead surface")))
    return findings


@rule("wiring-kinds-help-stale", family="wiring")
def check_kinds_help_stale(ctx: AnalysisContext) -> list[Finding]:
    """The chaos ``--kinds`` help must be BUILT from
    chaos.plan.INJECTION_KINDS (a ``.join(INJECTION_KINDS)``), not
    hardcoded: a hand-written list goes stale silently the day a
    kind is added, and operators pass kinds they read in --help.

    Provenance: the PR 10 node_preempt_notice review
    (test_names_consistency), where the help was derived precisely
    so this could never drift."""
    cli_src = ctx.get(_CLI_PATH)
    if cli_src is None:
        return []
    joins = 0
    kinds_options = 0
    for node in ast.walk(cli_src.tree):
        if isinstance(node, ast.Call):
            if call_name(node) == "join" and node.args and \
                    isinstance(node.args[0], ast.Attribute) and \
                    node.args[0].attr == "INJECTION_KINDS":
                joins += 1
            if call_name(node) == "option" and any(
                    isinstance(a, ast.Constant)
                    and a.value == "--kinds" for a in node.args):
                kinds_options += 1
    # One derived join per --kinds option: a NEW option with a
    # hand-written help must not hide behind the existing derived
    # ones.
    if joins < kinds_options:
        return [Finding(
            rule="wiring-kinds-help-stale", path=_CLI_PATH, line=1,
            message=(f"{kinds_options} --kinds option(s) but only "
                     f"{joins} help string(s) derive from "
                     f"chaos.plan.INJECTION_KINDS via "
                     f"', '.join(INJECTION_KINDS)"))]
    return []


@rule("wiring-compile-cache-optout", family="wiring")
def check_compile_cache_optout(ctx: AnalysisContext) -> list[Finding]:
    """Every workload that builds a parallel.train harness must call
    compilecache.enable_from_args AND add_compile_cache_args: a
    workload that silently opts out pays a cold XLA compile on every
    node and every restart — exactly the compile badput the
    warm-start pipeline (PR 4) removes.

    Provenance: migrated verbatim from test_names_consistency's
    train-workload scan."""
    findings = []
    for src in ctx.python_files:
        if not (src.rel.startswith("batch_shipyard_tpu/workloads/"
                                   "train_")
                and src.rel.endswith(".py")):
            continue
        uses_train = any(
            isinstance(node, ast.ImportFrom) and
            node.module == "batch_shipyard_tpu.parallel" and
            any(alias.name == "train" for alias in node.names)
            for node in ast.walk(src.tree))
        if not uses_train:
            continue
        calls = {
            call_name(node)
            for node in ast.walk(src.tree)
            if isinstance(node, ast.Call)}
        for required in ("enable_from_args", "add_compile_cache_args"):
            if required not in calls:
                findings.append(Finding(
                    rule="wiring-compile-cache-optout", path=src.rel,
                    line=1,
                    message=(f"parallel.train workload never calls "
                             f"compilecache.{required}; it silently "
                             f"opts out of the persistent compile "
                             f"cache")))
    return findings

"""Task termination relay + task file listing + import-walk lint."""

import importlib
import pkgutil
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


@pytest.fixture()
def env():
    conf = {"pool_specification": {
        "id": "tt", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30}}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    yield store, substrate, pool
    substrate.stop_all()


def test_terminate_running_task(env):
    store, substrate, pool = env
    jobs = settings_mod.job_settings_list({"job_specifications": [{
        "id": "jt", "tasks": [{"command": "sleep 120"}]}]})
    jobs_mgr.add_jobs(store, pool, jobs)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        task = jobs_mgr.get_task(store, "tt", "jt", "task-00000")
        if task.get("state") == "running":
            break
        time.sleep(0.1)
    assert task.get("state") == "running"
    jobs_mgr.terminate_task(store, "tt", "jt", "task-00000",
                            wait=True, timeout=60)
    task = jobs_mgr.get_task(store, "tt", "jt", "task-00000")
    assert task["state"] == "failed"
    assert task["exit_code"] != 0


def test_terminate_pending_task(env):
    store, substrate, pool = env
    from batch_shipyard_tpu.state import names
    store.insert_entity(names.TABLE_JOBS, "tt", "jp2",
                        {"state": "disabled", "spec": {}})
    store.insert_entity(
        names.TABLE_TASKS, names.task_pk("tt", "jp2"), "t0",
        {"state": "pending", "retries": 0,
         "spec": {"command": "echo x", "runtime": "none"}})
    jobs_mgr.terminate_task(store, "tt", "jp2", "t0")
    task = jobs_mgr.get_task(store, "tt", "jp2", "t0")
    assert task["state"] == "failed"


def test_list_task_files(env):
    store, substrate, pool = env
    jobs = settings_mod.job_settings_list({"job_specifications": [{
        "id": "jf",
        "tasks": [{"command": "echo data > out.bin",
                   "output_data": [{"include": "*.bin"}]}]}]})
    jobs_mgr.add_jobs(store, pool, jobs)
    jobs_mgr.wait_for_tasks(store, "tt", "jf", timeout=30)
    files = jobs_mgr.list_task_files(store, "tt", "jf", "task-00000")
    assert "stdout.txt" in files
    assert "outputs/out.bin" in files


def test_all_modules_import():
    """Import-walk lint: every module in the package imports cleanly
    (the flake8-F821-class error net; reference CI was lint-only)."""
    import batch_shipyard_tpu
    failures = []
    for info in pkgutil.walk_packages(
            batch_shipyard_tpu.__path__,
            prefix="batch_shipyard_tpu."):
        try:
            importlib.import_module(info.name)
        except Exception as exc:  # noqa: BLE001
            failures.append((info.name, repr(exc)))
    assert not failures, failures


def test_large_output_uploaded_in_full(env):
    """A task writing >4MB of stdout is uploaded whole (streamed) —
    the round-1 silent 4MB truncation is gone."""
    store, substrate, pool = env
    from batch_shipyard_tpu.state import names
    jobs = settings_mod.job_settings_list({"job_specifications": [{
        "id": "jbig",
        "tasks": [{"command":
                   "python3 -c \"import sys; "
                   "sys.stdout.write('x' * (6 * 1024 * 1024))\""}]}]})
    jobs_mgr.add_jobs(store, pool, jobs)
    jobs_mgr.wait_for_tasks(store, "tt", "jbig", timeout=60)
    key = names.task_output_key("tt", "jbig", "task-00000",
                                "stdout.txt")
    assert store.get_object_meta(key).size == 6 * 1024 * 1024


def test_output_cap_keeps_head_tail_with_marker(tmp_path):
    """With a configured cap, uploads keep head+tail around an
    explicit truncation marker (never a silent cut)."""
    import os

    from batch_shipyard_tpu.agent import node_agent as na
    from batch_shipyard_tpu.agent import task_runner

    store = MemoryStateStore()
    agent = na.NodeAgent.__new__(na.NodeAgent)
    agent.store = store
    agent.identity = type("I", (), {"pool_id": "p", "node_id": "n"})()
    agent.output_upload_cap_bytes = 1024
    task_dir = tmp_path / "t"
    task_dir.mkdir()
    payload = b"H" * 5000 + b"T" * 5000
    (task_dir / "stdout.txt").write_bytes(payload)
    execution = task_runner.TaskExecution.__new__(
        task_runner.TaskExecution)
    execution.task_dir = str(task_dir)
    agent._upload_outputs("j", "t0", execution)
    from batch_shipyard_tpu.state import names
    data = store.get_object(
        names.task_output_key("p", "j", "t0", "stdout.txt"))
    assert data.startswith(b"H" * 512)
    assert data.endswith(b"T" * 512)
    assert b"output truncated, 10000 bytes total, cap 1024" in data


def test_kata_runtime_in_docker_argv():
    """container_runtime_default: kata_containers plumbs end-to-end
    into `docker run --runtime kata-runtime` (reference
    shipyard_nodeprep.sh:1105 kata install + :1133 default-runtime)."""
    from batch_shipyard_tpu.agent import task_runner
    from batch_shipyard_tpu.config import settings as sm
    from batch_shipyard_tpu.jobs.manager import _task_spec
    execution = task_runner.TaskExecution(
        pool_id="p", job_id="j", task_id="t", node_id="n",
        node_index=0, command="echo x", runtime="docker",
        image="busybox", container_runtime="kata_containers",
        env={}, task_dir="/tmp/kata-test")
    argv = task_runner.synthesize_command(execution)
    k = argv.index("--runtime")
    assert argv[k + 1] == "kata-runtime"
    # Default runc: no --runtime flag injected.
    plain = task_runner.TaskExecution(
        pool_id="p", job_id="j", task_id="t", node_id="n",
        node_index=0, command="echo x", runtime="docker",
        image="busybox", env={}, task_dir="/tmp/kata-test")
    assert "--runtime" not in task_runner.synthesize_command(plain)
    # Pool-level default reaches the task spec.
    pool = sm.pool_settings({"pool_specification": {
        "id": "kp", "substrate": "fake",
        "container_runtime_default": "kata_containers",
        "tpu": {"accelerator_type": "v5litepod-4"}}})
    jobs = sm.job_settings_list({"job_specifications": [{
        "id": "kj", "tasks": [{"command": "echo"}]}]})
    task = sm.task_settings({"command": "echo"}, jobs[0], pool)
    spec = _task_spec(task, jobs[0], pool)
    assert spec["container_runtime"] == "kata_containers"


def test_allow_run_on_missing_image_gate():
    """A docker task whose image is NOT in the pool's global
    resources fails cleanly under the strict default and runs when
    the job opts in (reference batch.py:4747)."""
    import json as json_mod
    from batch_shipyard_tpu.config import settings as sm
    from batch_shipyard_tpu.jobs import manager as jm
    from batch_shipyard_tpu.pool import manager as pm
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    conf = {"pool_specification": {
        "id": "imgpool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30}}
    pool = sm.pool_settings(conf)
    pm.create_pool(store, substrate, pool, sm.global_settings({}),
                   conf)
    try:
        jobs = sm.job_settings_list({"job_specifications": [{
            "id": "strict",
            "tasks": [{"id": "t", "runtime": "docker",
                       "docker_image": "ghost/image:latest",
                       "command": "echo x"}]}]})
        jm.add_jobs(store, pool, jobs)
        tasks = jm.wait_for_tasks(store, "imgpool", "strict",
                                  timeout=30)
        assert tasks[0]["state"] == "failed"
        assert "allow_run_on_missing_image" in tasks[0]["error"]
        # Opt-in: the gate passes (execution still fails later only
        # if docker itself is absent — fake nodes have no docker, so
        # just assert the spec carries the opt-in and the gate logic
        # passes via the agent method).
        from batch_shipyard_tpu.agent.node_agent import (
            NodeAgent, TaskEnvError)
        agent = list(substrate._agents["imgpool"].values())[0]
        spec = {"image": "ghost/image:latest", "runtime": "docker",
                "allow_run_on_missing_image": True}
        agent._ensure_images(spec)  # no raise
        import pytest as pytest_mod
        spec["allow_run_on_missing_image"] = False
        with pytest_mod.raises(TaskEnvError):
            agent._ensure_images(spec)
    finally:
        substrate.stop_all()


def test_retention_time_removes_task_dir():
    """retention_time_seconds: a completed task's working dir is
    swept after the window (Azure Batch retention_time analog)."""
    import os as os_mod
    import time as time_mod
    from batch_shipyard_tpu.config import settings as sm
    from batch_shipyard_tpu.jobs import manager as jm
    from batch_shipyard_tpu.pool import manager as pm
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2)
    conf = {"pool_specification": {
        "id": "retpool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30}}
    pool = sm.pool_settings(conf)
    pm.create_pool(store, substrate, pool, sm.global_settings({}),
                   conf)
    try:
        jobs = sm.job_settings_list({"job_specifications": [{
            "id": "rj",
            "tasks": [{"id": "t", "command": "echo kept",
                       "retention_time_seconds": 1}]}]})
        jm.add_jobs(store, pool, jobs)
        tasks = jm.wait_for_tasks(store, "retpool", "rj", timeout=30)
        assert tasks[0]["state"] == "completed"
        node_id = FakePodSubstrate.node_id("retpool", 0, 0)
        task_dir = os_mod.path.join(substrate.work_root, "retpool",
                                    node_id, "tasks", "rj", "t")
        assert os_mod.path.isdir(task_dir)
        deadline = time_mod.monotonic() + 15
        while os_mod.path.isdir(task_dir):
            assert time_mod.monotonic() < deadline, \
                "task dir never swept after retention"
            time_mod.sleep(0.25)
        # Outputs in the store survive the node-side sweep.
        assert jm.get_task_output(store, "retpool", "rj",
                                  "t").strip() == b"kept"
    finally:
        substrate.stop_all()


def test_docker_env_contract_forwards_task_dir_and_slot():
    """Regression (PR 11, found by shipyard lint's
    env-docker-unmapped): SHIPYARD_TASK_SLOT must cross the docker
    boundary as a passthrough and SHIPYARD_TASK_DIR as the REMAPPED
    container path — docker run starts from an empty env, so before
    the fix both vars existed for runtime=none tasks and silently
    vanished inside containers."""
    from batch_shipyard_tpu.agent import task_runner
    execution = task_runner.TaskExecution(
        pool_id="p", job_id="j", task_id="t", node_id="n",
        node_index=0, command="echo x", runtime="docker",
        image="busybox", env={}, task_dir="/tmp/envmap-test", slot=3)
    argv = task_runner.synthesize_command(execution)
    pairs = set(zip(argv, argv[1:]))
    assert ("-e", "SHIPYARD_TASK_SLOT") in pairs
    # The host path would be a lie inside the container: the task
    # dir is mounted at /shipyard/task, so the forwarded value must
    # be the mount, not the passthrough.
    assert ("-e", "SHIPYARD_TASK_DIR=/shipyard/task") in pairs
    assert ("-e", "SHIPYARD_TASK_DIR") not in pairs

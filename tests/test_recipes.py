"""Recipe validation + execution: every shipped recipe config must
pass strict schema validation, and the substrate-independent ones run
end-to-end on the fake substrate (recipes are the acceptance suite,
SURVEY.md section 4)."""

import pathlib

import pytest
import yaml

from batch_shipyard_tpu.config.validator import ConfigType, validate_config

RECIPES = pathlib.Path(__file__).resolve().parent.parent / "recipes"

_TYPES = {"pool": ConfigType.POOL, "jobs": ConfigType.JOBS,
          "fs": ConfigType.REMOTEFS, "federation": ConfigType.FEDERATION,
          "slurm": ConfigType.SLURM, "monitor": ConfigType.MONITOR,
          "credentials": ConfigType.CREDENTIALS,
          "config": ConfigType.GLOBAL}


def all_recipe_configs():
    for config in sorted(RECIPES.glob("*/config/*.yaml")):
        yield config


@pytest.mark.parametrize(
    "path", list(all_recipe_configs()),
    ids=lambda p: f"{p.parent.parent.name}/{p.name}")
def test_recipe_config_validates(path):
    name = path.stem
    assert name in _TYPES, f"unknown config type {name}"
    with open(path, "r", encoding="utf-8") as fh:
        data = yaml.safe_load(fh)
    assert validate_config(_TYPES[name], data) == []


def test_every_recipe_has_readme():
    for recipe in sorted(RECIPES.iterdir()):
        if recipe.is_dir():
            assert (recipe / "README.md").exists(), recipe.name


def test_helloworld_recipe_runs_end_to_end(tmp_path):
    from batch_shipyard_tpu import fleet
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    creds = {"credentials": {"storage": {
        "backend": "localfs", "root": str(tmp_path / "store")}}}
    pool_conf = yaml.safe_load(open(
        RECIPES / "HelloWorld-CPU" / "config" / "pool.yaml"))
    jobs_conf = yaml.safe_load(open(
        RECIPES / "HelloWorld-CPU" / "config" / "jobs.yaml"))
    ctx = fleet.load_context(extra={
        "credentials": creds, "pool": pool_conf, "jobs": jobs_conf})
    try:
        fleet.action_pool_add(ctx)
        fleet.action_jobs_add(ctx)
        tasks = jobs_mgr.wait_for_tasks(
            ctx.store, "hello-pool", "hello", timeout=30)
        assert tasks[0]["state"] == "completed"
        out = jobs_mgr.get_task_output(
            ctx.store, "hello-pool", "hello", "task-00000")
        assert out.startswith(b"hello from")
    finally:
        ctx.substrate().stop_all()


def test_parametric_sweep_recipe_runs(tmp_path):
    from batch_shipyard_tpu import fleet
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    creds = {"credentials": {"storage": {
        "backend": "localfs", "root": str(tmp_path / "store")}}}
    pool_conf = yaml.safe_load(open(
        RECIPES / "ParametricSweep" / "config" / "pool.yaml"))
    jobs_conf = yaml.safe_load(open(
        RECIPES / "ParametricSweep" / "config" / "jobs.yaml"))
    ctx = fleet.load_context(extra={
        "credentials": creds, "pool": pool_conf, "jobs": jobs_conf})
    try:
        fleet.action_pool_add(ctx)
        submitted = fleet.action_jobs_add(ctx)
        assert submitted["lr-sweep"] == 6
        tasks = jobs_mgr.wait_for_tasks(
            ctx.store, "sweep-pool", "lr-sweep", timeout=30)
        assert all(t["state"] == "completed" for t in tasks)
    finally:
        ctx.substrate().stop_all()

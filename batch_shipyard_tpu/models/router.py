"""Serving fleet router: one ingress over N replica front ends.

The per-replica front end (models/server.py) binds ONE engine; a real
deployment runs an engine per chip/slice and needs a single entry
point that knows which replicas are alive and where the shortest
queue is. This router is that entry point (VERDICT r4 next #6 —
net-new depth: the reference has no serving at all):

  - **health checks**: a background thread polls every replica's
    /healthz (and scrapes /v1/stats for observability) on an
    interval; a replica that fails the probe — or any dispatch — is
    taken out of rotation and returns on its next passing probe;
  - **queue-depth-aware dispatch**: the router counts its own
    in-flight per replica (incremented at dispatch, decremented at
    completion) and adds the replica's last-scraped engine backlog,
    picking the least-loaded healthy replica — a long-running
    generation therefore steers new work elsewhere, which plain
    round-robin cannot do;
  - **failover**: a connection-refused dispatch marks the replica
    unhealthy and retries the remaining ones (non-streaming, and
    streaming before the first byte — a half-streamed response can
    not be replayed);
  - **sticky cancel**: request_id -> replica is remembered so
    DELETE /v1/requests/<id> reaches the replica that owns the run.

Same wire API as the front end, so models/loadgen.py (and any client)
points at the router unchanged. stdlib-only, like the front end: the
fleet's throughput lives in the replicas' jitted decode steps, not in
this socket layer.
"""

from __future__ import annotations

import hashlib
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer
from typing import Optional, Sequence

from batch_shipyard_tpu.goodput import events as gp_events
from batch_shipyard_tpu.models.server import (
    JsonRequestHandler, prometheus_lines)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class NoHealthyReplicaError(RuntimeError):
    pass


class DuplicateRequestError(ValueError):
    """The request_id is already in flight somewhere in the fleet."""


class _Replica:
    __slots__ = ("url", "healthy", "inflight", "backlog",
                 "last_probe_at", "last_error", "stats",
                 "dispatched", "completed", "failed",
                 "consecutive_failures", "draining",
                 "unhealthy_total")

    def __init__(self, url: str) -> None:
        self.url = url.rstrip("/")
        self.healthy = True          # optimistic until first probe
        self.inflight = 0            # router-tracked
        self.backlog = 0             # replica-reported engine depth
        self.last_probe_at = 0.0
        self.last_error: Optional[str] = None
        self.stats: dict = {}
        self.dispatched = 0
        self.completed = 0
        self.failed = 0
        # Prober backoff state: consecutive failed probes (reset on
        # any success); past the threshold the prober re-probes this
        # replica on an exponentially backed-off cadence.
        self.consecutive_failures = 0
        # Cooperative drain (healthz 503 + draining marker): out of
        # rotation like unhealthy, but NOT a fault — no probe
        # backoff, no unhealthy_total increment, and cancel still
        # reaches it (it may own live decodes finishing out).
        self.draining = False
        # healthy->unhealthy transitions (probe or dispatch failure);
        # exported as shipyard_router_replica_unhealthy_total.
        self.unhealthy_total = 0

    def load(self) -> int:
        return self.inflight + self.backlog

    def snapshot(self) -> dict:
        return {
            "url": self.url, "healthy": self.healthy,
            "draining": self.draining,
            "inflight": self.inflight, "backlog": self.backlog,
            "dispatched": self.dispatched,
            "completed": self.completed, "failed": self.failed,
            "consecutive_failures": self.consecutive_failures,
            "unhealthy_total": self.unhealthy_total,
            "last_error": self.last_error,
        }


class ServingRouter:
    def __init__(self, replica_urls: Sequence[str],
                 host: str = "127.0.0.1", port: int = 0,
                 health_interval: float = 2.0,
                 probe_timeout: float = 5.0,
                 request_timeout: float = 300.0,
                 owner_ttl: float = 600.0,
                 affinity_prefix_tokens: int = 32,
                 affinity_load_slack: int = 2,
                 retry_budget: int = 4,
                 retry_backoff_base: float = 0.05,
                 retry_backoff_cap: float = 1.0,
                 probe_failure_threshold: int = 3,
                 probe_backoff_cap: float = 30.0) -> None:
        if not replica_urls:
            raise ValueError("router needs at least one replica URL")
        self._replicas = [_Replica(u) for u in replica_urls]
        # Retry storm control: a request fails over at most
        # retry_budget times, with capped exponential backoff between
        # attempts — one dead replica must not amplify into a
        # synchronized hammering of the survivors.
        self._retry_budget = retry_budget
        self._retry_backoff_base = retry_backoff_base
        self._retry_backoff_cap = retry_backoff_cap
        self._probe_failure_threshold = probe_failure_threshold
        self._probe_backoff_cap = probe_backoff_cap
        # Mid-stream recovery bookkeeping: resume attempts begun,
        # streams completed after >=1 resume, streams given up on,
        # and a bounded recent-recovery log (the bench's TTFT-delta
        # source).
        self.recoveries = 0
        self.recovered_requests = 0
        self.lost_streams = 0
        import collections
        self.recovery_log: "collections.deque" = collections.deque(
            maxlen=256)
        self._lock = threading.Lock()
        self._owner: dict[str, _Replica] = {}  # request_id -> replica
        # Last-write stamp per ownership entry: the TTL retirement
        # sweep (_retire_stale) uses it to find entries that leaked
        # past their completion path under sustained traffic.
        self._owner_stamp: dict[str, float] = {}
        self._owner_ttl = owner_ttl
        # Prefix-affinity routing: prefix key -> (replica, stamp).
        # Same-prefix requests steer to the replica whose paged KV
        # pool already holds the prefix pages (server-side prefix
        # cache, models/serving.py) — the key is client-supplied
        # ("prefix_key") or derived from the first N prompt tokens.
        self._affinity: dict[str, tuple[_Replica, float]] = {}
        self._affinity_prefix_tokens = affinity_prefix_tokens
        self._affinity_load_slack = affinity_load_slack
        self.affinity_routed = 0
        # Timed-out dispatches whose runs may still be live on their
        # replica (reconciled by the health loop).
        self._orphaned: dict[str, _Replica] = {}
        self._health_interval = health_interval
        self._probe_timeout = probe_timeout
        self._request_timeout = request_timeout
        self._stop = threading.Event()
        # One LONG-LIVED prober thread per replica (ADVICE r5): each
        # keeps its own cadence, so a hung replica's probe (connect
        # timeout, not refuse) cannot stretch fault detection for the
        # rest of the fleet — and large fleets stop paying
        # per-interval thread churn. The health thread itself only
        # reconciles orphans.
        self._prober_threads = [
            threading.Thread(target=self._probe_loop, args=(r,),
                             name=f"router-probe-{k}", daemon=True)
            for k, r in enumerate(self._replicas)]
        self._health_thread = threading.Thread(
            target=self._health_loop, name="router-health",
            daemon=True)
        # Live client sockets (handler setup/finish): kill() severs
        # them to reproduce a router-process crash for the chaos
        # drill — clients see a dead stream and must cancel-then-
        # resume against the successor router.
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        router = self

        class Handler(JsonRequestHandler):
            def setup(self):
                super().setup()
                with router._conns_lock:
                    router._conns.add(self.connection)

            def finish(self):
                try:
                    super().finish()
                finally:
                    with router._conns_lock:
                        router._conns.discard(self.connection)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    healthy = router.healthy_count()
                    self._reply(200 if healthy else 503,
                                {"ok": healthy > 0,
                                 "healthy_replicas": healthy})
                elif self.path == "/metrics":
                    self._reply_metrics(router.prometheus_metrics())
                elif self.path == "/v1/stats":
                    self._reply(200, router.stats())
                elif self.path == "/v1/replicas":
                    self._reply(200, {"replicas": router.replicas()})
                else:
                    self._reply(404, {"error": "not found"})

            def do_DELETE(self):  # noqa: N802
                request_id = self._delete_request_id()
                if request_id is None:
                    return
                code, payload = router.cancel(request_id)
                self._reply(code, payload)

            def do_POST(self):  # noqa: N802
                if self.path != "/v1/generate":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    spec = json.loads(self.rfile.read(length))
                except (ValueError, OSError) as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                if not isinstance(spec, dict):
                    self._reply(400,
                                {"error": "body must be a JSON "
                                          "object"})
                    return
                if spec.get("stream"):
                    self._stream(spec)
                    return
                try:
                    code, payload = router.dispatch(spec)
                except NoHealthyReplicaError as exc:
                    self._reply(503, {"error": str(exc)})
                    return
                except DuplicateRequestError as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                self._reply(code, payload)

            def _stream(self, spec: dict) -> None:
                """Streaming proxy with mid-stream recovery: forward
                the replica's NDJSON chunk stream, journaling every
                emitted token. If the replica dies (bare EOF before
                the final result line, a connection reset) or drains
                the decode out from under us (a marked error line),
                the request is resumed on a sibling via
                resume_tokens — the sibling re-prefills prompt +
                emitted and continues the greedy stream byte-
                identically; an index-based dedupe keeps token
                delivery to the client exactly-once across the
                failover. Read TIMEOUTS never resume (slow is not
                dead: the run may still be live — resuming would
                decode it twice)."""
                try:
                    upstream, replica, request_id = \
                        router.open_stream(spec)
                except NoHealthyReplicaError as exc:
                    self._reply(503, {"error": str(exc)})
                    return
                except DuplicateRequestError as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                except urllib.error.HTTPError as exc:
                    self._reply(exc.code,
                                getattr(exc, "payload", None) or
                                _json_or_error(exc.read()))
                    return
                except (urllib.error.URLError, OSError,
                        TimeoutError) as exc:
                    self._reply(504, {"error": f"replica timed "
                                               f"out: {exc}"})
                    return
                import http.client as http_client
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                except OSError:
                    upstream.close()
                    router.finish(replica, request_id, ok=True)
                    return

                def _relay(line: bytes) -> bool:
                    try:
                        self.wfile.write(
                            f"{len(line):x}\r\n".encode()
                            + line + b"\r\n")
                        self.wfile.flush()
                        return True
                    except (BrokenPipeError, ConnectionResetError):
                        return False

                # Progress journal for this request: greedy tokens
                # relayed so far (by global index) — exactly what a
                # sibling needs to resume, and the dedupe source for
                # exactly-once delivery. Seeded from the client's own
                # resume_tokens (a cancel-then-resume after a ROUTER
                # crash): token indexes are global across the whole
                # request, so the journal must start where the client
                # already is — a replica replaying the full run then
                # dedupes to exactly the missing tail, and a second
                # failover resumes with the full prefix, not just the
                # tokens this router relayed.
                emitted: list[int] = [
                    int(t) for t in
                    (spec.get("resume_tokens") or [])]
                resumes = 0
                timed_out = False
                saw_final = False
                failed_urls = {replica.url}
                # outcome: "final" (result line relayed), "timeout"
                # (slow-is-not-dead orphan), "client_gone",
                # "synthesized" / "lost" (recovery path did its own
                # accounting).
                outcome = None
                while outcome is None:  # one pass per replica
                    client_ok = True
                    resume_needed = False
                    # http.client strips the upstream chunked
                    # framing; re-chunk line-by-line downstream.
                    # Upstream read failures and downstream write
                    # failures are distinguished: a replica dying
                    # mid-stream is a recovery event; a client
                    # disconnect is not (the replica finishes fine).
                    while True:
                        try:
                            line = upstream.readline()
                        except (OSError,
                                http_client.HTTPException) as exc:
                            timed_out = _is_timeout(exc)
                            if timed_out:
                                outcome = "timeout"
                            else:
                                router._mark_unhealthy(replica, exc)
                                resume_needed = True
                            break
                        if not line:
                            if saw_final:
                                outcome = "final"
                            else:
                                # Bare EOF with no final result line:
                                # the replica was killed mid-decode.
                                resume_needed = True
                            break
                        try:
                            event = json.loads(line)
                        except ValueError:
                            event = None
                        if isinstance(event, dict) and \
                                "token" in event and "index" in event:
                            idx = event["index"]
                            if idx < len(emitted):
                                continue  # replayed after a resume
                            emitted.append(int(event["token"]))
                            if not _relay(line):
                                client_ok = False
                                outcome = "client_gone"
                                break
                            continue
                        if isinstance(event, dict) and \
                                event.get("error") and \
                                event.get("draining"):
                            # Drain-abandoned decode: resume on a
                            # sibling instead of surfacing the error.
                            resume_needed = True
                            break
                        if isinstance(event, dict) and (
                                "tokens" in event or
                                event.get("error")):
                            # Terminal line (result, or an error the
                            # replica means: shed/cancel/validation).
                            saw_final = True
                        if not _relay(line):
                            client_ok = False
                            outcome = "client_gone"
                            break
                    upstream.close()
                    if outcome is not None or not resume_needed:
                        if outcome is None:
                            outcome = "final" if saw_final \
                                else "client_gone"
                        break
                    # --- recovery path -------------------------------
                    detect_at = time.monotonic()
                    router.finish(replica, request_id, ok=False,
                                  retrying=True)
                    max_new = int(spec.get("max_new_tokens", 16) or 16)
                    eos_id = spec.get("eos_id")
                    if len(emitted) >= max_new or (
                            eos_id is not None and emitted and
                            emitted[-1] == eos_id):
                        # Everything was already delivered; only the
                        # final result line was lost — synthesize it.
                        _relay(json.dumps(
                            {"request_id": request_id,
                             "tokens": emitted,
                             "num_tokens": len(emitted),
                             "recovered": True,
                             "resumes": resumes}).encode()
                            + b"\n")
                        router._release_claim(request_id)
                        router._note_recovery(
                            request_id, replica.url, None,
                            len(emitted), 0.0, synthesized=True)
                        outcome = "synthesized"
                        break
                    resumes += 1
                    if resumes > router._retry_budget:
                        _relay(json.dumps(
                            {"error": "stream lost: retry budget "
                                      f"({router._retry_budget}) "
                                      "exhausted"}).encode() + b"\n")
                        router._release_claim(request_id)
                        router._note_lost(request_id)
                        outcome = "lost"
                        break
                    router._retry_wait(resumes - 1)
                    try:
                        upstream, to_replica = router.resume_stream(
                            spec, request_id, emitted,
                            exclude=failed_urls)
                    except (NoHealthyReplicaError,
                            urllib.error.HTTPError,
                            urllib.error.URLError, OSError,
                            TimeoutError) as exc:
                        _relay(json.dumps(
                            {"error": f"stream lost: resume failed: "
                                      f"{exc}"}).encode() + b"\n")
                        router._release_claim(request_id)
                        router._note_lost(request_id)
                        outcome = "lost"
                        break
                    router._note_recovery(
                        request_id, replica.url, to_replica.url,
                        len(emitted),
                        time.monotonic() - detect_at)
                    replica = to_replica
                    failed_urls.add(replica.url)
                    # loop: relay from the sibling
                try:
                    if client_ok:
                        if outcome == "timeout":
                            _relay(json.dumps(
                                {"error": "replica failed "
                                          "mid-stream"}).encode()
                                + b"\n")
                        self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass
                if outcome == "timeout":
                    # The run may still be live on the (slow)
                    # replica: keep ownership — duplicate gate +
                    # sticky cancel stay correct — and let orphan
                    # reconciliation release the id once the
                    # replica forgets it (ADVICE r5).
                    router._orphan_inflight(replica, request_id)
                elif outcome in ("final", "client_gone"):
                    # A vanished client doesn't fail the replica —
                    # its engine finishes the run on its own.
                    if resumes and outcome == "final":
                        router._note_recovered(request_id)
                    router.finish(replica, request_id, ok=True)
                # "synthesized"/"lost": the recovery path already
                # released accounting and the claim.

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="router-http",
            daemon=True)

    # ----------------------------- lifecycle ---------------------------

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServingRouter":
        self._probe_all()  # honest health before the first dispatch
        for t in self._prober_threads:
            t.start()
        self._health_thread.start()
        self._http_thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._health_thread.join(timeout=5.0)
        for t in self._prober_threads:
            # Daemon probers may sit inside a probe_timeout read;
            # don't block shutdown on them.
            t.join(timeout=0.5)

    def kill(self) -> None:
        """The router-process-crash failure shape (chaos drills):
        stop serving AND sever every live client connection mid-
        stream — no final lines, no clean terminators. Clients must
        recover through a successor router with cancel-then-resume;
        the replicas keep decoding untouched (their duplicate gates
        are what keeps delivery exactly-once across the handoff)."""
        self._stop.set()
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._health_thread.join(timeout=5.0)

    # ------------------------------ health -----------------------------

    def _probe(self, replica: _Replica) -> None:
        draining = False
        try:
            try:
                with urllib.request.urlopen(
                        f"{replica.url}/healthz",
                        timeout=self._probe_timeout) as resp:
                    ok = resp.status == 200
            except urllib.error.HTTPError as exc:
                # A draining replica answers healthz 503 with a
                # marker: cooperative shutdown, not a fault — keep
                # scraping its stats (live decodes are finishing out)
                # but take it out of rotation without probe backoff.
                payload = _json_or_error(exc.read())
                if not payload.get("draining"):
                    raise
                ok, draining = False, True
            stats = {}
            with urllib.request.urlopen(
                    f"{replica.url}/v1/stats",
                    timeout=self._probe_timeout) as resp:
                stats = json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError) as exc:
            with self._lock:
                if replica.healthy:
                    replica.unhealthy_total += 1
                replica.healthy = False
                replica.draining = False
                replica.consecutive_failures += 1
                replica.last_error = str(exc)
                replica.last_probe_at = time.time()
            return
        with self._lock:
            if replica.healthy and not ok and not draining:
                replica.unhealthy_total += 1
            replica.healthy = ok
            replica.draining = draining
            replica.last_error = (None if ok else
                                  "draining" if draining
                                  else "healthz != 200")
            if ok or draining:
                replica.consecutive_failures = 0
            else:
                replica.consecutive_failures += 1
            replica.backlog = int(stats.get("engine_backlog", 0))
            replica.stats = stats
            replica.last_probe_at = time.time()

    def _probe_all(self) -> None:
        # One-shot concurrent sweep for start(): honest health before
        # the first dispatch. Steady-state probing runs in the
        # long-lived per-replica _probe_loop threads.
        threads = [threading.Thread(target=self._probe, args=(r,),
                                    daemon=True)
                   for r in self._replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self._probe_timeout * 2 + 1)

    def _probe_delay(self, replica: _Replica) -> float:
        """Probe cadence: the base interval while healthy (or within
        the failure threshold), then exponential backoff capped at
        probe_backoff_cap — a flapping or long-dead replica stops
        being hammered at full cadence, and its first passing probe
        resets the cadence."""
        with self._lock:
            failures = replica.consecutive_failures
        if failures <= self._probe_failure_threshold:
            return self._health_interval
        exp = min(failures - self._probe_failure_threshold, 6)
        return min(self._probe_backoff_cap,
                   self._health_interval * (2 ** exp))

    def _probe_loop(self, replica: _Replica) -> None:
        """Per-replica steady-state prober: this replica's probe may
        hang for probe_timeout without delaying any other replica's
        cadence."""
        while not self._stop.wait(self._probe_delay(replica)):
            self._probe(replica)

    def _health_loop(self) -> None:
        while not self._stop.wait(self._health_interval):
            self._reconcile_orphans()
            self._retire_stale()

    def _retire_stale(self) -> None:
        """TTL retirement for the sticky/duplicate-id ownership map
        and the affinity table: under sustained traffic, entries that
        leak past their completion path (a client that vanished
        between claim and finish, a replica that crashed with ids
        mapped) would otherwise accumulate forever. Retirement keeps
        the failover-race guarantees: a stale RESERVED claim retires
        unconditionally (reservations live for one dispatch call),
        but a stale LIVE mapping drops only after the owning replica
        demonstrably no longer knows the id (the orphan-reconciliation
        probe) — a long decode's duplicate gate and sticky cancel
        survive any TTL. A retired id is immediately safe to
        resubmit."""
        now = time.time()
        live: list = []
        with self._lock:
            for key in [k for k, (_r, stamp)
                        in self._affinity.items()
                        if now - stamp > self._owner_ttl]:
                # Routing hints, not correctness state: pure TTL.
                del self._affinity[key]
            for rid in list(self._owner_stamp):
                if rid not in self._owner:
                    del self._owner_stamp[rid]  # desync backstop
                    continue
                if now - self._owner_stamp[rid] <= self._owner_ttl:
                    continue
                if rid in self._orphaned:
                    continue  # orphan reconciliation owns this id
                owner = self._owner[rid]
                if owner is None:
                    self._owner.pop(rid, None)
                    self._owner_stamp.pop(rid, None)
                else:
                    live.append((rid, owner))
        for rid, owner in live:
            forgotten = False
            try:
                with urllib.request.urlopen(
                        f"{owner.url}/v1/requests/{rid}",
                        timeout=self._probe_timeout) as resp:
                    forgotten = resp.status != 200
            except urllib.error.HTTPError as exc:
                forgotten = exc.code == 404
            except (urllib.error.URLError, OSError):
                forgotten = True  # replica gone: the run went with it
            with self._lock:
                if forgotten:
                    if self._owner.get(rid) is owner:
                        self._owner.pop(rid, None)
                        self._owner_stamp.pop(rid, None)
                elif rid in self._owner_stamp:
                    # Alive and still decoding: refresh so the sweep
                    # doesn't re-probe it every interval.
                    self._owner_stamp[rid] = time.time()

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.healthy)

    def replicas(self) -> list[dict]:
        with self._lock:
            return [r.snapshot() for r in self._replicas]

    # ----------------------------- dispatch ----------------------------

    def _affinity_key(self, spec: dict) -> Optional[str]:
        """Prefix key for affinity routing: client-supplied
        ("prefix_key" — e.g. a system-prompt/template id) or derived
        from the first affinity_prefix_tokens prompt tokens. Prompts
        shorter than the window get no key (nothing worth steering
        for)."""
        key = spec.get("prefix_key")
        if key:
            return f"client:{key}"
        prompt = spec.get("prompt")
        n = self._affinity_prefix_tokens
        if not isinstance(prompt, list) or len(prompt) < n or n <= 0:
            return None
        head = ",".join(str(t) for t in prompt[:n])
        return hashlib.blake2b(head.encode(),
                               digest_size=16).hexdigest()

    def _pick(self, exclude: set,
              affinity_key: Optional[str] = None) -> _Replica:
        """Least-loaded healthy replica (router inflight + last
        scraped engine backlog). With an affinity key, prefer the
        replica that last served this prefix — its paged KV pool
        holds the prefix pages, so prefill there is a gather instead
        of a recompute — unless it is unhealthy, excluded, or more
        than affinity_load_slack ahead of the least-loaded choice
        (prefix stickiness must not create hot spots)."""
        with self._lock:
            candidates = [r for r in self._replicas
                          if r.healthy and r.url not in exclude]
            if not candidates:
                raise NoHealthyReplicaError(
                    f"no healthy replica "
                    f"({len(self._replicas)} registered)")
            best = min(candidates, key=lambda r: (r.load(),
                                                  r.dispatched))
            chosen = best
            if affinity_key is not None:
                entry = self._affinity.get(affinity_key)
                if entry is not None:
                    sticky = entry[0]
                    if (sticky.healthy and sticky.url not in exclude
                            and sticky.load() <= best.load() +
                            self._affinity_load_slack):
                        if sticky is not best:
                            chosen = sticky
                        self.affinity_routed += 1
                self._affinity[affinity_key] = (chosen, time.time())
            chosen.inflight += 1
            chosen.dispatched += 1
            return chosen

    def finish(self, replica: _Replica, request_id: Optional[str],
               ok: bool, retrying: bool = False) -> None:
        """Release one dispatch's accounting. ``retrying=True`` keeps
        the duplicate-request claim alive by demoting the ownership
        back to the reserved sentinel instead of popping it — the
        caller is about to re-dispatch the same id to another replica,
        and a concurrent same-id POST must NOT pass _claim() in that
        window (ADVICE r5: the fleet would decode it twice)."""
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)
            if ok:
                replica.completed += 1
            else:
                replica.failed += 1
            # Only the current owner clears the mapping (a failover
            # retry may have remapped the id to another replica).
            if request_id is not None and \
                    self._owner.get(request_id) is replica:
                if retrying:
                    self._owner[request_id] = None  # back to reserved
                    self._owner_stamp[request_id] = time.time()
                else:
                    self._owner.pop(request_id, None)
                    self._owner_stamp.pop(request_id, None)

    def _orphan_inflight(self, replica: _Replica,
                         request_id: Optional[str]) -> None:
        """A dispatch (or mid-stream read) timed out while the run may
        still be live on the replica: release the inflight slot but
        KEEP ownership, handing the id to orphan reconciliation — the
        duplicate gate and sticky cancel stay correct until the
        replica demonstrably forgets the run."""
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)
            replica.failed += 1
        self._orphan(request_id, replica)

    def _claim(self, request_id: Optional[str]) -> None:
        """Router-level duplicate-id gate: the per-replica front end
        rejects ids IT has in flight (server.py _make_pending), but
        two replicas can't see each other — without this, a retry of
        a live id lands on the other replica and decodes twice.
        Check-and-RESERVE under one lock acquisition (a None owner =
        claimed, replica not yet picked), so two concurrent claims of
        the same id cannot both pass."""
        if not request_id:
            return
        with self._lock:
            if request_id in self._owner:
                raise DuplicateRequestError(
                    f"request_id {request_id} in flight")
            self._owner[request_id] = None  # reserved
            self._owner_stamp[request_id] = time.time()

    def _release_claim(self, request_id: Optional[str]) -> None:
        """Drop a reservation that never reached a replica (e.g. no
        healthy replica after the claim)."""
        if request_id:
            with self._lock:
                if self._owner.get(request_id) is None:
                    self._owner.pop(request_id, None)
                    self._owner_stamp.pop(request_id, None)

    def _remember(self, request_id: Optional[str],
                  replica: _Replica) -> None:
        if request_id:
            with self._lock:
                self._owner[request_id] = replica
                self._owner_stamp[request_id] = time.time()

    def _orphan(self, request_id: Optional[str],
                replica: _Replica) -> None:
        """A dispatch timed out but the run may still be live on the
        replica: keep the ownership (duplicate gate + sticky cancel
        stay correct) and let the health loop reconcile — the entry
        clears once the replica no longer knows the id."""
        if request_id:
            with self._lock:
                self._orphaned[request_id] = replica

    def _reconcile_orphans(self) -> None:
        with self._lock:
            orphans = dict(self._orphaned)
        for request_id, replica in orphans.items():
            done = False
            try:
                with urllib.request.urlopen(
                        f"{replica.url}/v1/requests/{request_id}",
                        timeout=self._probe_timeout) as resp:
                    done = resp.status != 200
            except urllib.error.HTTPError as exc:
                done = exc.code == 404
            except (urllib.error.URLError, OSError):
                done = True  # replica gone: the run is gone with it
            if done:
                with self._lock:
                    self._orphaned.pop(request_id, None)
                    if self._owner.get(request_id) is replica:
                        self._owner.pop(request_id, None)
                        self._owner_stamp.pop(request_id, None)

    def _mark_unhealthy(self, replica: _Replica, exc: Exception
                        ) -> None:
        logger.warning("replica %s failed dispatch: %s", replica.url,
                       exc)
        with self._lock:
            if replica.healthy:
                replica.unhealthy_total += 1
            replica.healthy = False
            replica.consecutive_failures += 1
            replica.last_error = str(exc)

    def _mark_draining(self, replica: _Replica) -> None:
        """A dispatch saw the replica's 503+draining answer: converge
        rotation state ahead of the next probe."""
        with self._lock:
            replica.healthy = False
            replica.draining = True
            replica.last_error = "draining"

    def _retry_wait(self, attempt: int) -> None:
        """Capped exponential backoff between failover attempts
        (retry storm control); interruptible by shutdown."""
        delay = min(self._retry_backoff_cap,
                    self._retry_backoff_base * (2 ** attempt))
        self._stop.wait(delay)

    @staticmethod
    def _is_backpressure(code: int, payload: dict) -> bool:
        """Replica answers that mean 'try a sibling', not 'the
        request failed': drain refusals and 429 concurrency caps.
        A shed 503 is NOT included — the request's TTFT deadline is
        already blown fleet-wide; relaying it is honest."""
        return (code in (503, 429) and isinstance(payload, dict) and
                bool(payload.get("draining") or
                     payload.get("backpressure")))

    def dispatch(self, spec: dict) -> tuple[int, dict]:
        """Route one non-streaming generate; fail over across
        replicas on connection errors."""
        request_id = spec.get("request_id")
        affinity_key = self._affinity_key(spec)
        self._claim(request_id)
        tried: set = set()
        attempts = 0
        while True:
            try:
                replica = self._pick(tried, affinity_key)
            except NoHealthyReplicaError:
                self._release_claim(request_id)
                raise
            tried.add(replica.url)
            self._remember(request_id, replica)
            body = json.dumps(spec).encode()
            req = urllib.request.Request(
                f"{replica.url}/v1/generate", data=body,
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                with urllib.request.urlopen(
                        req, timeout=self._request_timeout) as resp:
                    body = resp.read()
                    status = resp.status
                try:
                    payload = json.loads(body)
                    if not isinstance(payload, dict):
                        raise ValueError("non-object JSON")
                except ValueError:
                    # A 200 with an unparseable body is a broken
                    # replica, not a crashed one: release the
                    # inflight slot and relay the failure.
                    self.finish(replica, request_id, ok=False)
                    return 502, {"error": f"replica {replica.url} "
                                          f"returned non-JSON body"}
                self.finish(replica, request_id, ok=True)
                payload["_replica"] = replica.url
                return status, payload
            except urllib.error.HTTPError as exc:
                payload = _json_or_error(exc.read())
                if self._is_backpressure(exc.code, payload):
                    # Drain refusal / 429 cap: the request is fine,
                    # the replica just won't take it — fail over
                    # within the retry budget instead of relaying.
                    if exc.code == 503:
                        self._mark_draining(replica)
                    self.finish(replica, request_id, ok=False,
                                retrying=True)
                    attempts += 1
                    if attempts > self._retry_budget:
                        self._release_claim(request_id)
                        return 503, {
                            "error": f"request_id {request_id}: "
                                     f"retry budget "
                                     f"({self._retry_budget}) "
                                     f"exhausted", "retryable": True}
                    self._retry_wait(attempts - 1)
                    continue
                # The replica answered (4xx/5xx): not a health event,
                # relay verbatim.
                self.finish(replica, request_id, ok=False)
                return exc.code, payload
            except (urllib.error.URLError, OSError,
                    TimeoutError) as exc:
                if _is_timeout(exc):
                    # A saturated-but-alive replica: generate is NOT
                    # idempotent (the run may still complete there),
                    # so re-dispatching would double the work — and
                    # slow is not dead, so no health event either.
                    # Ownership is kept (duplicate gate + cancel stay
                    # correct) until reconciliation sees the replica
                    # forget the id; the load signal falls back to
                    # the scraped engine backlog.
                    self._orphan_inflight(replica, request_id)
                    return 504, {"error": f"replica {replica.url} "
                                          f"timed out: {exc}"}
                # retrying=True: the claim stays reserved through the
                # retry loop so a concurrent duplicate POST is still
                # rejected in the failover window.
                self.finish(replica, request_id, ok=False,
                            retrying=True)
                self._mark_unhealthy(replica, exc)
                attempts += 1
                if attempts > self._retry_budget:
                    self._release_claim(request_id)
                    return 503, {
                        "error": f"request_id {request_id}: retry "
                                 f"budget ({self._retry_budget}) "
                                 f"exhausted", "retryable": True}
                self._retry_wait(attempts - 1)
                # loop: try the next healthy replica

    def open_stream(self, spec: dict):
        """Dispatch a streaming generate; returns (upstream response,
        replica, request_id). Failover happens here (before any byte
        reaches the client)."""
        request_id = spec.get("request_id")
        affinity_key = self._affinity_key(spec)
        self._claim(request_id)
        tried: set = set()
        attempts = 0
        while True:
            try:
                replica = self._pick(tried, affinity_key)
            except NoHealthyReplicaError:
                self._release_claim(request_id)
                raise
            tried.add(replica.url)
            self._remember(request_id, replica)
            req = urllib.request.Request(
                f"{replica.url}/v1/generate",
                data=json.dumps(spec).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                upstream = urllib.request.urlopen(
                    req, timeout=self._request_timeout)
                return upstream, replica, request_id
            except urllib.error.HTTPError as exc:
                payload = _json_or_error(exc.read())
                if self._is_backpressure(exc.code, payload):
                    if exc.code == 503:
                        self._mark_draining(replica)
                    self.finish(replica, request_id, ok=False,
                                retrying=True)
                    attempts += 1
                    if attempts > self._retry_budget:
                        self._release_claim(request_id)
                        raise NoHealthyReplicaError(
                            f"retry budget ({self._retry_budget}) "
                            f"exhausted") from exc
                    self._retry_wait(attempts - 1)
                    continue
                self.finish(replica, request_id, ok=False)
                # The body was consumed above; stash the parsed
                # payload for the handler's relay.
                exc.payload = payload
                raise
            except (urllib.error.URLError, OSError,
                    TimeoutError) as exc:
                if _is_timeout(exc):
                    self._orphan_inflight(replica, request_id)
                    raise  # see dispatch(): slow is not dead
                self.finish(replica, request_id, ok=False,
                            retrying=True)
                self._mark_unhealthy(replica, exc)
                attempts += 1
                if attempts > self._retry_budget:
                    self._release_claim(request_id)
                    raise NoHealthyReplicaError(
                        f"retry budget ({self._retry_budget}) "
                        f"exhausted") from exc
                self._retry_wait(attempts - 1)

    def resume_stream(self, spec: dict, request_id: Optional[str],
                      emitted: list[int], exclude: set):
        """Re-dispatch a broken stream on a sibling: same spec plus
        resume_tokens (the journaled progress) so the sibling's
        engine re-prefills prompt+emitted in one pass and the greedy
        decode continues byte-identically. The caller still holds the
        id's reserved claim (finish(retrying=True)) — no re-claim
        here; exclude carries the replicas that already failed this
        request. Returns (upstream response, replica). Raises
        NoHealthyReplicaError when no sibling can take it."""
        resume_spec = dict(spec, resume_tokens=list(emitted))
        affinity_key = self._affinity_key(spec)
        tried: set = set(exclude)
        body = json.dumps(resume_spec).encode()
        while True:
            replica = self._pick(tried, affinity_key)
            tried.add(replica.url)
            self._remember(request_id, replica)
            req = urllib.request.Request(
                f"{replica.url}/v1/generate", data=body,
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                upstream = urllib.request.urlopen(
                    req, timeout=self._request_timeout)
                self.recoveries += 1
                return upstream, replica
            except urllib.error.HTTPError as exc:
                payload = _json_or_error(exc.read())
                self.finish(replica, request_id, ok=False,
                            retrying=True)
                if self._is_backpressure(exc.code, payload):
                    if exc.code == 503:
                        self._mark_draining(replica)
                    continue  # next sibling
                exc.payload = payload
                raise
            except (urllib.error.URLError, OSError,
                    TimeoutError) as exc:
                self.finish(replica, request_id, ok=False,
                            retrying=True)
                if _is_timeout(exc):
                    raise  # slow is not dead; do not double-dispatch
                self._mark_unhealthy(replica, exc)

    def _note_recovery(self, request_id: Optional[str],
                       from_url: str, to_url: Optional[str],
                       resumed_tokens: int, recovery_seconds: float,
                       synthesized: bool = False) -> None:
        with self._lock:
            self.recovery_log.append({
                "request_id": request_id, "from": from_url,
                "to": to_url, "resumed_tokens": resumed_tokens,
                "recovery_seconds": recovery_seconds,
                "synthesized": synthesized, "at": time.time()})
            if synthesized:
                self.recovered_requests += 1
        # Price the re-dispatch as serving-recovery badput when this
        # router runs inside a pool task (no-op otherwise).
        gp_events.record(
            gp_events.SERVE_RECOVERY,
            time.time() - recovery_seconds, time.time(),
            request_id=request_id or "",
            resumed_tokens=resumed_tokens)

    def _note_recovered(self, request_id: Optional[str]) -> None:
        with self._lock:
            self.recovered_requests += 1

    def _note_lost(self, request_id: Optional[str]) -> None:
        logger.warning("stream %s lost: recovery failed", request_id)
        with self._lock:
            self.lost_streams += 1

    def cancel(self, request_id: str) -> tuple[int, dict]:
        """Cancel on the owning replica when known; otherwise
        broadcast — replicas 404 unknown ids (server.py do_DELETE),
        so the probe keeps going until the owner answers 202.
        Draining replicas stay in the broadcast: they may own live
        decodes finishing out."""
        with self._lock:
            replica = self._owner.get(request_id)
            targets = ([replica] if replica is not None
                       else [r for r in self._replicas
                             if r.healthy or r.draining])
        last: tuple[int, dict] = (404, {"error": f"unknown "
                                                 f"request_id "
                                                 f"{request_id}"})
        for target in targets:
            req = urllib.request.Request(
                f"{target.url}/v1/requests/{request_id}",
                method="DELETE")
            try:
                with urllib.request.urlopen(
                        req, timeout=self._probe_timeout) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                last = (exc.code, _json_or_error(exc.read()))
                if exc.code != 404:
                    return last  # owner answered with a real error
            except (urllib.error.URLError, OSError) as exc:
                self._mark_unhealthy(target, exc)
                last = (503, {"error": "no replica reachable for "
                                       "cancel"})
        return last

    def prometheus_metrics(self) -> list[str]:
        """Fleet metrics in Prometheus exposition format: aggregate
        gauges plus per-replica series labeled by replica URL — one
        scrape target for the whole fleet."""
        stats = self.stats()
        lines = prometheus_lines("shipyard_router", {
            "replicas": stats["replicas"],
            "healthy_replicas": stats["healthy_replicas"],
            "inflight": stats["router_inflight"],
            "dispatched_total": stats["dispatched"],
            "completed_total": stats["completed"],
            "failed_total": stats["failed"],
            "affinity_routed_total": stats["affinity_routed"],
            "recoveries_total": stats["recoveries"],
            "recovered_requests_total": stats["recovered_requests"],
            "lost_streams_total": stats["lost_streams"],
        })
        prefix = stats.get("prefix_cache")
        if prefix:
            lines.extend(prometheus_lines("shipyard_router", {
                "prefix_hit_rate": prefix["hit_rate"],
                "prefix_hit_tokens_total": prefix["hit_tokens"],
                "prefix_prompt_tokens_total":
                    prefix["total_prompt_tokens"],
            }))
        for snap in stats["per_replica"]:
            lines.extend(prometheus_lines(
                "shipyard_router_replica", {
                    "healthy": 1 if snap["healthy"] else 0,
                    "inflight": snap["inflight"],
                    "backlog": snap["backlog"],
                    "dispatched_total": snap["dispatched"],
                    "completed_total": snap["completed"],
                    "failed_total": snap["failed"],
                    "draining": 1 if snap["draining"] else 0,
                    "unhealthy_total": snap["unhealthy_total"],
                }, labels={"replica": snap["url"]}))
        # Fleet-wide latency: quantile gauges + the merged histogram
        # in native _bucket exposition (stats() merged the replicas'
        # fixed-bucket counts losslessly).
        from batch_shipyard_tpu.trace.histogram import \
            LatencyHistogram
        for metric in ("ttft", "tpot"):
            for pct, value in stats.get(f"{metric}_ms", {}).items():
                lines.extend(prometheus_lines(
                    "shipyard_router", {f"{metric}_ms": value},
                    labels={"quantile": f"0.{pct}"}))
            merged = LatencyHistogram.from_dict(
                stats.get(f"{metric}_hist"))
            if merged is not None and merged.count:
                lines.extend(merged.prometheus_bucket_lines(
                    f"shipyard_router_{metric}_ms"))
        return lines

    def stats(self) -> dict:
        """Aggregate + per-replica: the fleet view of
        ServingFrontEnd.stats()."""
        with self._lock:
            snaps = [r.snapshot() for r in self._replicas]
            stats = {r.url: dict(r.stats) for r in self._replicas}
        agg = {
            "replicas": len(snaps),
            "healthy_replicas": sum(1 for s in snaps if s["healthy"]),
            "router_inflight": sum(s["inflight"] for s in snaps),
            "dispatched": sum(s["dispatched"] for s in snaps),
            "completed": sum(s["completed"] for s in snaps),
            "failed": sum(s["failed"] for s in snaps),
            "affinity_routed": self.affinity_routed,
            # Mid-stream recovery: attempts begun, streams completed
            # after >=1 resume (or with a synthesized final), streams
            # given up on, and the recent-recovery detail the bench's
            # TTFT-delta report reads.
            "recoveries": self.recoveries,
            "recovered_requests": self.recovered_requests,
            "lost_streams": self.lost_streams,
            "recovery_log": list(self.recovery_log),
            "completed_requests": sum(
                s.get("completed_requests", 0)
                for s in stats.values()),
            "generated_tokens": sum(
                s.get("generated_tokens", 0) for s in stats.values()),
            "per_replica": snaps,
        }
        # Fleet-wide latency percentiles from LOSSLESSLY merged
        # per-replica histograms (trace/histogram.py — every replica
        # bins into the same fixed edges, so the merge is exact;
        # averaging per-replica percentiles would be statistically
        # meaningless). Replicas running pre-histogram code simply
        # don't contribute.
        from batch_shipyard_tpu.trace.histogram import \
            LatencyHistogram
        for metric in ("ttft", "tpot"):
            merged = LatencyHistogram.merged(
                h for h in (LatencyHistogram.from_dict(
                    s.get(f"{metric}_hist")) for s in stats.values())
                if h is not None)
            if merged.count:
                pcts = merged.percentiles((50, 90, 99))
                agg[f"{metric}_ms"] = {p: pcts[f"p{p}"]
                                       for p in (50, 90, 99)}
                agg[f"{metric}_hist"] = merged.to_dict()
        # Fleet-wide speculative-decode acceptance (replicas running
        # a draft model report per-engine counters in their stats).
        proposed = sum(
            s.get("speculative", {}).get("proposed", 0)
            for s in stats.values())
        accepted = sum(
            s.get("speculative", {}).get("accepted", 0)
            for s in stats.values())
        if proposed:
            agg["speculative"] = {
                "proposed": proposed,
                "accepted": accepted,
                "acceptance_rate": accepted / proposed,
            }
        # Fleet-wide prefix-cache effectiveness: hit/total token sums
        # across replicas (token-level hit rate — exactly what each
        # replica reports, merged losslessly). Replicas with the
        # cache disabled simply don't contribute.
        prefix_reports = [s.get("prefix_cache") for s in stats.values()
                         if s.get("prefix_cache")]
        if prefix_reports:
            hit = sum(p.get("hit_tokens", 0) for p in prefix_reports)
            total = sum(p.get("total_prompt_tokens", 0)
                        for p in prefix_reports)
            agg["prefix_cache"] = {
                "lookups": sum(p.get("lookups", 0)
                               for p in prefix_reports),
                "hit_tokens": hit,
                "total_prompt_tokens": total,
                "hit_rate": hit / total if total else 0.0,
                "published_pages": sum(p.get("published_pages", 0)
                                       for p in prefix_reports),
                "evictions": sum(p.get("evictions", 0)
                                 for p in prefix_reports),
            }
        return agg


def _json_or_error(body: bytes) -> dict:
    try:
        return json.loads(body)
    except ValueError:
        return {"error": body.decode(errors="replace")[:400]}


def _is_timeout(exc: Exception) -> bool:
    """socket timeouts surface bare (TimeoutError) or wrapped in
    URLError(reason=timeout) depending on where in the request they
    strike."""
    if isinstance(exc, TimeoutError):
        return True
    return (isinstance(exc, urllib.error.URLError)
            and isinstance(exc.reason, TimeoutError))


def main() -> int:
    """Standalone fleet router:

        python -m batch_shipyard_tpu.models.router \\
            http://node0:8900 http://node1:8900 --port 8800
    """
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("urls", nargs="+",
                        help="Replica front end base URL(s)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8800)
    parser.add_argument("--health-interval", type=float, default=2.0)
    args = parser.parse_args()
    router = ServingRouter(args.urls, host=args.host, port=args.port,
                           health_interval=args.health_interval)
    router.start()
    print(f"router listening on {router.url} over "
          f"{len(args.urls)} replica(s)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        router.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

"""Goodput interval event log: typed, append-only, store-persisted.

Two producer surfaces feed one log:

  * **Store-backed** (`emit` / `span` / `query`): used by components
    that hold a StateStore handle — the node agent, pool autoscale,
    jobs manager, monitor. Events land in TABLE_GOODPUT partitioned by
    pool, RowKey = timestamp with a microsecond collision bump (the
    perf-table scheme, agent/perf.py).

  * **Process-local** (`record` / `phase`): used by workload code that
    runs INSIDE a task subprocess (train/serve/checkpoint) and has no
    store. Events append as JSON lines to $SHIPYARD_GOODPUT_FILE (the
    agent exports it into every task env); after the task finishes the
    agent ingests the file into the store with the task's identity
    attached (`ingest_local_events`). With no file configured the
    recorder is a no-op, so workloads run unchanged outside pools.

Event dict schema (what accounting.py consumes)::

    {"kind": str, "start": float, "end": float,
     "pool_id"/"job_id"/"task_id"/"node_id": Optional[str],
     "attrs": {...}}   # e.g. step_start/step_end/tokens counters

Emission is best-effort by design: a failed goodput write must never
fail the work being measured.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
import uuid
from typing import Any, Iterator, Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Env var the agent exports into every task: process-local recorder
# sink (JSONL), ingested into the store post-task.
GOODPUT_FILE_ENV = "SHIPYARD_GOODPUT_FILE"

# Node lifecycle
NODE_PROVISIONING = "provisioning"     # slice allocation / resize
NODE_PREP = "nodeprep"                 # node prep (boot -> ready)
NODE_IDLE = "idle"                     # ready but running nothing
NODE_PREEMPTED = "preempted"           # provider reclaim -> recovered

# Task lifecycle
TASK_QUEUED = "queued"                 # submit -> first claim
TASK_IMAGE_PULL = "image_pull"         # image provisioning on node
TASK_CONTAINER_START = "container_start"
TASK_RUNNING = "running"               # task process start -> exit
TASK_RETRY = "retry"                   # instantaneous requeue marker
TASK_BACKOFF = "backoff"               # retry supervisor's deliberate
                                       # requeue delay (requeue ->
                                       # not_before); its own badput
                                       # category so retry waits never
                                       # land in "unaccounted"
# Cooperative preemption (scheduler-driven; agent/preemption.py):
TASK_PREEMPT_NOTICE = "preempt_notice"   # instantaneous: the sweep
                                         # stamped a preempt request
                                         # on a running victim
TASK_PREEMPT_EXIT = "preempt_exit"       # instantaneous: the victim
                                         # drained, committed, and
                                         # exited EXIT_PREEMPTED
TASK_PREEMPT_RECOVERY = "preempt_recovery"  # interval: preempted exit
                                         # -> re-claim; priced as the
                                         # preemption_recovery badput
                                         # leg (arxiv 2502.06982) —
                                         # emitted by the CLAIM side
                                         # once the wait has elapsed,
                                         # like TASK_BACKOFF
# Forcible eviction (the escalation ladder past the cooperative
# notice; agent/node_agent.py _sweep_preemptions + _enforce_eviction):
TASK_EVICTED = "evicted"                 # instantaneous: the victim
                                         # ignored its notice past
                                         # preempt_grace_seconds and
                                         # was hard-killed; requeued
                                         # at full budget
TASK_EVICTION_RECOVERY = "eviction_recovery"  # interval: evicted exit
                                         # -> re-claim; priced as the
                                         # "eviction" badput leg,
                                         # distinct from
                                         # preemption_recovery (an
                                         # eviction also pays the
                                         # steps replayed since the
                                         # pre-notice barrier) —
                                         # emitted by the CLAIM side
                                         # once the wait has elapsed
# Elastic gang resize (instantaneous marker: a broken gang re-formed
# at a new size; attrs carry old_size/new_size/live_nodes).
GANG_RESIZE = "gang_resize"
# Cross-pool gang migration (federation/federation.py elastic
# evaluator): INTERVAL from when the gang was first starved/preempted
# in its pool to the re-target completing on the sibling pool —
# priced as the "migration" badput leg. Emitted at migration time
# (the window has fully elapsed; never future-dated).
GANG_MIGRATE = "gang_migrate"
# Control-plane legs (state/resilient.py + agent crash-restart
# adoption):
STORE_OUTAGE = "store_outage"     # interval: first failed store op ->
                                  # first successful one; emitted by
                                  # the resilient wrapper on latch
                                  # close with the journal-replay
                                  # counts in attrs — the exact
                                  # partition of the outage window,
                                  # priced as the "store_outage"
                                  # badput leg
TASK_EXPANSION = "expansion"      # interval: a server-side task-
                                  # factory expansion run (generator
                                  # row claimed -> all chunks
                                  # materialized) on the expander
                                  # leader — scheduling machinery, so
                                  # its own badput leg next to
                                  # "queueing"; attrs carry the
                                  # submit-leg breakdown (expanded,
                                  # entity/enqueue/encode seconds)
TASK_ADOPTION = "adoption"        # interval: the crashed agent's last
                                  # heartbeat -> the restarted agent
                                  # re-adopting the still-running
                                  # task (agent/node_agent.py
                                  # _adopt_restart_state) — the
                                  # control-plane gap an agent crash
                                  # costs, priced as the "adoption"
                                  # badput leg; the task itself never
                                  # stopped

# Program phases (emitted from inside the workload process)
PROGRAM_COMPILE = "compile"            # jit compile / warm-up steps
PROGRAM_WARMUP = "warmup"              # serving engine warm-up
PROGRAM_STEP_WINDOW = "step_window"    # productive steps; attrs carry
                                       # step_start/step_end/tokens
PROGRAM_CHECKPOINT_SAVE = "checkpoint_save"
PROGRAM_CHECKPOINT_RESTORE = "checkpoint_restore"
# Overlapped persist of the async save pipeline
# (workloads/checkpoint.AsyncCheckpointManager): runs in a background
# writer thread UNDER live step windows, so the accounting sweep
# scores it productive-overlapped rather than checkpoint badput.
PROGRAM_CHECKPOINT_ASYNC = "checkpoint_async"
PROGRAM_EVAL = "eval"
# Serving-tier recovery (models/router.py mid-stream failover):
# INTERVAL from detecting a dead/draining replica mid-decode to the
# resumed stream opening on a sibling — the re-prefill of
# prompt+emitted tokens plus drain-abandoned decode work, priced as
# the "serving_recovery" badput leg; attrs carry request_id and
# resumed_tokens.
SERVE_RECOVERY = "serve_recovery"

EVENT_KINDS = frozenset({
    NODE_PROVISIONING, NODE_PREP, NODE_IDLE, NODE_PREEMPTED,
    TASK_QUEUED, TASK_IMAGE_PULL, TASK_CONTAINER_START, TASK_RUNNING,
    TASK_RETRY, TASK_BACKOFF,
    TASK_PREEMPT_NOTICE, TASK_PREEMPT_EXIT, TASK_PREEMPT_RECOVERY,
    TASK_EVICTED, TASK_EVICTION_RECOVERY,
    GANG_RESIZE, GANG_MIGRATE, STORE_OUTAGE, TASK_ADOPTION,
    TASK_EXPANSION,
    PROGRAM_COMPILE, PROGRAM_WARMUP, PROGRAM_STEP_WINDOW,
    PROGRAM_CHECKPOINT_SAVE, PROGRAM_CHECKPOINT_RESTORE,
    PROGRAM_CHECKPOINT_ASYNC, PROGRAM_EVAL,
    SERVE_RECOVERY,
})


def iso_to_epoch(value: Optional[str]) -> Optional[float]:
    """Parse the framework's UTC ISO timestamps (util
    datetime_utcnow_iso) to epoch seconds; None on junk."""
    if not value:
        return None
    import datetime
    try:
        return datetime.datetime.strptime(
            value, "%Y-%m-%dT%H:%M:%S.%fZ").replace(
            tzinfo=datetime.timezone.utc).timestamp()
    except ValueError:
        try:
            return datetime.datetime.fromisoformat(
                value.replace("Z", "+00:00")).timestamp()
        except ValueError:
            return None


# ----------------------------- store-backed ----------------------------

def emit(store: StateStore, pool_id: str, kind: str, *,
         job_id: Optional[str] = None, task_id: Optional[str] = None,
         node_id: Optional[str] = None,
         start: Optional[float] = None, end: Optional[float] = None,
         attrs: Optional[dict] = None,
         trace_id: Optional[str] = None,
         span_id: Optional[str] = None) -> None:
    """Append one event. Instantaneous events omit ``end`` (it
    defaults to ``start``). ``trace_id``/``span_id`` join the event to
    a submission's distributed trace (trace/): schema-compatible —
    absent ids are legacy rows, and the accounting partition ignores
    them entirely. Never raises: goodput accounting is an observer,
    not a participant."""
    if kind not in EVENT_KINDS:
        logger.warning("unknown goodput event kind %r dropped", kind)
        return
    try:
        # Coercion INSIDE the guard: start/end/attrs may come from a
        # task-written JSONL line (ingest path) and junk there must
        # drop the event, never raise into the agent's task flow.
        ts = time.time() if start is None else float(start)
        entity = {
            "kind": kind, "job_id": job_id, "task_id": task_id,
            "node_id": node_id, "start": ts,
            "end": ts if end is None else float(end),
            "attrs": dict(attrs or {}),
        }
        if trace_id:
            entity["trace_id"] = str(trace_id)
            if span_id:
                entity["span_id"] = str(span_id)
        # RowKey: timestamp (sortable, the perf-table convention) + a
        # uuid suffix — unlike agent/perf.py's deterministic keys, no
        # collision-bump loop is needed.
        row_key = f"{ts:017.6f}${uuid.uuid4().hex[:8]}"
        store.insert_entity(names.TABLE_GOODPUT, pool_id, row_key,
                            entity)
    except Exception:  # noqa: BLE001 - observer must not fail work
        logger.debug("goodput emit failed", exc_info=True)


@contextlib.contextmanager
def span(store: StateStore, pool_id: str, kind: str, *,
         job_id: Optional[str] = None, task_id: Optional[str] = None,
         node_id: Optional[str] = None,
         attrs: Optional[dict] = None,
         trace_id: Optional[str] = None,
         span_id: Optional[str] = None) -> Iterator[dict]:
    """Time a block as one interval event. Yields the attrs dict so
    the body can add counters before the event is emitted."""
    out_attrs = dict(attrs or {})
    start = time.time()
    try:
        yield out_attrs
    finally:
        emit(store, pool_id, kind, job_id=job_id, task_id=task_id,
             node_id=node_id, start=start, end=time.time(),
             attrs=out_attrs, trace_id=trace_id, span_id=span_id)


def query(store: StateStore, pool_id: str,
          job_id: Optional[str] = None,
          task_id: Optional[str] = None,
          trace_id: Optional[str] = None) -> list[dict]:
    """Events of a pool (optionally one job/task/trace), sorted by
    start."""
    out = []
    for row in store.query_entities(names.TABLE_GOODPUT,
                                    partition_key=pool_id):
        if job_id is not None and row.get("job_id") != job_id:
            continue
        if task_id is not None and row.get("task_id") != task_id:
            continue
        if trace_id is not None and row.get("trace_id") != trace_id:
            continue
        out.append(row)
    return sorted(out, key=lambda e: (e.get("start", 0.0),
                                      e.get("end", 0.0)))


def prune(store: StateStore, pool_id: str,
          older_than_seconds: float) -> int:
    """Retention sweep: delete events that ENDED more than
    ``older_than_seconds`` ago. The log is append-only by design;
    without pruning a long-lived pool's accounting scans grow with
    fleet age. Returns the number of rows removed."""
    cutoff = time.time() - older_than_seconds
    removed = 0
    for row in list(store.query_entities(names.TABLE_GOODPUT,
                                         partition_key=pool_id)):
        if float(row.get("end", row.get("start", 0.0))) < cutoff:
            try:
                store.delete_entity(names.TABLE_GOODPUT, pool_id,
                                    row["_rk"])
                removed += 1
            except Exception:  # noqa: BLE001 - best effort
                logger.debug("goodput prune failed", exc_info=True)
    return removed


# ---------------------------- process-local ----------------------------

def local_events_path() -> Optional[str]:
    """The JSONL sink for THIS process, or None (recorder disabled)."""
    return os.environ.get(GOODPUT_FILE_ENV) or None


def record(kind: str, start: float, end: Optional[float] = None,
           **attrs: Any) -> None:
    """Process-local emit: append one JSONL event to
    $SHIPYARD_GOODPUT_FILE. The task's exported trace context
    ($SHIPYARD_TRACE_*) is attached automatically so program-phase
    intervals join the submission's distributed trace. No-op when
    unset; never raises."""
    path = local_events_path()
    if path is None:
        return
    event = {"kind": kind, "start": float(start),
             "end": float(start if end is None else end),
             "attrs": attrs}
    from batch_shipyard_tpu.trace import context as trace_ctx
    ctx = trace_ctx.TraceContext.from_env()
    if ctx is not None:
        event["trace_id"] = ctx.trace_id
        event["span_id"] = ctx.span_id
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(event) + "\n")
    except OSError:
        logger.debug("goodput local record failed", exc_info=True)


@contextlib.contextmanager
def phase(kind: str, **attrs: Any) -> Iterator[dict]:
    """Time a block as a process-local event; yields the attrs dict
    (mutable — step/token counters get filled in by the body)."""
    out_attrs = dict(attrs)
    start = time.time()
    try:
        yield out_attrs
    finally:
        record(kind, start, time.time(), **out_attrs)


def ingest_local_events(store: StateStore, pool_id: str, path: str, *,
                        job_id: Optional[str] = None,
                        task_id: Optional[str] = None,
                        node_id: Optional[str] = None) -> int:
    """Fold a task's process-local JSONL into the store, attaching the
    task's identity. Returns the number of events ingested; the file
    is removed on success so retries don't double-count."""
    if not os.path.exists(path):
        return 0
    count = 0
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                kind = event.get("kind")
                if kind not in EVENT_KINDS:
                    continue
                # The file's contents are task-controlled: coerce the
                # numeric fields here and skip junk lines so one bad
                # event never poisons the ingest (or, downstream, the
                # accounting of the whole pool).
                try:
                    start = float(event.get("start"))
                    end = float(event.get("end", start))
                except (TypeError, ValueError):
                    continue
                attrs = event.get("attrs")
                if not isinstance(attrs, dict):
                    attrs = {}
                trace_id = event.get("trace_id")
                emit(store, pool_id, kind, job_id=job_id,
                     task_id=task_id, node_id=node_id,
                     start=start, end=end, attrs=attrs,
                     trace_id=(str(trace_id) if trace_id else None),
                     span_id=(str(event["span_id"])
                              if trace_id and event.get("span_id")
                              else None))
                count += 1
        os.remove(path)
    except OSError:
        logger.debug("goodput ingest failed for %s", path,
                     exc_info=True)
    return count

"""Input pipeline: sharded datasets with background host->device
prefetch.

SURVEY.md section 7 flags input-pipeline parity as a hard part of the
ResNet/ImageNet baseline ("orchestrator must make data locality
configurable"). This loader covers the workload side:

  - ``ShardedDataset``: enumerate .npy/.npz shard files from a local
    directory or the state store (staged by input_data/gcsfuse),
    partitioned across jax processes (each pod worker reads only its
    slice — data parallel by construction);
  - ``prefetch_to_device``: a background thread that stages the next
    batches onto the device (with the mesh sharding applied) while the
    current step computes, hiding host->HBM transfer latency — the
    tf.data.prefetch analog without TensorFlow.

Synthetic mode keeps benches and tests hermetic.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
from typing import Callable, Iterator, Optional

import jax
import numpy as np

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class ShardedDataset:
    """Iterate batches from .npy/.npz shards, partitioned across
    processes."""

    def __init__(self, shard_dir: str, batch_size: int,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 loop: bool = True, seed: int = 0) -> None:
        self.shard_dir = shard_dir
        self.batch_size = batch_size
        self.loop = loop
        self.seed = seed
        pidx = (process_index if process_index is not None
                else jax.process_index())
        pcnt = (process_count if process_count is not None
                else jax.process_count())
        shards = sorted(
            os.path.join(shard_dir, name)
            for name in os.listdir(shard_dir)
            if name.endswith((".npy", ".npz")))
        if not shards:
            raise ValueError(f"no .npy/.npz shards in {shard_dir}")
        # Round-robin shard assignment across pod workers.
        self.shards = shards[pidx::pcnt]
        if not self.shards:
            raise ValueError(
                f"process {pidx}/{pcnt}: no shards assigned "
                f"({len(shards)} total)")

    def _load(self, path: str) -> dict[str, np.ndarray]:
        if path.endswith(".npz"):
            with np.load(path) as data:
                return {k: data[k] for k in data.files}
        return {"data": np.load(path)}

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        rng = np.random.RandomState(self.seed)
        epoch = 0
        while True:
            order = list(self.shards)
            rng.shuffle(order)
            carry: dict[str, list] = collections.defaultdict(list)
            carried = 0
            for path in order:
                arrays = self._load(path)
                n = len(next(iter(arrays.values())))
                start = 0
                while start < n:
                    take = min(self.batch_size - carried, n - start)
                    for key, arr in arrays.items():
                        carry[key].append(arr[start:start + take])
                    carried += take
                    start += take
                    if carried == self.batch_size:
                        yield {k: np.concatenate(v)
                               for k, v in carry.items()}
                        carry = collections.defaultdict(list)
                        carried = 0
            epoch += 1
            if not self.loop:
                return


def synthetic_batches(make_batch: Callable[[int], dict],
                      ) -> Iterator[dict]:
    """Infinite synthetic batches (hermetic benches)."""
    step = 0
    while True:
        yield make_batch(step)
        step += 1


def prefetch_to_device(batches: Iterator[dict], sharding,
                       depth: int = 2) -> Iterator[dict]:
    """Stage upcoming batches onto device(s) on a background thread.

    sharding: a jax Sharding (or pytree of them matching the batch
    dict) applied via device_put — on a mesh this lands each host's
    slice directly in the right HBM shards.
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    q: queue.Queue = queue.Queue(maxsize=depth)
    _SENTINEL = object()

    def producer():
        try:
            for batch in batches:
                placed = jax.device_put(batch, sharding)
                q.put(placed)
        except Exception as exc:  # noqa: BLE001
            q.put(exc)
            return
        q.put(_SENTINEL)

    thread = threading.Thread(target=producer, daemon=True,
                              name="prefetch")
    thread.start()
    while True:
        item = q.get()
        if item is _SENTINEL:
            return
        if isinstance(item, Exception):
            raise item
        yield item


def write_synthetic_imagenet_shards(
        out_dir: str, num_shards: int = 4, per_shard: int = 512,
        image_size: int = 64, num_classes: int = 1000,
        seed: int = 0) -> list[str]:
    """Materialize synthetic ImageNet-shaped .npz shards (tooling for
    recipes/tests; real data lands here via input_data or gcsfuse)."""
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.RandomState(seed)
    paths = []
    for idx in range(num_shards):
        path = os.path.join(out_dir, f"shard_{idx:05d}.npz")
        np.savez(
            path,
            images=rng.randint(
                0, 255, (per_shard, image_size, image_size, 3),
                dtype=np.uint8),
            labels=rng.randint(0, num_classes, (per_shard,),
                               dtype=np.int32))
        paths.append(path)
    return paths

"""Secret indirection: resolve credential values from secret stores.

Reference analog: convoy/keyvault.py — any credential may be a KeyVault
secret id (parse_secret_ids :196, get_secret :176) and the whole
credentials file can live in KeyVault (:71). TPU-native mapping: GCP
Secret Manager is the cloud provider; ``env`` and ``file`` providers
cover air-gapped/test use. A value of the form::

    secret://<provider>/<name>

anywhere a credential string is accepted resolves through this module.
"""

from __future__ import annotations

import os
import re
from typing import Optional

import yaml

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_SECRET_RE = re.compile(r"^secret://(?P<provider>[a-z_]+)/(?P<name>.+)$")


class SecretResolutionError(RuntimeError):
    pass


def is_secret_id(value: object) -> bool:
    return isinstance(value, str) and bool(_SECRET_RE.match(value))


def parse_secret_id(value: str) -> tuple[str, str]:
    match = _SECRET_RE.match(value)
    if not match:
        raise SecretResolutionError(f"not a secret id: {value!r}")
    return match.group("provider"), match.group("name")


def _resolve_env(name: str) -> str:
    value = os.environ.get(name)
    if value is None:
        raise SecretResolutionError(f"env secret {name!r} not set")
    return value


def _resolve_file(name: str, secrets_file: Optional[str]) -> str:
    if not secrets_file:
        raise SecretResolutionError(
            "file secret provider requires credentials.secrets.file")
    with open(secrets_file, "r", encoding="utf-8") as fh:
        data = yaml.safe_load(fh) or {}
    if name not in data:
        raise SecretResolutionError(
            f"secret {name!r} not in {secrets_file}")
    return str(data[name])


def _resolve_gcp(name: str, project: Optional[str]) -> str:
    """GCP Secret Manager via gcloud (network path; gated)."""
    import shutil
    if shutil.which("gcloud") is None:
        raise SecretResolutionError(
            "gcloud CLI required for gcp_secret_manager provider")
    cmd = ["gcloud", "secrets", "versions", "access", "latest",
           f"--secret={name}"]
    if project:
        cmd.append(f"--project={project}")
    rc, out, err = util.subprocess_capture(cmd)
    if rc != 0:
        raise SecretResolutionError(
            f"gcloud secret access failed: {err.strip()}")
    return out.rstrip("\n")


def resolve_secret(value: str, secrets_file: Optional[str] = None,
                   project: Optional[str] = None) -> str:
    """Resolve one secret:// id to its value."""
    provider, name = parse_secret_id(value)
    if provider == "env":
        return _resolve_env(name)
    if provider == "file":
        return _resolve_file(name, secrets_file)
    if provider == "gcp_secret_manager":
        return _resolve_gcp(name, project)
    raise SecretResolutionError(f"unknown secret provider {provider!r}")


def resolve_config_secrets(config: dict,
                           secrets_file: Optional[str] = None,
                           project: Optional[str] = None) -> dict:
    """Deep-resolve every secret:// string in a config dict."""
    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        if is_secret_id(node):
            return resolve_secret(node, secrets_file, project)
        return node
    return walk(config)


# ------------------------- store (keyvault add) -------------------------

def store_secret(secret_id: str, value: str,
                 secrets_file: Optional[str] = None,
                 project: Optional[str] = None) -> None:
    """Write a secret value under a secret:// id (the reference's
    `keyvault add` half, convoy/keyvault.py:112 store_credentials /
    :176 get_secret's sibling). Providers: ``file`` updates the YAML
    secrets file in place; ``gcp_secret_manager`` creates the secret
    (idempotently) and adds a new version via gcloud with the value
    on stdin — never in argv, so it cannot leak through process
    listings. The ``env`` provider is read-only by nature."""
    provider, name = parse_secret_id(secret_id)
    if provider == "env":
        raise SecretResolutionError(
            "env secrets are read-only (set the variable in the "
            "environment instead)")
    if provider == "file":
        if not secrets_file:
            raise SecretResolutionError(
                "file secret provider requires credentials.secrets."
                "file")
        data = {}
        if os.path.exists(secrets_file):
            with open(secrets_file, "r", encoding="utf-8") as fh:
                data = yaml.safe_load(fh) or {}
        data[name] = value
        tmp = secrets_file + ".tmp"
        with open(tmp, "w", encoding="utf-8",
                  opener=lambda p, f: os.open(p, f, 0o600)) as fh:
            yaml.safe_dump(data, fh, default_flow_style=False)
        os.replace(tmp, secrets_file)
        return
    if provider == "gcp_secret_manager":
        import shutil
        if shutil.which("gcloud") is None:
            raise SecretResolutionError(
                "gcloud CLI required for gcp_secret_manager provider")
        base = ["gcloud", "secrets"]
        if project:
            base.append(f"--project={project}")
        # Idempotent create; failure is fine when it already exists.
        util.subprocess_capture(
            base[:2] + ["create", name,
                        "--replication-policy=automatic"] + base[2:])
        rc, _out, err = util.subprocess_capture(
            base[:2] + ["versions", "add", name, "--data-file=-"] +
            base[2:], stdin_data=value)
        if rc != 0:
            raise SecretResolutionError(
                f"gcloud secret store failed: {err.strip()}")
        return
    raise SecretResolutionError(f"unknown secret provider {provider!r}")


def store_credentials_config(secret_id: str, credentials: dict,
                             secrets_file: Optional[str] = None,
                             project: Optional[str] = None) -> None:
    """Store an entire credentials.yaml under one secret id (the
    reference keeps whole credential files in KeyVault,
    convoy/keyvault.py:71/:112); fetch back with
    fetch_credentials_config."""
    store_secret(secret_id, yaml.safe_dump(credentials),
                 secrets_file=secrets_file, project=project)


def fetch_credentials_config(secret_id: str,
                             secrets_file: Optional[str] = None,
                             project: Optional[str] = None) -> dict:
    """Fetch a whole credentials.yaml stored via
    store_credentials_config."""
    raw = resolve_secret(secret_id, secrets_file=secrets_file,
                         project=project)
    data = yaml.safe_load(raw)
    if not isinstance(data, dict):
        raise SecretResolutionError(
            f"secret {secret_id} does not hold a credentials mapping")
    return data

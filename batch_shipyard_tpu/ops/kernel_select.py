"""Silicon-validation-gated kernel dispatch.

tools/tpu_checks.py --write-marker persists per-kernel oracle results
as KERNEL_VALIDATION.json (repo root, or $SHIPYARD_KERNEL_VALIDATION).
Ops whose Pallas paths cannot be exercised by the CPU CI suite gate
their impl='auto' on that marker: the fast path turns itself on the
moment it is proven on the chip — and never before. This is the
durable half of the VERDICT r4 "flip auto to flash on pass" order,
shared by ops/ring_attention.py and ops/chunked_loss.py.
"""

from __future__ import annotations

import json
import os
import pathlib

import jax

MARKER_ENV = "SHIPYARD_KERNEL_VALIDATION"
DEFAULT_MARKER = (pathlib.Path(__file__).resolve().parents[2]
                  / "KERNEL_VALIDATION.json")


def kernel_validation(path: str | os.PathLike | None = None) -> dict:
    """Load the validation marker ({check_name: {ok, backend, ...}});
    {} when absent/unreadable — absence of proof means 'not proven'."""
    path = path or os.environ.get(MARKER_ENV) or DEFAULT_MARKER
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        return data if isinstance(data, dict) else {}
    except (OSError, ValueError):
        return {}


def kernel_validated(name: str) -> bool:
    """True when check `name` passed ON A TPU BACKEND. A pass recorded
    on cpu (interpret mode) deliberately does not count — the point of
    the marker is Mosaic-on-silicon proof."""
    record = kernel_validation().get(name, {})
    return (isinstance(record, dict) and bool(record.get("ok"))
            and record.get("backend") == "tpu")


def resolve_auto(name: str, pallas_impl: str = "pallas",
                 fallback: str = "xla") -> str:
    """impl='auto' resolution: the validated Pallas path on a TPU
    backend, the fallback everywhere else."""
    if jax.default_backend() == "tpu" and kernel_validated(name):
        return pallas_impl
    return fallback

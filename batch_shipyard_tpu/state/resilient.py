"""Store-outage ride-through: bounded retry + a local write-ahead
journal.

A sustained state-store outage (GCS unreachable for minutes) used to
have no ride-through path: chaos injected only per-op faults, and a
real outage would fail claims, drop goodput/trace intervals, and
eventually kill running tasks through the error paths. This wrapper
classifies every store op into one of two lanes:

  * **critical** — claims, state transitions, queue traffic, object
    IO: correctness depends on them, so they block and retry with
    jittered exponential backoff until the store answers (or a
    configured outage ceiling passes). A running task never dies
    because the control plane blinked.

  * **advisory** — goodput events, trace spans, node heartbeat /
    health publishes: observers, not participants. During an outage
    they append to a per-node local write-ahead journal (JSONL,
    fsynced appends, the ``_atomic_write`` discipline for rewrites)
    and are replayed IN ORDER on recovery — so a multi-minute outage
    loses zero accounting intervals, and the goodput partition stays
    exact across it.

The first transport failure latches an **outage**; while latched,
advisory ops go straight to the journal (no per-op timeout tax) and
one advisory op per ``probe_interval`` probes the store live. The
first success — probe or critical retry — replays the journal,
closes the latch, and prices the outage window as one
``store_outage`` goodput event with the exact [first-failure,
first-success] interval (the new badput category).

Replay is idempotent: entries carry the caller-minted row keys, so a
crash mid-replay re-inserts into ``EntityExistsError`` (treated as
success) instead of double-counting. The journal file survives agent
restarts; a restarted agent drains its predecessor's backlog before
anything else is lost.

Transport vs semantic failures: the store's own contract errors
(NotFoundError, EtagMismatchError, EntityExistsError,
PreconditionFailedError, LeaseLostError) are SUCCESSFUL round trips
and propagate untouched — retrying them would corrupt optimistic-
concurrency protocols. Lease ops are deliberately NOT wrapped at
all: a leader partitioned from the store must fail its renewal and
abdicate honestly (state/leases.py), not have this wrapper pretend
the lease extended.

**Group commit** (the WAL's ordered-journal discipline turned into a
write-combining throughput lane): inside a ``group_commit()`` block —
or always, when constructed with ``group_commit_rows > 0`` — the two
batch write ops (``insert_entities`` / ``put_messages``) buffer into
an ordered in-memory journal instead of hitting the backend per call.
Adjacent calls against the same (op, target) coalesce into ONE
backend round trip; the buffer flushes when it reaches the row cap,
when the oldest buffered write exceeds the flush interval, when ANY
other managed op runs (flush-on-read: a reader can never observe the
store ahead of writes this wrapper already accepted), and on block
exit. Semantics are preserved at the flush boundary: semantic errors
(EntityExistsError et al) surface from the flushing call; per-key
ordering holds because entries never reorder and only coalesce into
the journal tail. A transport fault mid-batch switches that entry to
per-row idempotent repair — the replay discipline: re-insert every
row, treating EntityExistsError as an already-applied success — so a
faulted batch is always driven to fully-applied, never left torn.
Queue batches retry whole (duplicates are the queue contract's
at-least-once, which agents already tolerate).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import zlib
from typing import Any, Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, LeaseLostError,
    NotFoundError, PreconditionFailedError)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Ops the wrapper manages. Lease ops are intentionally absent (see
# module docstring); everything else delegates raw.
_MANAGED_OPS = frozenset({
    "put_object", "get_object", "get_object_meta", "delete_object",
    "list_objects", "insert_entity", "upsert_entity", "merge_entity",
    "get_entity", "query_entities", "delete_entity",
    "insert_entities", "put_message", "put_messages", "get_messages",
    "delete_message", "update_message", "queue_length",
    "count_entities_by",
    "put_object_stream", "get_object_stream",
})

# Batch write ops the group-commit layer may buffer (everything else
# flushes the buffer first, so ordering across op kinds is preserved).
_GROUP_COMMIT_OPS = frozenset({"insert_entities", "put_messages"})

# Successful round trips wearing exception suits: never retried,
# never journaled, always propagated.
_SEMANTIC_ERRORS = (NotFoundError, PreconditionFailedError,
                    EntityExistsError, EtagMismatchError,
                    LeaseLostError)

_JOURNALED_ETAG = "journaled"


class StoreOutageError(RuntimeError):
    """A critical op exhausted the outage ceiling."""


class ResilientStore:
    """StateStore wrapper: critical ops retry through outages,
    advisory ops ride a local WAL. Transparent pass-through while the
    store is healthy."""

    def __init__(self, inner, journal_path: str,
                 pool_id: Optional[str] = None,
                 node_id: Optional[str] = None,
                 retry_base: float = 0.25, retry_cap: float = 5.0,
                 max_outage_seconds: float = 900.0,
                 probe_interval: float = 1.0,
                 group_commit_rows: int = 0,
                 group_commit_interval: float = 0.05,
                 stop_check=None) -> None:
        self._inner = inner
        self._journal_path = journal_path
        self._pool_id = pool_id
        self._node_id = node_id
        self._retry_base = retry_base
        self._retry_cap = retry_cap
        self._max_outage_seconds = max_outage_seconds
        self._probe_interval = probe_interval
        self._stop_check = stop_check or (lambda: False)
        self._lock = threading.RLock()
        self._journal: list[dict] = []
        self._outage_since: Optional[float] = None
        self._last_probe = 0.0
        self._emitting = False
        # Replay is single-flight: one thread drains the backlog,
        # concurrent triggers return immediately (their entries are
        # picked up by the in-progress drain's tail scan).
        self._replay_lock = threading.Lock()
        # The entry being applied RIGHT NOW — coalescing must never
        # merge into it (the payload could be half-serialized into
        # the in-flight store call, and the pop would drop the
        # merged-in newer values without ever applying them).
        self._replay_inflight: Optional[dict] = None
        # Per-thread retry ceilings (``bounded``): lets latency-
        # sensitive callers (the agent heartbeat thread) cap how long
        # a critical op may block in the outage-retry loop.
        self._tls = threading.local()
        self.outage_seconds_total = 0.0
        self.outages_total = 0
        # Group-commit state. ``_gc_ambient_rows > 0`` turns the lane
        # on for the wrapper's whole lifetime; ``group_commit()``
        # blocks turn it on lexically. The flush lock is re-entrant
        # because a flush can recover an outage, which emits a goodput
        # event through SELF (see _emit_outage_event) — that advisory
        # write must not deadlock on its own flush-on-write.
        self._gc_ambient_rows = max(0, int(group_commit_rows))
        self._gc_interval = group_commit_interval
        self._gc_depth = 0
        self._gc_ctx_rows = 0
        self._gc_ctx_interval: Optional[float] = None
        self._gc_buffer: list[dict] = []
        self._gc_rows = 0
        self._gc_opened = 0.0
        self._gc_flush_lock = threading.RLock()
        self.group_commits_total = 0
        self.group_commit_rows_total = 0
        self.group_commit_coalesced_total = 0
        self._load_journal()

    # ---------------------------- delegation ---------------------------

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in _MANAGED_OPS and callable(attr):
            def managed(*args, **kwargs):
                return self._call(name, attr, args, kwargs)
            return managed
        return attr

    # --------------------------- classification ------------------------

    @staticmethod
    def _is_advisory(op: str, args: tuple) -> bool:
        """Goodput / trace appends and node heartbeat-ish publishes:
        observers whose loss would be an accounting hole but whose
        latency must never block (or fail) the work being measured."""
        if op not in ("insert_entity", "upsert_entity",
                      "merge_entity") or not args:
            return False
        table = args[0]
        if table in (names.TABLE_GOODPUT, names.TABLE_TRACE):
            return True
        # Node-entity publishes (heartbeat_at, health, state): stale
        # values self-repair on the next periodic beat, and the
        # in-order replay leaves the newest journaled beat last.
        return table == names.TABLE_NODES and op in ("merge_entity",
                                                     "upsert_entity")

    # ------------------------------- calls -----------------------------

    def _call(self, op: str, attr, args: tuple, kwargs: dict) -> Any:
        self._maybe_replay_backlog()
        if op in _GROUP_COMMIT_OPS and self._group_commit_active():
            return self._group_commit_buffer(op, args, kwargs)
        if self._gc_buffer:
            # Flush-on-read (and on any unbuffered write): no managed
            # op may observe — or order itself against — the backend
            # while accepted batch writes are still pending.
            self.flush_group_commit()
        if op == "put_object_stream":
            return self._critical_put_stream(attr, args, kwargs)
        if op == "get_object_stream":
            return self._critical_get_stream(attr, args, kwargs)
        if self._is_advisory(op, args):
            return self._advisory_call(op, attr, args, kwargs)
        return self._critical_call(op, attr, args, kwargs)

    def _critical_put_stream(self, attr, args: tuple,
                             kwargs: dict) -> Any:
        """put_object_stream with the critical ride-through (output
        uploads are what the completion path's classification hangs
        on — they must survive an outage exactly like the scalar
        puts). The chunk iterator is single-shot, and retrying a
        half-consumed iterator would commit a TORN object as whole —
        so the stream is spooled to an anonymous local temp file
        once, and every retry attempt re-streams from the spool."""
        import tempfile
        if len(args) >= 2:
            key, chunks, tail = args[0], args[1], args[2:]
        else:
            key = args[0] if args else kwargs.pop("key")
            chunks = kwargs.pop("chunks")
            tail = ()
        with tempfile.TemporaryFile() as spool:
            for block in chunks:
                spool.write(block)

            def attempt():
                spool.seek(0)

                def replay():
                    while True:
                        block = spool.read(1 << 20)
                        if not block:
                            return
                        yield block

                return attr(key, replay(), *tail, **kwargs)

            return self._critical_call("put_object_stream", attempt,
                                       (), {})

    def _critical_get_stream(self, attr, args: tuple,
                             kwargs: dict) -> Any:
        """get_object_stream with the critical ride-through on open +
        first chunk (backends implement it as a generator, so the
        bare call never fails — the first ``next`` is where missing
        keys and transport faults surface). Later chunks stream to
        the caller lazily and a mid-consumption transport failure
        still propagates: a half-yielded stream cannot be resumed
        without handing the consumer a torn prefix, and eagerly
        spooling would double the disk traffic of multi-GB
        transfers. Callers that need retried-to-completion reads use
        get_object."""
        import itertools

        def attempt():
            it = iter(attr(*args, **kwargs))
            try:
                first = next(it)
            except StopIteration:
                return iter(())
            return itertools.chain([first], it)

        return self._critical_call("get_object_stream", attempt,
                                   (), {})

    def _advisory_call(self, op: str, attr, args: tuple,
                       kwargs: dict) -> Any:
        with self._lock:
            latched = self._outage_since is not None
            backlog = bool(self._journal)
            probe = (latched and time.monotonic() - self._last_probe
                     >= self._probe_interval)
            if probe:
                self._last_probe = time.monotonic()
        if latched or backlog:
            # Journal FIRST, then (at most once per probe_interval)
            # probe the store with a cheap no-op read — recovery
            # replays the journal in order, this op included, so the
            # probe can never apply a newer event ahead of the
            # backlog it rode out the outage behind. The latch alone
            # is NOT enough: between latch-close and replay-drain a
            # direct write would race the replay of its own entity's
            # stale journaled value (heartbeat_at moving backwards),
            # so while ANY backlog exists the journal stays the
            # ordering authority and fresh advisories queue behind it.
            self._journal_append(op, args, kwargs)
            if probe:
                self._probe_recover()
            return _JOURNALED_ETAG
        try:
            return attr(*args, **kwargs)
        except _SEMANTIC_ERRORS:
            raise
        except Exception:  # noqa: BLE001 - transport failure
            self._latch_outage(op)
            self._journal_append(op, args, kwargs)
            return _JOURNALED_ETAG

    def _probe_recover(self) -> None:
        """One cheap metadata read against the raw store; any full
        round trip (a semantic miss included) proves recovery."""
        try:
            self._inner.get_object_meta("__outage-probe__")
        except _SEMANTIC_ERRORS:
            pass  # the store answered
        except Exception:  # noqa: BLE001 - still down
            return
        self._recovered()

    def outage_active(self) -> bool:
        """Observer view of the latch — lets loops with LOCAL duties
        (eviction kills, retention) decide to skip store-coordination
        work for a beat instead of discovering the outage by blocking
        inside it."""
        with self._lock:
            return self._outage_since is not None

    @contextlib.contextmanager
    def bounded(self, seconds: float):
        """Cap this thread's critical-op retries: inside the block a
        critical op that cannot complete before the deadline raises
        StoreOutageError instead of sleeping toward the global
        ``max_outage_seconds`` ceiling. For callers that multiplex
        unrelated duties on one thread (the agent heartbeat loop:
        heartbeats, lease renewal, eviction enforcement, retention) —
        a 900s blocking retry there would starve every other duty,
        the exact sleep-in-sweep class the lint rules forbid."""
        prior = getattr(self._tls, "deadline", None)
        self._tls.deadline = time.monotonic() + max(0.0, seconds)
        try:
            yield self
        finally:
            self._tls.deadline = prior

    # --------------------------- group commit --------------------------

    @contextlib.contextmanager
    def group_commit(self, max_rows: int = 4096,
                     flush_interval: Optional[float] = None):
        """Write-combining region: buffer ``insert_entities`` /
        ``put_messages`` and coalesce adjacent same-target calls into
        one backend round trip each. Flushes on the row cap, the
        flush interval, any other managed op, and block exit (errors
        from the final flush propagate out of the ``with``). Nested
        blocks inherit the outermost block's limits."""
        with self._lock:
            self._gc_depth += 1
            outermost = self._gc_depth == 1
            if outermost:
                self._gc_ctx_rows = max(1, int(max_rows))
                self._gc_ctx_interval = (
                    self._gc_interval if flush_interval is None
                    else flush_interval)
        try:
            yield self
        finally:
            with self._lock:
                self._gc_depth -= 1
                closing = self._gc_depth == 0
                if closing:
                    self._gc_ctx_rows = 0
                    self._gc_ctx_interval = None
            if closing:
                self.flush_group_commit()

    def _group_commit_active(self) -> bool:
        with self._lock:
            return self._gc_depth > 0 or self._gc_ambient_rows > 0

    def _gc_limits(self) -> tuple[int, float]:
        if self._gc_depth > 0:
            return (self._gc_ctx_rows,
                    self._gc_interval if self._gc_ctx_interval is None
                    else self._gc_ctx_interval)
        return self._gc_ambient_rows, self._gc_interval

    def group_commit_pending(self) -> int:
        """Buffered-but-unflushed row count (test observer)."""
        with self._lock:
            return self._gc_rows

    def _group_commit_buffer(self, op: str, args: tuple,
                             kwargs: dict) -> list:
        if op == "put_messages":
            target = args[0] if args else kwargs["queue"]
            items = list(args[1] if len(args) > 1
                         else kwargs["payloads"])
            delay = args[2] if len(args) > 2 \
                else kwargs.get("delay_seconds", 0.0)
            key = (op, target, delay)
        else:
            target = args[0] if args else kwargs["table"]
            items = list(args[1] if len(args) > 1 else kwargs["rows"])
            key = (op, target)
        if not items:
            return []
        do_flush = False
        with self._lock:
            now = time.monotonic()
            if self._gc_buffer and self._gc_buffer[-1]["key"] == key:
                # Adjacent same-(op, target[, delay]) calls combine.
                # Only the TAIL is a legal merge target — reaching
                # past a different-target entry would reorder writes
                # the caller sequenced deliberately (e.g. task rows
                # before their queue messages).
                self._gc_buffer[-1]["items"].extend(items)
                self.group_commit_coalesced_total += 1
            else:
                self._gc_buffer.append(
                    {"op": op, "key": key, "items": items})
            self._gc_rows += len(items)
            self.group_commit_rows_total += len(items)
            if not self._gc_opened:
                self._gc_opened = now
            rows_cap, interval = self._gc_limits()
            if self._gc_rows >= rows_cap or \
                    now - self._gc_opened >= interval:
                do_flush = True
        if do_flush:
            self.flush_group_commit()
        # Buffered writes cannot return backend etags / message ids;
        # placeholders keep the shape. (Submission ignores them — a
        # caller that needs real etags reads after the flush.)
        return [_JOURNALED_ETAG] * len(items)

    def flush_group_commit(self) -> None:
        """Apply the buffered entries IN ORDER. Transport faults on
        an entity batch demote that entry to per-row idempotent
        repair (EntityExistsError == already applied — the WAL replay
        discipline), so a faulted batch always ends fully applied,
        never torn. If even the repair path exhausts the outage
        ceiling, every unapplied entry is re-queued at the FRONT of
        the buffer before the error propagates — accepted writes are
        never silently dropped. Semantic errors apply the remaining
        entries first, then the first one raises (deferred-error
        surfacing at the flush boundary)."""
        with self._gc_flush_lock:
            with self._lock:
                entries = self._gc_buffer
                self._gc_buffer = []
                self._gc_rows = 0
                self._gc_opened = 0.0
            if not entries:
                return
            first_semantic: Optional[BaseException] = None
            for idx, entry in enumerate(entries):
                try:
                    self._gc_apply(entry)
                except _SEMANTIC_ERRORS as exc:
                    if first_semantic is None:
                        first_semantic = exc
                except Exception:
                    with self._lock:
                        remaining = entries[idx:]
                        self._gc_buffer[:0] = remaining
                        self._gc_rows += sum(len(e["items"])
                                             for e in remaining)
                        if not self._gc_opened:
                            self._gc_opened = time.monotonic()
                    raise
            self.group_commits_total += 1
            if first_semantic is not None:
                raise first_semantic

    def _gc_apply(self, entry: dict) -> None:
        op, key, items = entry["op"], entry["key"], entry["items"]
        if op == "put_messages":
            # Whole-batch critical retry: a replayed batch can
            # double-enqueue rows the faulted attempt already landed,
            # which is the queue contract's at-least-once — agents
            # already dedupe via the task-state claim transition.
            self._critical_call(
                "put_messages", self._inner.put_messages,
                (key[1], items), {"delay_seconds": key[2]})
            return
        table = key[1]
        if not entry.get("tolerant"):
            try:
                self._inner.insert_entities(table, items)
                return
            except _SEMANTIC_ERRORS:
                raise
            except Exception:  # noqa: BLE001 - transport: maybe torn
                self._latch_outage("insert_entities")
                entry["tolerant"] = True
        # Per-row repair. items shrinks as rows land so a re-queued
        # entry resumes exactly where the outage cut it off.
        while items:
            pk, rk, entity = items[0]
            try:
                self._critical_call(
                    "insert_entity", self._inner.insert_entity,
                    (table, pk, rk, entity), {})
            except EntityExistsError:
                pass  # applied before the fault — repair made whole
            items.pop(0)

    def _critical_call(self, op: str, attr, args: tuple,
                       kwargs: dict) -> Any:
        attempt = 0
        first_failed: Optional[float] = None
        while True:
            try:
                if op == "query_entities":
                    # Materialize so transport failures surface HERE,
                    # not at some later iteration site outside the
                    # retry loop.
                    result = list(attr(*args, **kwargs))
                else:
                    result = attr(*args, **kwargs)
            except _SEMANTIC_ERRORS:
                raise
            except Exception as exc:  # noqa: BLE001 - transport
                self._latch_outage(op)
                attempt += 1
                now = time.monotonic()
                if first_failed is None:
                    first_failed = now
                # The ceiling is THIS call's own failure window, not
                # the global latch clock: a concurrent advisory
                # probe's success clears the latch, and a flapping
                # store — or a deterministic CALLER error failing
                # against a perfectly healthy store — would re-latch
                # with a fresh start time every attempt, resetting a
                # latch-based clock forever and turning the bounded
                # ceiling into an infinite spin.
                elapsed = now - first_failed
                if elapsed > self._max_outage_seconds or \
                        self._stop_check():
                    raise StoreOutageError(
                        f"store op {op} failed through a "
                        f"{elapsed:.0f}s outage") from exc
                delay = self._backoff(op, attempt)
                deadline = getattr(self._tls, "deadline", None)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0.0:
                        raise StoreOutageError(
                            f"store op {op} exceeded its caller's "
                            f"bounded retry window during a "
                            f"{elapsed:.0f}s outage") from exc
                    delay = min(delay, remaining)
                time.sleep(delay)
                continue
            with self._lock:
                latched = self._outage_since is not None
            if latched:
                self._recovered()
            return result

    def _backoff(self, op: str, attempt: int) -> float:
        delay = min(self._retry_cap,
                    self._retry_base * (2.0 ** min(attempt - 1, 16)))
        # Deterministic per-(op, attempt) jitter (the retry
        # supervisor's idiom): desynchronize a fleet's retry thunder
        # without breaking seeded-drill replays.
        jitter = (zlib.crc32(f"{op}#{attempt}".encode()) % 1000) \
            / 1000.0
        return delay * (0.75 + 0.5 * jitter)

    # ------------------------------ outage -----------------------------

    def _latch_outage(self, op: str) -> None:
        with self._lock:
            if self._outage_since is None:
                self._outage_since = time.time()
                self._last_probe = time.monotonic()
                logger.warning(
                    "store outage latched (first failed op: %s); "
                    "critical ops retrying, advisory ops journaling "
                    "to %s", op, self._journal_path)

    def _recovered(self) -> None:
        with self._lock:
            since = self._outage_since
            self._outage_since = None
            if since is None:
                return
            now = time.time()
            self.outage_seconds_total += max(0.0, now - since)
            self.outages_total += 1
        replayed = self._replay()
        logger.warning(
            "store outage over after %.1fs; %d journaled event(s) "
            "replayed, %d still backlogged", now - since, replayed,
            self.journal_backlog())
        self._emit_outage_event(since, now, replayed)

    def _emit_outage_event(self, start: float, end: float,
                           replayed: int) -> None:
        """Price the outage as its own badput leg, with the exact
        [first-failure, first-success] partition. Emitted through
        SELF so a double-dip outage journals it like any other
        advisory event."""
        if not self._pool_id:
            return
        with self._lock:
            if self._emitting:
                return
            self._emitting = True
        try:
            from batch_shipyard_tpu.goodput import events as gp_events
            gp_events.emit(
                self, self._pool_id, gp_events.STORE_OUTAGE,
                node_id=self._node_id, start=start, end=end,
                attrs={"replayed": replayed,
                       "backlog": self.journal_backlog()})
        finally:
            with self._lock:
                self._emitting = False

    # ------------------------------ journal ----------------------------

    def journal_backlog(self) -> int:
        with self._lock:
            return len(self._journal)

    def _entry_key(self, op: str, args: tuple) -> Optional[tuple]:
        # Op is part of the key: folding an upsert into an earlier
        # merge entry would replay it with merge semantics and keep
        # columns the upsert meant to drop.
        if op in ("merge_entity", "upsert_entity") and len(args) >= 3:
            return (op, args[0], args[1], args[2])
        return None

    def _journal_append(self, op: str, args: tuple,
                        kwargs: dict) -> None:
        entry = {"op": op, "args": list(args),
                 "kwargs": dict(kwargs),
                 "recorded_at": time.time()}
        entry["kwargs"].pop("if_match", None)  # stale by replay time
        with self._lock:
            key = self._entry_key(op, args)
            if key is not None:
                # Coalesce repeated publishes of the same entity
                # (heartbeats every few seconds for minutes) into the
                # MOST RECENT journaled write for that entity — the
                # backlog stays O(entities), not O(outage duration).
                # Only the newest entry is a legal target: reaching
                # past an intervening different-op write (merge vs
                # upsert) or the entry being replayed right now would
                # reorder the chain on replay.
                for prior in reversed(self._journal):
                    pkey = self._entry_key(prior["op"],
                                           tuple(prior["args"]))
                    if pkey is None or pkey[1:] != key[1:]:
                        continue
                    if prior is self._replay_inflight or \
                            pkey[0] != key[0]:
                        break  # op boundary / in-flight: append
                    if op == "upsert_entity":
                        # Upsert semantics: the newest full-row
                        # replace wins outright.
                        prior["args"][3] = dict(entry["args"][3])
                    else:
                        merged = dict(prior["args"][3])
                        merged.update(entry["args"][3])
                        prior["args"][3] = merged
                    prior["recorded_at"] = entry["recorded_at"]
                    # O(1) on disk: append the RAW entry instead of
                    # rewriting the whole file per heartbeat. A crash
                    # replays the un-coalesced file in order —
                    # newest-last yields the same final store state;
                    # drains/stall-trims compact it.
                    self._append_journal_file(entry)
                    return
            self._journal.append(entry)
            self._append_journal_file(entry)

    def _append_journal_file(self, entry: dict) -> None:
        try:
            os.makedirs(os.path.dirname(self._journal_path) or ".",
                        exist_ok=True)
            with open(self._journal_path, "a",
                      encoding="utf-8") as fh:
                fh.write(json.dumps(entry, default=str) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        except OSError:
            logger.exception("WAL append failed for %s",
                             self._journal_path)

    def _rewrite_journal_file(self) -> None:
        """Atomic whole-file compaction — used by partial-replay
        trims (coalescing appends raw entries instead; see
        _journal_append)."""
        try:
            os.makedirs(os.path.dirname(self._journal_path) or ".",
                        exist_ok=True)
            payload = "".join(json.dumps(entry, default=str) + "\n"
                              for entry in self._journal)
            util.atomic_write(self._journal_path,
                              payload.encode("utf-8"))
        except OSError:
            logger.exception("WAL rewrite failed for %s",
                             self._journal_path)

    def _load_journal(self) -> None:
        """Crash-restart path: a predecessor agent's backlog is this
        agent's debt — loaded now, replayed before recovery declares
        itself done."""
        if not os.path.exists(self._journal_path):
            return
        entries: list[dict] = []
        try:
            with open(self._journal_path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        entry = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(entry, dict) and entry.get("op") \
                            in _MANAGED_OPS:
                        entries.append(entry)
        except OSError:
            logger.exception("WAL load failed for %s",
                             self._journal_path)
            return
        with self._lock:
            self._journal = entries
        if entries:
            logger.warning(
                "loaded %d journaled store op(s) from a previous "
                "agent process; replaying on first healthy op",
                len(entries))

    def _maybe_replay_backlog(self) -> None:
        """Restart-backlog drain: replay a loaded journal once the
        store answers, outside any outage latch."""
        with self._lock:
            pending = bool(self._journal) and \
                self._outage_since is None
        if pending:
            self._replay()

    def _replay(self) -> int:
        """Apply the journal IN ORDER. An entry that hits a transport
        error stops the replay (latch re-opens via the failing op's
        own path next time); semantic errors mean the world moved on
        — EntityExistsError is a crash-mid-replay duplicate (success),
        NotFoundError a deleted target (drop). Returns entries
        applied."""
        if not self._replay_lock.acquire(blocking=False):
            return 0  # a concurrent drain owns the backlog
        applied = 0
        try:
            while True:
                with self._lock:
                    if not self._journal:
                        break
                    entry = self._journal[0]
                    self._replay_inflight = entry
                    # Snapshot the payload under the lock: coalescing
                    # mutates args[3] in place and the store may
                    # serialize lazily.
                    args = list(entry["args"])
                    if len(args) >= 4 and isinstance(args[3], dict):
                        args[3] = dict(args[3])
                    args = tuple(args)
                op = entry["op"]
                kwargs = dict(entry.get("kwargs") or {})
                try:
                    if op == "upsert_entity" and len(args) >= 3 and \
                            args[0] == names.TABLE_NODES:
                        # A journaled node publish must never
                        # resurrect a row the substrate deleted
                        # during the outage (upsert re-creates
                        # unconditionally — ghost capacity for
                        # federation/heimdall observers); probe
                        # existence and let the NotFoundError drop
                        # the entry like any other retired target.
                        self._inner.get_entity(args[0], args[1],
                                               args[2])
                    getattr(self._inner, op)(*args, **kwargs)
                except (EntityExistsError, NotFoundError,
                        EtagMismatchError, PreconditionFailedError):
                    pass  # replayed before a crash, or target retired
                except Exception:  # noqa: BLE001 - transport: stop
                    logger.debug("WAL replay stalled at %s", op,
                                 exc_info=True)
                    with self._lock:
                        self._rewrite_journal_file()
                    return applied
                applied += 1
                with self._lock:
                    if self._journal and self._journal[0] is entry:
                        self._journal.pop(0)
            with self._lock:
                if not self._journal:
                    try:
                        os.remove(self._journal_path)
                    except OSError:
                        pass
                else:
                    self._rewrite_journal_file()
            return applied
        finally:
            with self._lock:
                self._replay_inflight = None
            self._replay_lock.release()

"""Attention kernel correctness: blockwise and ring vs the reference
oracle, on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.ops import attention as attn
from batch_shipyard_tpu.ops import ring_attention as ring
from batch_shipyard_tpu.parallel import mesh as mesh_mod


def make_qkv(batch=2, seq=256, heads=4, depth=64, seed=0,
             dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    shape = (batch, seq, heads, depth)
    q = jnp.asarray(rng.randn(*shape), dtype) * 0.1
    k = jnp.asarray(rng.randn(*shape), dtype) * 0.1
    v = jnp.asarray(rng.randn(*shape), dtype) * 0.1
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_reference(causal):
    q, k, v = make_qkv()
    expected = attn.mha_reference(q, k, v, causal=causal)
    got = attn.blockwise_mha(q, k, v, causal=causal, block_size=64)
    np.testing.assert_allclose(got, expected, atol=2e-5, rtol=2e-5)


def test_blockwise_gradients_match_reference():
    q, k, v = make_qkv(seq=128)

    def loss_ref(q, k, v):
        return jnp.sum(attn.mha_reference(q, k, v, causal=True) ** 2)

    def loss_blk(q, k, v):
        return jnp.sum(attn.blockwise_mha(q, k, v, causal=True,
                                          block_size=32) ** 2)

    grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    grads_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for gr, gb in zip(grads_ref, grads_blk):
        np.testing.assert_allclose(gb, gr, atol=5e-5, rtol=5e-4)


def test_offset_blocks_match_full():
    """Computing the second half of queries with q_offset equals the
    second half of the full computation (the ring invariant)."""
    q, k, v = make_qkv(seq=128)
    full = attn.mha_reference(q, k, v, causal=True)
    half = attn.blockwise_mha(q[:, 64:], k, v, causal=True,
                              block_size=64, q_offset=64, kv_offset=0)
    np.testing.assert_allclose(half, full[:, 64:], atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_attention_matches_reference(causal, sp):
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8, sp=sp))
    q, k, v = make_qkv(batch=8, seq=256, heads=4, depth=64)
    expected = attn.mha_reference(q, k, v, causal=causal)
    got = ring.ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_attention_differentiable():
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8, sp=4))
    q, k, v = make_qkv(batch=2, seq=128, heads=2, depth=32)

    def loss_ring(q, k, v):
        return jnp.sum(ring.ring_attention(q, k, v, mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attn.mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gg in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gr),
                                   atol=5e-5, rtol=5e-4)


def test_flash_attention_interpret_mode():
    """Pallas kernel numerics via the interpreter (no TPU needed)."""
    from batch_shipyard_tpu.ops.attention import _flash_forward
    import jax.experimental.pallas as pl  # noqa: F401
    q, k, v = make_qkv(batch=1, seq=256, heads=2, depth=64)
    expected = attn.mha_reference(q, k, v, causal=True)
    from jax.experimental.pallas import tpu as pltpu
    with pltpu.force_tpu_interpret_mode():
        got = _flash_forward(q, k, v, True, 128, 128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_attention_dispatch():
    q, k, v = make_qkv(seq=64)
    out = attn.attention(q, k, v, impl="blockwise", block_size=32)
    assert out.shape == q.shape
    with pytest.raises(ValueError):
        attn.attention(q, k, v, impl="bogus")


def test_flash_backward_matches_reference_interpret():
    """Grad parity of the hand-written pallas backward kernels vs the
    reference oracle (interpret mode, fp32 — exact math check)."""
    from jax.experimental.pallas import tpu as pltpu
    q, k, v = make_qkv(batch=1, seq=256, heads=2, depth=64)
    g = jnp.asarray(
        np.random.RandomState(7).randn(*q.shape), jnp.float32) * 0.1
    with pltpu.force_tpu_interpret_mode():
        for causal in (True, False):
            def loss_flash(q, k, v):
                return jnp.sum(attn.flash_attention(
                    q, k, v, causal, 128, 128) * g)

            def loss_ref(q, k, v):
                return jnp.sum(attn.mha_reference(q, k, v, causal) * g)

            grads_flash = jax.grad(loss_flash,
                                   argnums=(0, 1, 2))(q, k, v)
            grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
            for gf, gr in zip(grads_flash, grads_ref):
                np.testing.assert_allclose(
                    np.asarray(gf), np.asarray(gr), atol=2e-5,
                    rtol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow
def test_flash_ring_merge_algorithm_matches_reference(causal):
    """The flash-ring building blocks — flash_attention_with_lse,
    masked_attention_block, merge_attention_blocks, and the 3-case
    (masked/diagonal/full) selection — reproduce exact attention when
    the ring is simulated shard by shard. (Pallas interpret mode
    inside shard_map aborts on CPU, so the shard_map wiring itself is
    covered by the XLA-impl ring tests; this validates the flash
    algorithm.)"""
    from jax.experimental.pallas import tpu as pltpu
    sp = 4
    q, k, v = make_qkv(batch=2, seq=512, heads=2, depth=64)
    t_local = 512 // sp
    expected = attn.mha_reference(q, k, v, causal=causal)
    with pltpu.force_tpu_interpret_mode():
        outs = []
        for my in range(sp):
            q_s = q[:, my * t_local:(my + 1) * t_local]
            o_acc, lse_acc = attn.masked_attention_block(q_s)
            for src_idx in range(sp):
                k_s = k[:, src_idx * t_local:(src_idx + 1) * t_local]
                v_s = v[:, src_idx * t_local:(src_idx + 1) * t_local]
                if causal and src_idx > my:
                    o_s, lse_s = attn.masked_attention_block(q_s)
                elif causal and src_idx == my:
                    o_s, lse_s = attn.flash_attention_with_lse(
                        q_s, k_s, v_s, True)
                else:
                    o_s, lse_s = attn.flash_attention_with_lse(
                        q_s, k_s, v_s, False)
                o_acc, lse_acc = attn.merge_attention_blocks(
                    o_acc, lse_acc, o_s, lse_s)
            outs.append(o_acc)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


# ---------------- in-kernel int8 dense decode -------------------------

def _int8_cache(batch=6, t_len=64, heads=4, depth=64, seed=11):
    from batch_shipyard_tpu.ops.quantization import quantize_int8_rows
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(batch, 1, heads, depth), jnp.float32)
    k_f = jnp.asarray(rng.randn(batch, t_len, heads, depth),
                      jnp.float32)
    v_f = jnp.asarray(rng.randn(batch, t_len, heads, depth),
                      jnp.float32)
    ck, ks = quantize_int8_rows(k_f)
    cv, vs = quantize_int8_rows(v_f)
    return q, k_f, v_f, ck, ks, cv, vs


def test_dense_decode_int8_kernel_matches_dequant_einsum():
    """The in-kernel int8 dequant dense decode kernel
    (ops/decode_attention.py, interpret mode) vs the existing
    dequantize + einsum path, over ragged lengths INCLUDING the
    short-prefix masked region (length 1 and lengths straddling the
    kernel's block boundary)."""
    from batch_shipyard_tpu.ops import decode_attention as dd
    q, _, _, ck, ks, cv, vs = _int8_cache()
    lengths = jnp.asarray([1, 3, 16, 17, 63, 64], jnp.int32)
    got = dd.dense_decode_attention_kernel(q, ck, cv, ks, vs,
                                           lengths, interpret=True)
    want = dd.dense_decode_attention_xla(q, ck, cv, ks, vs, lengths)
    rel = (np.linalg.norm(np.asarray(got - want)) /
           np.linalg.norm(np.asarray(want)))
    assert rel < 1e-5, rel
    # And both within quantization noise of the fp cache.
    q2, k_f, v_f, *_ = _int8_cache()
    ones = jnp.ones(k_f.shape[:3], jnp.float32)
    ref = dd.dense_decode_attention_xla(q2, k_f, v_f, ones, ones,
                                        lengths)
    rel_fp = (np.linalg.norm(np.asarray(want - ref)) /
              np.linalg.norm(np.asarray(ref)))
    assert rel_fp < 0.02, rel_fp


def test_dense_decode_impl_resolution():
    """auto stays on the XLA path until the dense_decode_int8 check
    passes on a TPU backend; explicit impls pass through; unknown
    impls fail fast."""
    import json
    from batch_shipyard_tpu.ops import decode_attention as dd
    from batch_shipyard_tpu.ops import kernel_select
    assert dd.resolve_dense_decode_impl("kernel") == "kernel"
    assert dd.resolve_dense_decode_impl("xla") == "xla"
    with pytest.raises(ValueError):
        dd.resolve_dense_decode_impl("bogus")
    # CPU backend: even a tpu-backed marker leaves auto on xla.
    import os
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        marker = os.path.join(tmp, "KERNEL_VALIDATION.json")
        with open(marker, "w", encoding="utf-8") as fh:
            json.dump({"dense_decode_int8":
                       {"ok": True, "backend": "tpu"}}, fh)
        old = os.environ.get(kernel_select.MARKER_ENV)
        os.environ[kernel_select.MARKER_ENV] = marker
        try:
            assert dd.resolve_dense_decode_impl(None) == "xla"
        finally:
            if old is None:
                os.environ.pop(kernel_select.MARKER_ENV, None)
            else:
                os.environ[kernel_select.MARKER_ENV] = old


def test_dense_decode_kernel_through_transformer():
    """The flax dense int8 decode path with decode_attention_impl=
    'kernel' (interpret mode) matches the einsum path end to end —
    prefill via the multi-token insert, then one kernel decode
    step."""
    import dataclasses
    from jax.experimental.pallas import tpu as pltpu
    from batch_shipyard_tpu.models import inference as inf
    from batch_shipyard_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(
        vocab_size=97, d_model=64, n_layers=2, n_heads=4, d_head=16,
        d_ff=128, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32)
    params = tfm.TransformerLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.asarray([[5, 17, 31, 2, 9, 40]], jnp.int32)

    def decode_logits(impl):
        dcfg = dataclasses.replace(
            inf.decode_config(cfg, 64), kv_cache_dtype="int8",
            decode_attention_impl=impl)
        model = tfm.TransformerLM(dcfg)
        cache = inf.init_cache(model, params, 1)
        _, mutated = model.apply(
            {"params": params, "cache": cache}, prompt,
            return_hidden=True, mutable=["cache"])
        logits, _ = model.apply(
            {"params": params, "cache": mutated["cache"]},
            jnp.asarray([[7]], jnp.int32),
            positions=jnp.asarray([[6]], jnp.int32),
            mutable=["cache"])
        return logits

    ref = decode_logits("xla")
    with pltpu.force_tpu_interpret_mode():
        got = decode_logits("kernel")
    rel = (np.linalg.norm(np.asarray(got - ref)) /
           np.linalg.norm(np.asarray(ref)))
    assert rel < 1e-5, rel


@pytest.mark.parametrize("scale", [0.1, 1.0])
@pytest.mark.slow
def test_flash_ring_merge_gradients(scale):
    """Gradients flow correctly through the merge + flash building
    blocks (2-shard simulated ring vs oracle). The merge weights
    w_i = exp(lse_i - m) depend on each block's lse, so this also
    covers the lse-cotangent term of the flash backward; unit-scale
    inputs + a relative-error assertion keep atol from masking a
    missing term (advisor round-1 finding)."""
    from jax.experimental.pallas import tpu as pltpu
    q, k, v = make_qkv(batch=1, seq=256, heads=2, depth=64)
    q, k, v = q * (scale / 0.1), k * (scale / 0.1), v * (scale / 0.1)

    def ring_sim(q, k, v):
        # The production virtual-shard path: same 3-case rotation +
        # merge code the shard_map ring body runs.
        return ring.ring_attention_virtual_shards(q, k, v, sp=2,
                                                  causal=True)

    def loss_ref(q, k, v):
        return jnp.sum(attn.mha_reference(q, k, v, causal=True) ** 2)

    with pltpu.force_tpu_interpret_mode():
        def loss_sim(q, k, v):
            return jnp.sum(ring_sim(q, k, v) ** 2)
        g_sim = jax.grad(loss_sim, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gg in zip(g_ref, g_sim):
        gr, gg = np.asarray(gr), np.asarray(gg)
        np.testing.assert_allclose(gg, gr, atol=5e-5 * scale ** 2,
                                   rtol=5e-4)
        # Relative error of the whole gradient tensor, so atol on
        # small entries cannot hide a systematically missing term.
        rel = (np.linalg.norm(gg - gr) /
               max(np.linalg.norm(gr), 1e-30))
        assert rel < 1e-4, f"relative grad error {rel:.2e}"

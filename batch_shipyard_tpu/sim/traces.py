"""Arrival traces: the synthetic workloads the simulator schedules.

Every generator is a pure function of its arguments (seeded
``random.Random`` instances, never the global RNG, never the wall
clock) so the same (seed, params) always yields the same trace —
half of the byte-identical determinism contract.

Shapes:

* ``poisson_trace``          — steady Poisson arrivals, mixed
                               identities/checkpoint cadences.
* ``diurnal_trace``          — sinusoidal day/night rate profile (the
                               autoscale provisioning-vs-queueing
                               trade only exists under load swings).
* ``scheduler_scale_trace``  — BENCH_scheduler_scale-shaped: one
                               bulk submission of up to 10^6 tiny
                               tasks at t=0 (the PR-14 streaming
                               submission shape).
* ``priority_burst_trace``   — low-priority fleet filler plus a late
                               high-priority burst that cannot place:
                               the victim-selection shape.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Optional


@dataclasses.dataclass(frozen=True)
class SimTask:
    """One simulated task: everything placement, pricing, and victim
    selection need, and nothing else."""
    task_id: str
    arrival: float
    steps: int
    step_seconds: float
    priority: int = 0
    # Compile-cache identity digest (compilecache/manager.py
    # identity_key analog). None = nothing to compile / no affinity.
    cache_identity: Optional[str] = None
    # Cold-compile seconds paid when no node is warm for the
    # identity; a warm claim skips it (cache_hit).
    compile_seconds: float = 30.0
    # COMMITTED-checkpoint cadence in steps (0 = never): bounds the
    # replay rework a kill costs, exactly like workloads/checkpoint.
    ckpt_every: int = 0
    ckpt_seconds: float = 0.0
    gang_size: int = 1


def poisson_trace(seed: int, num_tasks: int, rate_per_second: float,
                  steps: int = 100, step_seconds: float = 0.5,
                  identities: int = 8,
                  identity_fraction: float = 0.7,
                  compile_seconds: float = 30.0,
                  ckpt_every: int = 20,
                  ckpt_seconds: float = 0.5,
                  priorities: tuple = (0,),
                  ) -> list[SimTask]:
    """Steady Poisson arrivals; ``identity_fraction`` of tasks carry
    one of ``identities`` compile-cache identities (the affinity
    policy's substrate), the rest are identity-less shell work."""
    rng = random.Random(seed)
    tasks = []
    t = 0.0
    for i in range(num_tasks):
        t += rng.expovariate(rate_per_second)
        identity = None
        if rng.random() < identity_fraction:
            identity = f"id-{rng.randrange(identities):04d}"
        tasks.append(SimTask(
            task_id=f"t{i:07d}", arrival=t,
            steps=max(1, int(rng.gauss(steps, steps * 0.2))),
            step_seconds=step_seconds,
            priority=priorities[rng.randrange(len(priorities))],
            cache_identity=identity,
            compile_seconds=compile_seconds,
            ckpt_every=ckpt_every, ckpt_seconds=ckpt_seconds))
    return tasks


def diurnal_arrivals(seed: int, num: int, day_seconds: float,
                     peak_rate: float, trough_rate: float,
                     ) -> list[float]:
    """Arrival times of an inhomogeneous Poisson process whose rate
    swings sinusoidally between trough and peak over a virtual day
    (thinning against the peak envelope). Factored out of
    ``diurnal_trace`` so the serving load generator
    (models/loadgen.py arrival="diurnal") replays the SAME arrival
    curve the fleet simulator schedules — one day/night shape across
    both layers, deterministic per (seed, params)."""
    rng = random.Random(seed)
    arrivals: list[float] = []
    t = 0.0
    while len(arrivals) < num:
        t += rng.expovariate(peak_rate)
        phase = math.sin(2.0 * math.pi * t / day_seconds)
        rate = trough_rate + (peak_rate - trough_rate) * \
            (0.5 + 0.5 * phase)
        if rng.random() * peak_rate > rate:
            continue
        arrivals.append(t)
    return arrivals


def diurnal_trace(seed: int, num_tasks: int, day_seconds: float,
                  peak_rate: float, trough_rate: float,
                  steps: int = 60, step_seconds: float = 0.5,
                  identities: int = 8,
                  compile_seconds: float = 30.0,
                  ckpt_every: int = 20,
                  ) -> list[SimTask]:
    """Sinusoidal arrival rate between trough and peak over a virtual
    day: the load swing that makes provisioning-vs-queueing badput a
    real trade. Arrivals come from ``diurnal_arrivals``; task
    attributes draw from an independent stream so attribute sampling
    cannot perturb the arrival curve (or vice versa)."""
    arrivals = diurnal_arrivals(seed, num_tasks, day_seconds,
                                peak_rate, trough_rate)
    rng = random.Random((seed << 1) ^ 0x5eed)
    tasks = []
    for i, t in enumerate(arrivals):
        identity = f"id-{rng.randrange(identities):04d}" \
            if rng.random() < 0.7 else None
        tasks.append(SimTask(
            task_id=f"t{i:07d}", arrival=t,
            steps=max(1, int(rng.gauss(steps, steps * 0.2))),
            step_seconds=step_seconds,
            cache_identity=identity,
            compile_seconds=compile_seconds,
            ckpt_every=ckpt_every, ckpt_seconds=0.5))
    return tasks


def scheduler_scale_trace(num_tasks: int = 1_000_000,
                          task_seconds: float = 1.0,
                          submit_rate: float = 50_000.0,
                          ) -> list[SimTask]:
    """BENCH_scheduler_scale-shaped: up to 10^6 tiny identity-less
    tasks streamed in one bulk submission (arrivals paced at the
    measured streaming-submission rate). Deterministic without a
    seed — the shape has no randomness to begin with."""
    return [SimTask(task_id=f"t{i:07d}",
                    arrival=i / submit_rate,
                    steps=1, step_seconds=task_seconds,
                    cache_identity=None, compile_seconds=0.0)
            for i in range(num_tasks)]


def priority_burst_trace(seed: int, filler_tasks: int,
                         burst_tasks: int, burst_at: float,
                         filler_steps: int = 200,
                         step_seconds: float = 0.5,
                         ckpt_every: int = 50,
                         ) -> list[SimTask]:
    """Low-priority long-running filler saturates the fleet; a
    high-priority burst arrives at ``burst_at`` and cannot place —
    the preemption sweep must elect victims, which is where the
    goodput-cost victim policy earns (or fails to earn) its keep.
    Half the filler checkpoints on cadence (cheap victims), half
    never commits (expensive victims)."""
    rng = random.Random(seed)
    tasks = []
    for i in range(filler_tasks):
        cadenced = i % 2 == 0
        tasks.append(SimTask(
            task_id=f"lo{i:06d}",
            arrival=rng.uniform(0.0, 5.0),
            steps=filler_steps, step_seconds=step_seconds,
            priority=0,
            cache_identity=f"id-{rng.randrange(8):04d}",
            compile_seconds=20.0,
            ckpt_every=ckpt_every if cadenced else 0,
            ckpt_seconds=0.3 if cadenced else 0.0))
    for i in range(burst_tasks):
        tasks.append(SimTask(
            task_id=f"hi{i:06d}",
            arrival=burst_at + rng.uniform(0.0, 2.0),
            steps=20, step_seconds=step_seconds, priority=5,
            cache_identity=None, compile_seconds=5.0))
    return tasks

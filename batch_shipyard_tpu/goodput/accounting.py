"""Goodput accounting engine: events -> the paper's decomposition.

"ML Productivity Goodput" (arxiv 2502.06982) decomposes the fraction
of wall-clock time that produces useful progress as::

    goodput = availability x resource x program

  availability — had resources at all (scheduling leg): wall minus
                 provisioning + queueing badput, over wall.
  resource     — resources actually ran the program (runtime leg):
                 minus image-pull, idle and unaccounted time.
  program      — the running program made FRESH progress (program
                 leg): minus compile/warm-up, checkpoint overhead and
                 preemption-recovery rework (steps replayed since the
                 last checkpoint).

Everything here is a pure function over event dicts (the shape
goodput/events.py produces), so the whole engine is testable on the
in-memory store with synthetic timelines.

Overlapping-interval resolution: the timeline is swept over elementary
segments between event boundaries; each segment is charged to exactly
one category — the highest-priority interval covering it (a checkpoint
save inside a step window is checkpoint overhead, not productive
time). Categories therefore PARTITION wall clock: productive +
badput + unaccounted == wall by construction.
"""

from __future__ import annotations

from typing import Any, Optional

from batch_shipyard_tpu.goodput import events as ev
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore

# Badput categories (the waterfall rows). "unaccounted" is wall time
# no event covers — surfaced explicitly instead of silently inflating
# a real category.
BADPUT_CATEGORIES = (
    "provisioning", "queueing", "expansion", "backoff", "image_pull",
    "compile", "checkpoint", "preemption_recovery", "eviction",
    "migration", "adoption", "serving_recovery", "store_outage",
    "idle", "unaccounted",
)

PRODUCTIVE = "productive"

# Overlapped categories: shown in the waterfall but NOT charged as
# badput. checkpoint_async is the async save pipeline's background
# persist — when live step windows cover it the time stays productive
# (the sweep ranks it below PRODUCTIVE); only its uncovered tail (e.g.
# the drain at loop exit) lands in this bucket. The partition
# invariant extends to: productive + badput + overlapped == wall.
OVERLAPPED_CATEGORIES = ("checkpoint_async",)

# kind -> category. step_window is handled specially (fresh portion is
# productive, replayed portion is preemption_recovery rework); retry is
# instantaneous (counted, zero duration).
_KIND_CATEGORY = {
    ev.NODE_PROVISIONING: "provisioning",
    ev.NODE_PREP: "provisioning",
    ev.NODE_PREEMPTED: "provisioning",   # reclaim -> re-provision time
    ev.TASK_QUEUED: "queueing",
    # Server-side task-factory expansion: the expander leader
    # materializing a generator spec into rows + messages. Scheduling
    # machinery like queueing, but its own leg so the 10^6-task bench
    # can show the submit work that moved pool-side instead of it
    # vanishing into the queued wait it overlaps.
    ev.TASK_EXPANSION: "expansion",
    ev.TASK_BACKOFF: "backoff",
    # Preempted exit -> re-claim: the recovery leg every preemption
    # pays (arxiv 2502.06982) — outranks queueing in the sweep, like
    # backoff, so the wait is charged to its more specific cause.
    ev.TASK_PREEMPT_RECOVERY: "preemption_recovery",
    # Evicted exit -> re-claim: the forcible sibling of the
    # preemption-recovery leg. Distinct because an eviction ALSO pays
    # the steps replayed since the pre-notice barrier (the drain
    # never happened), and fleet operators tune the grace window by
    # comparing exactly these two legs.
    ev.TASK_EVICTION_RECOVERY: "eviction",
    # Cross-pool migration wait: starved/preempted in the source pool
    # -> re-targeted and claimable on the sibling pool.
    ev.GANG_MIGRATE: "migration",
    # Agent crash -> restarted agent re-adopts the still-running
    # task: the control-plane gap an agent restart costs. Distinct
    # from the recovery legs above because NO work was lost — the
    # task ran through it — so the leg prices pure coordination
    # downtime.
    ev.TASK_ADOPTION: "adoption",
    # Serving-tier mid-stream failover (models/router.py): replica
    # death/drain detected -> resumed stream open on a sibling. The
    # re-prefill of prompt+emitted tokens and the drain-abandoned
    # decode are real lost work on the serving path — a priced leg,
    # not an invisible 5xx (arxiv 2502.06982 extended to serving).
    ev.SERVE_RECOVERY: "serving_recovery",
    # State-store outage window (state/resilient.py latch): the
    # control plane was down; whatever productive step windows cover
    # of it stays productive (the sweep ranks productive higher), and
    # only the uncovered remainder is charged here.
    ev.STORE_OUTAGE: "store_outage",
    ev.TASK_IMAGE_PULL: "image_pull",
    ev.TASK_CONTAINER_START: "image_pull",
    ev.PROGRAM_COMPILE: "compile",
    ev.PROGRAM_WARMUP: "compile",
    ev.PROGRAM_CHECKPOINT_SAVE: "checkpoint",
    ev.PROGRAM_CHECKPOINT_RESTORE: "checkpoint",
    ev.PROGRAM_CHECKPOINT_ASYNC: "checkpoint_async",
    ev.NODE_IDLE: "idle",
    ev.PROGRAM_STEP_WINDOW: PRODUCTIVE,
    ev.PROGRAM_EVAL: PRODUCTIVE,
    ev.TASK_RUNNING: "_running",         # container; lowest priority
}

# Decomposition legs: which categories each leg loses. The program
# leg (compile/checkpoint/preemption_recovery plus any uncovered
# overlapped persist) needs no tuple — it is whatever remains of run
# time after productive, so program goodput is computed directly as
# productive / run time.
_SCHEDULING_BADPUT = ("provisioning", "queueing", "expansion",
                      "backoff")
_RESOURCE_BADPUT = ("image_pull", "idle", "unaccounted")

# Sweep priority, highest first. SAME-PROGRAM overheads (rework,
# checkpoint, compile — instrumented as phases nested inside the
# program's own timeline) beat productive time; productive time beats
# the async persist (overlapped-by-design: a background write under a
# live step window must not erase the step's progress) which beats
# CROSS-TASK waits (another task's queued/image-pull span overlapping
# a busy node's step window is concurrency, not wasted node time —
# ranking those above PRODUCTIVE would let one waiting task erase a
# whole pool's productive seconds); waits beat idle beats the bare
# running container beats nothing (unaccounted).
# "backoff" outranks "queueing": the retry supervisor's deliberate
# delay window sits INSIDE the retried task's queued span (requeue ->
# re-claim), and the sweep must charge those seconds to the more
# specific cause exactly once.
_PRIORITY = (
    # "eviction"/"migration" sit with "preemption_recovery": each is
    # a recovery wait nested inside the victim's queued span, charged
    # to its more specific cause exactly once. Migration outranks
    # eviction outranks preemption: a migrated gang's window subsumes
    # the starvation that triggered it.
    # "adoption" rides with them: the restart gap is a recovery wait
    # on the task's timeline, charged to its specific cause before
    # any generic wait could claim the seconds.
    # "serving_recovery" rides at the same rank: a serving failover
    # window is a recovery wait on the request's timeline, charged to
    # its specific cause before productive step windows could absorb
    # the seconds.
    "migration", "eviction", "preemption_recovery", "adoption",
    "serving_recovery",
    "checkpoint", "compile", PRODUCTIVE,
    "checkpoint_async",
    # "store_outage" sits below the work-shaped categories (a task
    # that kept stepping through the outage keeps its productive
    # seconds — the ride-through working is not badput) but above
    # idle: control-plane downtime is a more specific story for
    # uncovered seconds than "nothing scheduled".
    # "expansion" outranks "queueing": while the expander is still
    # materializing a job's rows, that job's queued seconds have a
    # more specific cause than a generic backlog wait.
    "image_pull", "provisioning", "backoff", "expansion", "queueing",
    "store_outage", "idle",
    "_running",
)
_PRIORITY_RANK = {c: i for i, c in enumerate(_PRIORITY)}


def _as_int(value: Any) -> Optional[int]:
    """Counter attrs come from task-written JSONL: coerce defensively
    — junk degrades the window to counter-less, never a crash."""
    try:
        return int(value)
    except (TypeError, ValueError):
        return None


def _split_step_windows(windows: list[dict]) -> list[tuple]:
    """Split step_window events into (start, end, category) pieces:
    the portion covering steps already completed before (replay after
    a checkpoint restore) is preemption-recovery rework; the rest is
    productive. Windows without step counters are wholly productive.

    This is the "lost-step rework since last checkpoint" number: a
    job that checkpointed at step 80, was preempted at step 100 and
    restored to 80 replays steps 80..100 — that whole replayed window
    is badput.

    Step numbering is PER JOB: the high-water mark is tracked within
    each job_id group, so pool/fleet rollups never misprice an
    unrelated job's fresh steps 0..N as another job's replay. Within
    a job, only windows from STRICTLY EARLIER windows count toward
    the high-water mark: a gang's instances all record the same
    step range CONCURRENTLY (SPMD — that is one unit of progress,
    not replay), while a post-restore replay necessarily starts
    after the preempted window ended."""
    pieces: list[tuple] = []
    by_job: dict = {}
    for event in windows:
        by_job.setdefault(event.get("job_id"), []).append(event)
    for group in by_job.values():
        pieces.extend(_split_step_windows_one_job(group))
    return pieces


def _split_step_windows_one_job(windows: list[dict]) -> list[tuple]:
    return [(start, end, category) for (_event, start, end, category)
            in _split_step_windows_one_job_detailed(windows)]


def _split_step_windows_one_job_detailed(windows: list[dict]
                                         ) -> list[tuple]:
    """(event, start, end, category) pieces — the event reference
    lets callers re-attribute a piece to the node that executed it
    (see _replay_recovery_spans)."""
    pieces: list[tuple] = []
    completed: list[tuple] = []  # (end_time, step_end)
    for event in sorted(windows, key=lambda e: (e.get("start", 0.0),
                                                e.get("end", 0.0))):
        start = float(event.get("start", 0.0))
        end = float(event.get("end", start))
        attrs = event.get("attrs") or {}
        step_start = _as_int(attrs.get("step_start"))
        step_end = _as_int(attrs.get("step_end"))
        if step_start is None or step_end is None or \
                step_end <= step_start:
            pieces.append((event, start, end, PRODUCTIVE))
            continue
        # High-water mark over windows that ENDED before this one
        # started — concurrent (overlapping) gang instances never
        # count each other as replay.
        done_before = [se for (et, se) in completed if et <= start]
        replayed = 0
        if done_before:
            replayed = max(0, min(step_end, max(done_before))
                           - step_start)
        frac = min(1.0, replayed / (step_end - step_start))
        cut = start + (end - start) * frac
        if frac > 0:
            pieces.append((event, start, cut, "preemption_recovery"))
        if frac < 1.0:
            pieces.append((event, cut, end, PRODUCTIVE))
        completed.append((end, step_end))
    return pieces


def _replay_recovery_spans(event_list: list[dict]) -> list[dict]:
    """Explicit recovery spans for replayed step-window prefixes,
    computed over the FULL event set.

    ``_split_step_windows`` tracks the per-job step high-water mark,
    but ``decompose_by_node`` sweeps each node's events on its own
    timeline — a task preempted on node A whose replay runs on node B
    has its two windows in different groups, and B's sweep would
    price the rework as productive (it never sees A's completed
    range). This pre-pass finds every replayed prefix globally and
    emits a synthetic preemption-recovery span tagged to the node
    that EXECUTED the replay, so the per-node sweep prices it no
    matter where the task resumed. Same-node replay is double-covered
    (split piece + synthetic span) — harmless, the priority sweep
    charges each elementary second once."""
    windows = [e for e in event_list
               if e.get("kind") == ev.PROGRAM_STEP_WINDOW]
    spans: list[dict] = []
    by_job: dict = {}
    for event in windows:
        by_job.setdefault(event.get("job_id"), []).append(event)
    for group in by_job.values():
        for event, start, end, category in \
                _split_step_windows_one_job_detailed(group):
            if category != "preemption_recovery" or end <= start:
                continue
            spans.append({
                "kind": ev.TASK_PREEMPT_RECOVERY,
                "start": start, "end": end,
                "node_id": event.get("node_id"),
                "job_id": event.get("job_id"),
                "task_id": event.get("task_id"),
                "attrs": {"synthetic": "cross_node_replay"}})
    return spans


def _sweep(intervals: list[tuple], wall_start: float,
           wall_end: float) -> dict[str, float]:
    """Charge every elementary segment of [wall_start, wall_end] to
    the highest-priority covering category; uncovered time is
    "unaccounted". Returns {category: seconds} partitioning wall.

    Sweep line over sorted endpoints with per-category active counts:
    O(N log N) in the interval count — periodic consumers (the
    heimdall export) re-run this every poll, so no quadratic scans."""
    seconds = {c: 0.0 for c in BADPUT_CATEGORIES}
    seconds.update({c: 0.0 for c in OVERLAPPED_CATEGORIES})
    seconds[PRODUCTIVE] = 0.0
    seconds["_running"] = 0.0
    boundary: list[tuple] = [(wall_start, 0, None), (wall_end, 0, None)]
    for start, end, category in intervals:
        start = max(start, wall_start)
        end = min(end, wall_end)
        if end <= start:
            continue
        boundary.append((start, +1, category))
        boundary.append((end, -1, category))
    boundary.sort(key=lambda b: b[0])
    active = [0] * len(_PRIORITY)
    prev = wall_start
    for point, delta, category in boundary:
        left = max(prev, wall_start)
        right = min(point, wall_end)
        if right > left:
            winner = next((c for rank, c in enumerate(_PRIORITY)
                           if active[rank] > 0), None)
            seconds[winner if winner else "unaccounted"] += (
                right - left)
        prev = point
        if delta:
            active[_PRIORITY_RANK[category]] += delta
    # The bare running container (task process alive but no program
    # phase claimed the time) is runtime overhead the program leg
    # can't see; fold it into unaccounted rather than invent a
    # category the paper doesn't have.
    seconds["unaccounted"] += seconds.pop("_running")
    return seconds


def decompose(event_list: list[dict],
              wall: Optional[tuple[float, float]] = None
              ) -> dict[str, Any]:
    """Fold events into the goodput decomposition + badput breakdown.

    ``wall`` overrides the accounting window; by default it spans
    [min start, max end] over the events."""
    event_list = [e for e in event_list
                  if e.get("kind") in ev.EVENT_KINDS]
    if not event_list:
        return _empty_report()
    starts = [float(e.get("start", 0.0)) for e in event_list]
    ends = [float(e.get("end", e.get("start", 0.0)))
            for e in event_list]
    wall_start, wall_end = wall or (min(starts), max(ends))
    wall_seconds = max(0.0, wall_end - wall_start)

    intervals: list[tuple] = []
    step_windows: list[dict] = []
    retries = 0
    preemptions = 0
    steps = 0
    tokens = 0
    # Warm-start compilation detail (compilecache/manager.py): compile
    # and warm-up events carry cache_hit/saved_seconds attrs — the
    # seconds a warm persistent-cache hit did NOT spend compiling.
    # Reported NEXT TO compile badput (it is avoided time, not an
    # interval on the timeline — the partition is untouched).
    compile_saved = 0.0
    compile_hits = 0
    compile_misses = 0
    # Counter dedup: an N-wide SPMD gang ingests N identical step
    # ranges per job (one per instance) — one unit of progress, so
    # each distinct (job, step range) counts its steps/tokens once.
    counted_ranges: set = set()
    for event in event_list:
        kind = event.get("kind")
        if kind == ev.TASK_RETRY:
            retries += 1
            continue
        if kind == ev.PROGRAM_STEP_WINDOW:
            step_windows.append(event)
            attrs = event.get("attrs") or {}
            step_start = _as_int(attrs.get("step_start"))
            step_end = _as_int(attrs.get("step_end"))
            range_key = (event.get("job_id"), step_start, step_end)
            if step_start is not None and step_end is not None and \
                    range_key not in counted_ranges:
                counted_ranges.add(range_key)
                steps += max(0, step_end - step_start)
                tokens += _as_int(attrs.get("tokens")) or 0
            continue
        if kind in (ev.PROGRAM_COMPILE, ev.PROGRAM_WARMUP):
            attrs = event.get("attrs") or {}
            hit = attrs.get("cache_hit")
            if hit is True:
                compile_hits += 1
            elif hit is False:
                compile_misses += 1
            try:
                compile_saved += max(
                    0.0, float(attrs.get("saved_seconds") or 0.0))
            except (TypeError, ValueError):
                pass
        category = _KIND_CATEGORY.get(kind)
        if category is None:
            continue
        start = float(event.get("start", 0.0))
        end = float(event.get("end", start))
        if kind == ev.NODE_PREEMPTED and end <= start:
            # Zero-duration observation marker (autoscale emits these
            # as the count rises); the paired recovery SPAN carries
            # the downtime interval.
            preemptions += 1
            continue
        if end > start:
            intervals.append((start, end, category))
    intervals.extend(_split_step_windows(step_windows))

    seconds = _sweep(intervals, wall_start, wall_end)
    productive = seconds.pop(PRODUCTIVE)
    overlapped = {c: seconds.pop(c) for c in OVERLAPPED_CATEGORIES}
    badput = {c: seconds[c] for c in BADPUT_CATEGORIES}

    sched = sum(badput[c] for c in _SCHEDULING_BADPUT)
    resource = sum(badput[c] for c in _RESOURCE_BADPUT)
    avail_time = max(0.0, wall_seconds - sched)
    run_time = max(0.0, avail_time - resource)
    # The program leg is productive over run time (run time includes
    # both program badput AND any uncovered overlapped persist), so
    # the three legs still multiply out to the headline ratio exactly
    # — the sweep partitions wall into productive + badput +
    # overlapped.
    availability = avail_time / wall_seconds if wall_seconds else 0.0
    resource_g = run_time / avail_time if avail_time else 0.0
    program_g = productive / run_time if run_time else 0.0
    return {
        "wall_seconds": wall_seconds,
        "productive_seconds": productive,
        "goodput_ratio": (productive / wall_seconds
                          if wall_seconds else 0.0),
        "availability_goodput": availability,
        "resource_goodput": resource_g,
        "program_goodput": program_g,
        "badput_seconds": badput,
        "overlapped_seconds": overlapped,
        "compile_saved_seconds": compile_saved,
        "compile_cache_hits": compile_hits,
        "compile_cache_misses": compile_misses,
        "steps": steps,
        "tokens": tokens,
        "retries": retries,
        "preemptions": preemptions,
        "events": len(event_list),
        "window": [wall_start, wall_end],
    }


def _empty_report() -> dict[str, Any]:
    return {
        "wall_seconds": 0.0, "productive_seconds": 0.0,
        "goodput_ratio": 0.0, "availability_goodput": 0.0,
        "resource_goodput": 0.0, "program_goodput": 0.0,
        "badput_seconds": {c: 0.0 for c in BADPUT_CATEGORIES},
        "overlapped_seconds": {c: 0.0 for c in OVERLAPPED_CATEGORIES},
        "compile_saved_seconds": 0.0,
        "compile_cache_hits": 0, "compile_cache_misses": 0,
        "steps": 0, "tokens": 0, "retries": 0, "preemptions": 0,
        "events": 0, "window": None,
    }


def decompose_by_node(event_list: list[dict],
                      left_cutoff: Optional[float] = None
                      ) -> dict[str, Any]:
    """Pool-scope decomposition: events grouped per node and each
    group swept on its OWN timeline, then summed — so wall/badput are
    NODE-seconds and seven idle nodes can never hide behind one busy
    node's productive window (which a single shared timeline's
    priority sweep would let happen). Events without a node (queueing
    spans, pool resize, ingested program phases that predate node
    tagging) form their own group. ``left_cutoff`` clips each group's
    wall at the trailing-window boundary."""
    # Cross-node replay must be priced BEFORE per-node grouping —
    # see _replay_recovery_spans.
    event_list = list(event_list) + _replay_recovery_spans(event_list)
    groups: dict = {}
    for event in event_list:
        groups.setdefault(event.get("node_id"), []).append(event)
    total = _empty_report()
    total["badput_seconds"] = {c: 0.0 for c in BADPUT_CATEGORIES}
    total["overlapped_seconds"] = {c: 0.0
                                   for c in OVERLAPPED_CATEGORIES}
    for group in groups.values():
        starts = [float(e.get("start", 0.0)) for e in group]
        ends = [float(e.get("end", e.get("start", 0.0)))
                for e in group]
        left = min(starts)
        if left_cutoff is not None:
            left = max(left, left_cutoff)
        sub = decompose(group, wall=(left, max(max(ends), left)))
        total["wall_seconds"] += sub["wall_seconds"]
        total["productive_seconds"] += sub["productive_seconds"]
        for category, value in sub["badput_seconds"].items():
            total["badput_seconds"][category] += value
        for category, value in sub["overlapped_seconds"].items():
            total["overlapped_seconds"][category] += value
        for key in ("steps", "tokens", "retries", "preemptions",
                    "events", "compile_saved_seconds",
                    "compile_cache_hits", "compile_cache_misses"):
            total[key] += sub[key]
    wall = total["wall_seconds"]
    sched = sum(total["badput_seconds"][c]
                for c in _SCHEDULING_BADPUT)
    resource = sum(total["badput_seconds"][c]
                   for c in _RESOURCE_BADPUT)
    avail = max(0.0, wall - sched)
    run = max(0.0, avail - resource)
    total["goodput_ratio"] = (total["productive_seconds"] / wall
                              if wall else 0.0)
    total["availability_goodput"] = avail / wall if wall else 0.0
    total["resource_goodput"] = run / avail if avail else 0.0
    total["program_goodput"] = (total["productive_seconds"] / run
                                if run else 0.0)
    total["nodes"] = len(groups)
    return total


# ------------------------------- rollups -------------------------------

def job_report(store: StateStore, pool_id: str, job_id: str,
               trace_id: Optional[str] = None) -> dict[str, Any]:
    """One job's decomposition (job-scoped events only: queue, task
    lifecycle, program phases). ``trace_id`` scopes the waterfall to
    one submission's trace (events carrying that trace id — legacy
    rows without ids never match)."""
    report = decompose(ev.query(store, pool_id, job_id=job_id,
                                trace_id=trace_id))
    report["job_id"] = job_id
    report["pool_id"] = pool_id
    if trace_id is not None:
        report["trace_id"] = trace_id
    return report


def pool_report(store: StateStore, pool_id: str,
                window_seconds: Optional[float] = None,
                include_jobs: bool = True,
                event_list: Optional[list[dict]] = None
                ) -> dict[str, Any]:
    """Pool rollup: ALL events of the pool (node lifecycle included)
    folded into one timeline, plus per-job subreports.

    ``window_seconds`` restricts accounting to the trailing window —
    the append-only log grows with fleet age, and periodic consumers
    (the heimdall gauge export) must not re-sweep history forever.
    ``include_jobs=False`` skips the per-job subreports for callers
    that only read the pool-level numbers (heimdall, fleet).
    ``event_list`` lets a caller that already fetched the pool's
    events (heimdall fetches once per poll for several exports)
    skip the partition re-scan.

    Pool scope aggregates PER NODE (wall/badput are node-seconds, via
    decompose_by_node); job subreports are single-timeline (the job's
    own wall clock)."""
    if event_list is None:
        event_list = ev.query(store, pool_id)
    cutoff = None
    if window_seconds is not None and event_list:
        import time as time_mod
        cutoff = time_mod.time() - window_seconds
        event_list = [e for e in event_list
                      if float(e.get("end", e.get("start", 0.0)))
                      >= cutoff]
    if event_list:
        report = decompose_by_node(event_list, left_cutoff=cutoff)
    else:
        report = _empty_report()
    report["pool_id"] = pool_id
    if include_jobs:
        job_ids = sorted({e.get("job_id") for e in event_list
                          if e.get("job_id")})
        report["jobs"] = {
            job_id: decompose([e for e in event_list
                               if e.get("job_id") == job_id])
            for job_id in job_ids}
    return report


def fleet_report(store: StateStore,
                 window_seconds: Optional[float] = None
                 ) -> dict[str, Any]:
    """Fleet rollup over every registered pool: per-pool reports plus
    a wall-clock-weighted aggregate ratio."""
    pools = {}
    total_wall = 0.0
    total_productive = 0.0
    badput = {c: 0.0 for c in BADPUT_CATEGORIES}
    overlapped = {c: 0.0 for c in OVERLAPPED_CATEGORIES}
    compile_saved = 0.0
    compile_hits = 0
    compile_misses = 0
    for row in store.query_entities(names.TABLE_POOLS,
                                    partition_key="pools"):
        pool_id = row["_rk"]
        report = pool_report(store, pool_id,
                             window_seconds=window_seconds,
                             include_jobs=False)
        pools[pool_id] = report
        total_wall += report["wall_seconds"]
        total_productive += report["productive_seconds"]
        for category, value in report["badput_seconds"].items():
            badput[category] += value
        for category, value in report.get(
                "overlapped_seconds", {}).items():
            overlapped[category] += value
        compile_saved += report.get("compile_saved_seconds", 0.0)
        compile_hits += report.get("compile_cache_hits", 0)
        compile_misses += report.get("compile_cache_misses", 0)
    sched = sum(badput[c] for c in _SCHEDULING_BADPUT)
    resource = sum(badput[c] for c in _RESOURCE_BADPUT)
    avail = max(0.0, total_wall - sched)
    run = max(0.0, avail - resource)
    return {
        "pools": pools,
        "wall_seconds": total_wall,
        "productive_seconds": total_productive,
        "goodput_ratio": (total_productive / total_wall
                          if total_wall else 0.0),
        "availability_goodput": (avail / total_wall
                                 if total_wall else 0.0),
        "resource_goodput": run / avail if avail else 0.0,
        "program_goodput": (total_productive / run
                            if run else 0.0),
        "badput_seconds": badput,
        "overlapped_seconds": overlapped,
        "compile_saved_seconds": compile_saved,
        "compile_cache_hits": compile_hits,
        "compile_cache_misses": compile_misses,
    }


def report_delta(baseline: dict[str, Any],
                 candidate: dict[str, Any]) -> dict[str, Any]:
    """Category-exact comparison of two decompositions (sim policy
    runs, before/after drill snapshots): per-category badput deltas,
    the three goodput legs, and the headline ratio — candidate minus
    baseline, so negative badput deltas are seconds the candidate
    bought back. Pure over report dicts; the fleet-sim bench and
    ``shipyard sim compare`` both render from this."""
    def _f(report: dict, key: str) -> float:
        return float(report.get(key, 0.0) or 0.0)

    badput = {}
    for category in BADPUT_CATEGORIES:
        base = float((baseline.get("badput_seconds") or {})
                     .get(category, 0.0) or 0.0)
        cand = float((candidate.get("badput_seconds") or {})
                     .get(category, 0.0) or 0.0)
        badput[category] = cand - base
    return {
        "goodput_ratio_delta": (_f(candidate, "goodput_ratio")
                                - _f(baseline, "goodput_ratio")),
        "availability_goodput_delta": (
            _f(candidate, "availability_goodput")
            - _f(baseline, "availability_goodput")),
        "resource_goodput_delta": (
            _f(candidate, "resource_goodput")
            - _f(baseline, "resource_goodput")),
        "program_goodput_delta": (
            _f(candidate, "program_goodput")
            - _f(baseline, "program_goodput")),
        "productive_seconds_delta": (
            _f(candidate, "productive_seconds")
            - _f(baseline, "productive_seconds")),
        "wall_seconds_delta": (_f(candidate, "wall_seconds")
                               - _f(baseline, "wall_seconds")),
        "badput_seconds_delta": badput,
    }


# ------------------------------ rendering ------------------------------

def waterfall_table(report: dict[str, Any]) -> str:
    """Badput waterfall: productive first, then every category,
    summing to wall clock. Overlapped categories (the async
    checkpoint persist) render as their own ``~``-marked rows: shown,
    but not badput — the covered portion is already inside
    productive, and only the uncovered tail carries seconds here."""
    wall = report.get("wall_seconds") or 0.0

    def pct(value: float) -> str:
        return f"{100.0 * value / wall:5.1f}%" if wall else "    -"

    lines = [f"{'category':<22}{'seconds':>12}  {'share':>6}",
             "-" * 42]
    lines.append(f"{PRODUCTIVE:<22}"
                 f"{report.get('productive_seconds', 0.0):>12.2f}  "
                 f"{pct(report.get('productive_seconds', 0.0))}")
    for category in BADPUT_CATEGORIES:
        value = report.get("badput_seconds", {}).get(category, 0.0)
        lines.append(f"{category:<22}{value:>12.2f}  {pct(value)}")
    # Rows render only when overlapped time exists — a sync-only
    # job's waterfall is unchanged.
    shown = [(category, report.get("overlapped_seconds", {}).get(
        category, 0.0)) for category in OVERLAPPED_CATEGORIES]
    shown = [(c, v) for c, v in shown if v > 0.0]
    for category, value in shown:
        lines.append(f"{'~' + category:<22}{value:>12.2f}  "
                     f"{pct(value)}")
    if shown:
        lines.append("(~ overlapped persist: not badput; covered "
                     "portions already count as productive)")
    # Warm vs cold compile: charged compile badput is what was PAID;
    # compile_saved_seconds is what the warm persistent cache avoided
    # paying (not an interval — the partition above is untouched).
    saved = report.get("compile_saved_seconds", 0.0)
    if saved > 0.0:
        hits = report.get("compile_cache_hits", 0)
        misses = report.get("compile_cache_misses", 0)
        lines.append(f"{'~compile_saved':<22}{saved:>12.2f}  "
                     f"(warm cache: {hits} hit / {misses} cold)")
        lines.append("(~ compile_saved: wall time AVOIDED by the "
                     "warm compile cache, not badput)")
    lines.append("-" * 42)
    lines.append(f"{'wall':<22}{wall:>12.2f}  {pct(wall)}")
    lines.append(
        f"goodput_ratio = {report.get('goodput_ratio', 0.0):.3f} "
        f"(availability {report.get('availability_goodput', 0.0):.3f}"
        f" x resource {report.get('resource_goodput', 0.0):.3f}"
        f" x program {report.get('program_goodput', 0.0):.3f})")
    if report.get("steps"):
        lines.append(f"steps = {report['steps']}  "
                     f"tokens = {report.get('tokens', 0)}  "
                     f"retries = {report.get('retries', 0)}")
    return "\n".join(lines)


def prometheus_lines(report: dict[str, Any],
                     labels: dict[str, str]) -> list[str]:
    """Gauge export for the heimdall-scraped dashboards:
    goodput_ratio{...} and badput_seconds{...,category=...}."""
    label_str = ",".join(f'{k}="{v}"'
                         for k, v in sorted(labels.items()))
    lines = [
        f"goodput_ratio{{{label_str}}} "
        f"{report.get('goodput_ratio', 0.0):.6f}",
        f"goodput_productive_seconds{{{label_str}}} "
        f"{report.get('productive_seconds', 0.0):.3f}",
    ]
    sep = "," if label_str else ""
    for category in BADPUT_CATEGORIES:
        value = report.get("badput_seconds", {}).get(category, 0.0)
        lines.append(
            f"badput_seconds{{{label_str}{sep}"
            f'category="{category}"}} {value:.3f}')
    for category in OVERLAPPED_CATEGORIES:
        value = report.get("overlapped_seconds", {}).get(category,
                                                         0.0)
        lines.append(
            f"goodput_overlapped_seconds{{{label_str}{sep}"
            f'category="{category}"}} {value:.3f}')
    lines.append(
        f"goodput_compile_saved_seconds{{{label_str}}} "
        f"{report.get('compile_saved_seconds', 0.0):.3f}")
    return lines

"""Autoscale scenario evaluator tests (reference scenarios,
autoscale.py:351)."""

import datetime
import json

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.pool import autoscale
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore


def make_pool(scenario=None, formula=None, slices=1):
    spec = {"pool_specification": {
        "id": "ap", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16",
                "num_slices": slices},
        "task_slots_per_node": 2,
        "autoscale": {"enabled": True},
    }}
    if scenario:
        spec["pool_specification"]["autoscale"]["scenario"] = scenario
    if formula:
        spec["pool_specification"]["autoscale"]["formula"] = formula
    return settings_mod.pool_settings(spec)


def seed_tasks(store, pool_id, pending=0, running=0):
    store.insert_entity(names.TABLE_JOBS, pool_id, "j",
                        {"state": "active", "spec": {}})
    pk = names.task_pk(pool_id, "j")
    for idx in range(pending):
        store.insert_entity(names.TABLE_TASKS, pk, f"p{idx}",
                            {"state": "pending", "spec": {}})
    for idx in range(running):
        store.insert_entity(names.TABLE_TASKS, pk, f"r{idx}",
                            {"state": "running", "spec": {}})


def seed_nodes(store, pool_id, count, per_slice=4):
    for idx in range(count):
        store.upsert_entity(names.TABLE_NODES, pool_id, f"n{idx}", {
            "state": "idle", "node_index": idx,
            "slice_index": idx // per_slice, "worker_index":
                idx % per_slice, "heartbeat_at": 1e18,
            "hostname": f"n{idx}", "internal_ip": "10.0.0.1"})


def test_pending_tasks_scale_up_quantized_to_slices():
    store = MemoryStateStore()
    pool = make_pool(scenario={
        "name": "pending_tasks",
        "maximum_vm_count": {"dedicated": 16},
        "bias_last_sample": False})
    seed_nodes(store, "ap", 4)
    seed_tasks(store, "ap", pending=20)  # 20 tasks / 2 slots = 10 nodes
    decision = autoscale.evaluate(store, pool)
    assert decision["target_slices"] == 3  # ceil(10/4) slices
    assert decision["target_nodes"] == 12


def test_active_tasks_scale_down_to_minimum():
    store = MemoryStateStore()
    pool = make_pool(scenario={
        "name": "active_tasks",
        "minimum_vm_count": {"dedicated": 4},
        "maximum_vm_count": {"dedicated": 16},
        "bias_last_sample": False})
    seed_nodes(store, "ap", 8)
    decision = autoscale.evaluate(store, pool)  # no tasks at all
    assert decision["target_nodes"] == 4


def test_max_increment_limits_growth():
    store = MemoryStateStore()
    pool = make_pool(scenario={
        "name": "pending_tasks",
        "maximum_vm_count": {"dedicated": 64},
        "maximum_vm_increment_per_evaluation": {"dedicated": 4},
        "bias_last_sample": False})
    seed_nodes(store, "ap", 4)
    seed_tasks(store, "ap", pending=100)
    decision = autoscale.evaluate(store, pool)
    assert decision["target_nodes"] == 8  # 4 current + 4 increment


def test_workday_scenario():
    store = MemoryStateStore()
    pool = make_pool(scenario={
        "name": "workday",
        "minimum_vm_count": {"dedicated": 0},
        "maximum_vm_count": {"dedicated": 8}})
    monday_noon = datetime.datetime(2026, 7, 27, 12, 0)
    sunday = datetime.datetime(2026, 7, 26, 12, 0)
    assert autoscale.evaluate(
        store, pool, now=monday_noon)["target_nodes"] == 8
    assert autoscale.evaluate(
        store, pool, now=sunday)["target_nodes"] == 0


def test_user_formula():
    store = MemoryStateStore()
    pool = make_pool(formula="min(16, pending_tasks * 2)")
    seed_tasks(store, "ap", pending=3)
    decision = autoscale.evaluate(store, pool)
    assert decision["target_nodes"] == 8  # ceil(6/4)*4 slice-quantized


def test_formula_rejects_unsafe():
    store = MemoryStateStore()
    pool = make_pool(formula="__import__('os').system('true')")
    with pytest.raises(ValueError):
        autoscale.evaluate(store, pool)


def test_autoscale_tick_applies_via_substrate():
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    from batch_shipyard_tpu.config import settings as S
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    conf = {"pool_specification": {
        "id": "ap", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16"},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 30,
        "autoscale": {"enabled": True, "scenario": {
            "name": "pending_tasks",
            "maximum_vm_count": {"dedicated": 8},
            "bias_last_sample": False}},
    }}
    pool = S.pool_settings(conf)
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             S.global_settings({}), conf)
        autoscale.enable_autoscale(store, pool)
        seed_tasks(store, "ap", pending=8)
        decision = autoscale.autoscale_tick(store, substrate, pool)
        assert decision["applied"]
        assert len(pool_mgr.list_nodes(store, "ap")) == 8
    finally:
        substrate.stop_all()


def test_formula_rejects_attribute_escape():
    store = MemoryStateStore()
    pool = make_pool(
        formula="().__class__.__bases__[0].__subclasses__()")
    with pytest.raises(ValueError):
        autoscale.evaluate(store, pool)
    pool2 = make_pool(formula="[x for x in (1,)][0]")
    with pytest.raises(ValueError):
        autoscale.evaluate(store, pool2)


def test_rebalance_preemption_shifts_low_pri_to_dedicated():
    """rebalance_preemption_percentage (reference autoscale.py:92-135):
    when the provider reclaims >= the threshold share of capacity,
    the low-priority target shifts into dedicated."""
    store = MemoryStateStore()
    pool = make_pool(scenario={
        "name": "workday_with_offpeak_max_low_priority",
        "minimum_vm_count": {"dedicated": 2, "low_priority": 0},
        "maximum_vm_count": {"dedicated": 12, "low_priority": 8},
        "rebalance_preemption_percentage": 25})
    # Off-peak (Sunday): target = min dedicated + max low-pri.
    sunday = datetime.datetime(2026, 7, 26, 12, 0)
    seed_nodes(store, "ap", 4)
    calm = autoscale.evaluate(store, pool, now=sunday)
    assert not calm["rebalance"]
    assert calm["target_nodes"] == (2 + 8 + 3) // 4 * 4 or \
        calm["target_nodes"] >= 8  # slice-quantized 2+8
    # Preemption signal: 2 of 6 nodes reclaimed (33% >= 25%).
    for idx in (10, 11):
        store.upsert_entity(names.TABLE_NODES, "ap", f"px{idx}", {
            "state": "preempted", "node_index": idx,
            "slice_index": 2, "worker_index": idx % 4,
            "heartbeat_at": 1e18, "hostname": f"px{idx}",
            "internal_ip": "10.0.0.9"})
    hot = autoscale.evaluate(store, pool, now=sunday)
    assert hot["rebalance"]
    assert hot["preempted_nodes"] == 2
    # Low-pri share (8) folded into dedicated, capped at 12: target
    # 2+8=10 dedicated + 0 low-pri (same total here, but all
    # dedicated -> reflected in the reason).
    assert "rebalanced to dedicated" in hot["reason"]
    # Below threshold: 1 preempted of 9 (11% < 25%) -> no rebalance.
    store.delete_entity(names.TABLE_NODES, "ap", "px10")
    seed_nodes(store, "ap", 8)
    cool = autoscale.evaluate(store, pool, now=sunday)
    assert not cool["rebalance"]

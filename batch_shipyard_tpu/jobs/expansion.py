"""Server-side task-factory expansion.

`jobs add` with ``server_side_expansion: true`` submits a job's
GENERATOR spec (jobs/task_factory.py) as one expansion row instead of
materializing N task rows + N queue messages from the client — the
client round-trips O(1) while the pool's leader-gated expander
materializes chunks pool-side, right next to the store. This is the
submission analog of moving work from the control CLI onto the fleet
(the reference's federation proxy pattern), and what makes a 10^6-task
`jobs add` return in under a second.

Protocol (TABLE_EXPANSIONS, pk=pool_id, rk=job_id):

  * The client parks {state: "pending", spec: job_settings_to_raw(job)}
    plus the submission's trace columns, and stamps the job entity
    with ``expansion: pending`` so waiters gate on materialization.
  * Exactly one agent per pool — the ROLE_EXPANDER leader
    (state/leases.py) — claims rows and expands them on a dedicated
    thread (the heartbeat sweep only spawns/uses it; lint forbids slow
    sweeps). Each chunk is fenced: the expander re-checks its lease
    epoch before committing, and persists a cursor (etag-guarded)
    after.
  * Resume is deterministic re-expansion: task factories are
    deterministic (seeded rng, sorted file listings), so a successor
    leader re-derives the same (task_id, spec) sequence, skips
    ``cursor`` entries, and re-applies the boundary chunk idempotently
    (EntityExistsError == already landed; duplicate queue messages are
    the at-least-once contract agents already dedupe via the claim
    transition).
  * Completion merges {state: "completed", stats} with the submit-leg
    breakdown and prices the whole run as one "expansion" goodput
    interval — scheduling badput, so the 10^6 bench shows exactly
    where the submit work went.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, NotFoundError, StateStore)
from batch_shipyard_tpu.trace import context as trace_ctx
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Tasks per fenced commit: large enough that the pipelined submitter
# amortizes, small enough that a leader handover replays at most one
# chunk's worth of duplicate messages.
EXPANSION_CHUNK = 20_000


def _check_deterministic(job) -> None:
    """Server-side expansion re-runs the factory on resume, so the
    factory must expand identically every time. An unseeded `random`
    factory would hand a successor leader a DIFFERENT task set than
    the one already half-submitted — reject it at the client leg
    where the user can still fix the spec."""
    for raw_task in job.tasks:
        factory = raw_task.get("task_factory") or {}
        rand = factory.get("random")
        if rand is not None and rand.get("seed") is None:
            raise ValueError(
                f"job {job.id}: server_side_expansion requires a "
                "deterministic task factory; add a `seed` to the "
                "`random` factory or submit client-side")


def submit_expansion(store: StateStore, pool_id: str,
                     job, trace: Optional[trace_ctx.TraceContext] = None,
                     required_node: Optional[str] = None) -> None:
    """Client leg: one expansion row + the job-entity gate column."""
    _check_deterministic(job)
    entity = {
        "state": "pending",
        "spec": settings_mod.job_settings_to_raw(job),
        names.EXPANSION_COL_CURSOR: 0,
        "submitted_at": util.datetime_utcnow_iso(),
    }
    if required_node:
        entity["required_node"] = required_node
    if trace is not None:
        entity[trace_ctx.COL_TRACE_ID] = trace.trace_id
        entity[trace_ctx.COL_TRACE_SPAN] = trace.span_id
    store.insert_entity(names.TABLE_EXPANSIONS, pool_id, job.id,
                        entity)
    store.merge_entity(names.TABLE_JOBS, pool_id, job.id,
                       {"expansion": "pending"})


def expansion_state(store: StateStore, pool_id: str,
                    job_id: str) -> Optional[str]:
    """The job's expansion row state, or None when the job was not
    submitted for server-side expansion."""
    try:
        row = store.get_entity(names.TABLE_EXPANSIONS, pool_id,
                               job_id)
    except NotFoundError:
        return None
    return str(row.get("state") or "pending")


def expansion_error(store: StateStore, pool_id: str,
                    job_id: str) -> str:
    try:
        row = store.get_entity(names.TABLE_EXPANSIONS, pool_id,
                               job_id)
    except NotFoundError:
        return ""
    return str(row.get("error") or "")


def pending_expansions(store: StateStore, pool_id: str) -> list[dict]:
    """Rows the expander leader still owes work: fresh submissions
    plus "expanding" rows a crashed predecessor left behind (the
    fencing lease guarantees no LIVE predecessor — only the leader
    calls this)."""
    return [row for row in store.query_entities(
                names.TABLE_EXPANSIONS, partition_key=pool_id)
            if row.get("state") in ("pending", "expanding")]


def run_expansion(store: StateStore, pool_id: str, row: dict,
                  node_id: Optional[str] = None,
                  fenced: Optional[Callable[[], bool]] = None,
                  stop_check: Optional[Callable[[], bool]] = None,
                  chunk: int = EXPANSION_CHUNK) -> bool:
    """Materialize one expansion row. Returns True when the row
    reached "completed"; False when the run yielded (lost fence /
    stop requested) with the cursor persisted for the successor.
    Unparseable specs fail the row (state="failed" + error) — a bad
    generator must surface to `jobs wait`, not loop forever."""
    from batch_shipyard_tpu.goodput import events as gp_events
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    fenced = fenced or (lambda: True)
    stop_check = stop_check or (lambda: False)
    job_id = row["_rk"]
    etag = row["_etag"]
    started = time.time()
    try:
        etag = store.merge_entity(
            names.TABLE_EXPANSIONS, pool_id, job_id,
            {"state": "expanding", "claimed_by": node_id,
             "claimed_at": util.datetime_utcnow_iso()},
            if_match=etag)
    except (EtagMismatchError, NotFoundError):
        return False  # someone else moved it; not ours this round
    trace = trace_ctx.TraceContext.from_entity(row)
    try:
        job = settings_mod._job_settings(dict(row.get("spec") or {}))
        pool_entity = store.get_entity(names.TABLE_POOLS, "pools",
                                       pool_id)
        pool = settings_mod.pool_settings(
            dict(pool_entity.get("spec") or {}))
        pending = jobs_mgr._expand_job_tasks(
            store, job, pool,
            required_node=row.get("required_node") or None)
    except Exception as exc:  # noqa: BLE001 - bad spec: fail the row
        logger.exception("expansion of %s/%s failed to expand",
                         pool_id, job_id)
        _finish(store, pool_id, job_id, etag, "failed",
                error=f"{type(exc).__name__}: {exc}")
        return False
    cursor = int(row.get(names.EXPANSION_COL_CURSOR, 0) or 0)
    stats: dict = {"expanded": len(pending)}
    expand_started = time.monotonic()
    while cursor < len(pending):
        if stop_check() or not fenced():
            logger.info(
                "expansion of %s/%s yielding at cursor %d/%d",
                pool_id, job_id, cursor, len(pending))
            return False
        batch = pending[cursor:cursor + chunk]
        # tolerate_existing: the boundary chunk of a predecessor's
        # crash may be half-landed; re-applying converges.
        jobs_mgr._submit_tasks_batched(
            store, pool_id, job_id, batch, priority=job.priority,
            trace=trace, stats=stats, tolerate_existing=True)
        cursor += len(batch)
        if not fenced():
            # The chunk landed but this term ended mid-commit: do
            # NOT advance the cursor — the successor re-applies the
            # chunk idempotently under its own epoch.
            return False
        try:
            etag = store.merge_entity(
                names.TABLE_EXPANSIONS, pool_id, job_id,
                {names.EXPANSION_COL_CURSOR: cursor},
                if_match=etag)
        except (EtagMismatchError, NotFoundError):
            return False  # row moved under us: yield
    stats["expand_seconds"] = time.monotonic() - expand_started
    if not _finish(store, pool_id, job_id, etag, "completed",
                   stats=stats):
        return False
    gp_events.emit(
        store, pool_id, gp_events.TASK_EXPANSION, job_id=job_id,
        node_id=node_id, start=started, end=time.time(),
        attrs=stats,
        trace_id=(trace.trace_id if trace else None),
        span_id=(trace.span_id if trace else None))
    logger.info("expansion of %s/%s materialized %d task(s)",
                pool_id, job_id, stats["expanded"])
    return True


def _finish(store: StateStore, pool_id: str, job_id: str, etag: str,
            state: str, stats: Optional[dict] = None,
            error: Optional[str] = None) -> bool:
    patch: dict = {"state": state,
                   "completed_at": util.datetime_utcnow_iso()}
    if stats is not None:
        patch[names.EXPANSION_COL_STATS] = stats
    if error is not None:
        patch["error"] = error
    try:
        store.merge_entity(names.TABLE_EXPANSIONS, pool_id, job_id,
                           patch, if_match=etag)
    except (EtagMismatchError, NotFoundError):
        return False
    try:
        store.merge_entity(names.TABLE_JOBS, pool_id, job_id,
                           {"expansion": state})
    except NotFoundError:
        pass  # job deleted mid-expansion; nothing to gate
    return True


def run_pending_expansions(store: StateStore, pool_id: str,
                           node_id: Optional[str] = None,
                           fenced: Optional[Callable[[], bool]] = None,
                           stop_check: Optional[
                               Callable[[], bool]] = None) -> int:
    """Expander-thread entry: drain every claimable expansion row.
    Returns the number of rows completed this run."""
    done = 0
    for row in pending_expansions(store, pool_id):
        if (stop_check and stop_check()) or \
                (fenced and not fenced()):
            break
        if run_expansion(store, pool_id, row, node_id=node_id,
                         fenced=fenced, stop_check=stop_check):
            done += 1
    return done

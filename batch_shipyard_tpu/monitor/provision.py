"""Monitoring resource provisioning: Prometheus + Grafana + heimdall.

Reference analog: convoy/monitor.py (creates the monitoring VM with a
custom-script extension running shipyard_monitoring_bootstrap.sh,
which docker-composes prometheus+grafana+heimdall+nginx,
monitoring_bootstrap.sh:307-345). Ours generates the same deployable
bundle — prometheus.yml with file_sd discovery, docker-compose.yml, a
canned Grafana dashboard/provisioning, and a systemd unit — into a
directory, then either runs it locally (docker compose) or ships it to
a GCE VM (gated on gcloud). The heimdall daemon itself is pure Python
(monitor/heimdall.py) and can also run standalone.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

_PROMETHEUS_YML = """\
global:
  scrape_interval: {scrape_interval}s
  evaluation_interval: {scrape_interval}s
scrape_configs:
  - job_name: shipyard
    file_sd_configs:
      - files:
          - /etc/prometheus/file_sd/*.json
        refresh_interval: 30s
  - job_name: prometheus
    static_configs:
      - targets: ['localhost:{prom_port}']
"""

_DOCKER_COMPOSE_YML = """\
services:
  prometheus:
    image: prom/prometheus:latest
    ports:
      - "{prom_bind}{prom_port}:9090"
    volumes:
      - ./prometheus.yml:/etc/prometheus/prometheus.yml:ro
      - ./file_sd:/etc/prometheus/file_sd:ro
    restart: unless-stopped
  grafana:
    image: grafana/grafana-oss:latest
    ports:
      - "{grafana_bind}{grafana_port}:3000"
    environment:
      - "GF_SECURITY_ADMIN_PASSWORD={grafana_password}"
    volumes:
      - ./grafana/provisioning:/etc/grafana/provisioning:ro
      - ./grafana/dashboards:/var/lib/grafana/dashboards:ro
    restart: unless-stopped
"""

_NGINX_CONF = """\
events {}
http {
  server {
    listen 443 ssl;
    server_name {fqdn};
    ssl_certificate /etc/letsencrypt/live/{fqdn}/fullchain.pem;
    ssl_certificate_key /etc/letsencrypt/live/{fqdn}/privkey.pem;
    location / {
      proxy_pass http://grafana:3000;
      proxy_set_header Host $host;
    }
  }
  server {
    listen 80;
    server_name {fqdn};
    location /.well-known/acme-challenge/ { root /var/www/certbot; }
    location / { return 301 https://$host$request_uri; }
  }
}
"""

_NGINX_COMPOSE_SERVICES = """\
  nginx:
    image: nginx:stable
    ports:
      - "80:80"
      - "443:443"
    volumes:
      - ./nginx.conf:/etc/nginx/nginx.conf:ro
      - certbot-etc:/etc/letsencrypt
      - certbot-www:/var/www/certbot
    depends_on:
      - grafana
    restart: unless-stopped
  certbot:
    image: certbot/certbot:latest
    volumes:
      - certbot-etc:/etc/letsencrypt
      - certbot-www:/var/www/certbot
    entrypoint: >-
      /bin/sh -c 'certbot certonly --webroot -w /var/www/certbot
      -d {fqdn} --agree-tos -m {email} -n {staging}
      && trap exit TERM;
      while :; do certbot renew; sleep 12h & wait $${{!}}; done'
volumes:
  certbot-etc:
  certbot-www:
"""

_GRAFANA_DATASOURCE = """\
apiVersion: 1
datasources:
  - name: Prometheus
    type: prometheus
    access: proxy
    url: http://prometheus:9090
    isDefault: true
"""

_GRAFANA_DASHBOARD_PROVIDER = """\
apiVersion: 1
providers:
  - name: shipyard
    folder: ''
    type: file
    options:
      path: /var/lib/grafana/dashboards
"""

_SYSTEMD_UNIT = """\
[Unit]
Description=batch-shipyard-tpu monitoring stack
After=docker.service
Requires=docker.service

[Service]
WorkingDirectory={bundle_dir}
ExecStart=/usr/bin/docker compose up
ExecStop=/usr/bin/docker compose down
Restart=always

[Install]
WantedBy=multi-user.target
"""


def _dashboard_json() -> dict:
    """Canned dashboard (reference: batch_shipyard_dashboard.json):
    per-pool CPU/memory/network panels over node_exporter metrics."""
    def panel(panel_id, title, expr, y):
        return {
            "id": panel_id, "title": title, "type": "timeseries",
            "gridPos": {"h": 8, "w": 12, "x": (panel_id % 2) * 12,
                        "y": y},
            "targets": [{"expr": expr, "refId": "A"}],
        }
    return {
        "title": "Batch Shipyard TPU",
        "uid": "shipyard-tpu",
        "panels": [
            panel(0, "CPU busy %",
                  "100 - avg by (instance) "
                  "(rate(node_cpu_seconds_total{mode='idle'}[2m])) "
                  "* 100", 0),
            panel(1, "Memory available",
                  "node_memory_MemAvailable_bytes", 0),
            panel(2, "Network RX",
                  "rate(node_network_receive_bytes_total[2m])", 8),
            panel(3, "Disk IO",
                  "rate(node_disk_io_time_seconds_total[2m])", 8),
        ],
        "schemaVersion": 39,
        "time": {"from": "now-1h", "to": "now"},
    }


def generate_monitoring_bundle(
        output_dir: str, prometheus_port: int = 9090,
        grafana_port: int = 3000,
        grafana_password: str = "admin",
        scrape_interval: int = 15,
        additional_dashboards: Optional[dict] = None,
        lets_encrypt_fqdn: Optional[str] = None,
        lets_encrypt_email: str = "admin@example.com",
        lets_encrypt_staging: bool = False) -> str:
    """Write the full monitoring deployment bundle; returns its dir.

    With lets_encrypt_fqdn set, an nginx + certbot pair fronts Grafana
    over TLS (reference: heimdall/nginx.conf + the lets_encrypt knobs
    in monitor.yaml, monitoring_bootstrap.sh:307-345)."""
    os.makedirs(os.path.join(output_dir, "file_sd"), exist_ok=True)
    os.makedirs(os.path.join(output_dir, "grafana", "provisioning",
                             "datasources"), exist_ok=True)
    os.makedirs(os.path.join(output_dir, "grafana", "provisioning",
                             "dashboards"), exist_ok=True)
    os.makedirs(os.path.join(output_dir, "grafana", "dashboards"),
                exist_ok=True)
    with open(os.path.join(output_dir, "prometheus.yml"), "w",
              encoding="utf-8") as fh:
        fh.write(_PROMETHEUS_YML.format(
            scrape_interval=scrape_interval, prom_port=prometheus_port))
    # With the TLS front enabled, bind Grafana/Prometheus to loopback
    # only so the nginx HTTPS proxy (and its HTTP->HTTPS redirect)
    # cannot be bypassed over plaintext host ports.
    if any(c in grafana_password for c in "\n\r\""):
        raise ValueError(
            "grafana password must not contain newlines or double "
            "quotes (it is embedded in docker-compose.yml)")
    bind = "127.0.0.1:" if lets_encrypt_fqdn else ""
    compose = _DOCKER_COMPOSE_YML.format(
        prom_port=prometheus_port, grafana_port=grafana_port,
        grafana_password=grafana_password,
        prom_bind=bind, grafana_bind=bind)
    if lets_encrypt_fqdn:
        compose += _NGINX_COMPOSE_SERVICES.format(
            fqdn=lets_encrypt_fqdn, email=lets_encrypt_email,
            staging="--staging" if lets_encrypt_staging else "")
        with open(os.path.join(output_dir, "nginx.conf"), "w",
                  encoding="utf-8") as fh:
            fh.write(_NGINX_CONF.replace("{fqdn}", lets_encrypt_fqdn))
    with open(os.path.join(output_dir, "docker-compose.yml"), "w",
              encoding="utf-8") as fh:
        fh.write(compose)
    with open(os.path.join(output_dir, "grafana", "provisioning",
                           "datasources", "prometheus.yaml"), "w",
              encoding="utf-8") as fh:
        fh.write(_GRAFANA_DATASOURCE)
    with open(os.path.join(output_dir, "grafana", "provisioning",
                           "dashboards", "provider.yaml"), "w",
              encoding="utf-8") as fh:
        fh.write(_GRAFANA_DASHBOARD_PROVIDER)
    with open(os.path.join(output_dir, "grafana", "dashboards",
                           "shipyard.json"), "w",
              encoding="utf-8") as fh:
        json.dump(_dashboard_json(), fh, indent=2)
    # Extra dashboards (monitor.yaml grafana.additional_dashboards:
    # name -> local JSON path or URL-less inline dict; reference
    # additional_dashboards ship alongside the canned one).
    for name, source in (additional_dashboards or {}).items():
        dest = os.path.join(output_dir, "grafana", "dashboards",
                            name if name.endswith(".json")
                            else f"{name}.json")
        if isinstance(source, dict):
            with open(dest, "w", encoding="utf-8") as fh:
                json.dump(source, fh, indent=2)
        else:
            import shutil as shutil_mod
            shutil_mod.copyfile(source, dest)
    with open(os.path.join(output_dir, "shipyard-monitoring.service"),
              "w", encoding="utf-8") as fh:
        fh.write(_SYSTEMD_UNIT.format(bundle_dir=output_dir))
    logger.info("monitoring bundle generated at %s", output_dir)
    return output_dir


def start_local(bundle_dir: str) -> int:
    """docker compose up -d for the generated bundle (local mode)."""
    import shutil
    if shutil.which("docker") is None:
        raise RuntimeError("docker is required to start the "
                           "monitoring stack locally")
    return util.subprocess_with_output(
        ["docker", "compose", "up", "-d"], cwd=bundle_dir)


def stop_local(bundle_dir: str) -> int:
    return util.subprocess_with_output(
        ["docker", "compose", "down"], cwd=bundle_dir)


def provision_monitoring_vm(
        store, project: str, zone: Optional[str] = None,
        network: Optional[str] = None,
        vm_size: str = "e2-standard-2",
        name: str = "shipyard-monitor",
        public_ip: bool = True,
        vms=None, **bundle_kwargs) -> str:
    """Create a GCE VM running the monitoring bundle end-to-end
    (reference convoy/monitor.py:126 create_monitoring_resource: the
    VM + custom-script extension). The generated bundle is shipped
    inside the startup script as a base64 tarball, docker + compose
    are installed, and the systemd unit keeps the stack up across
    reboots. Returns the VM's internal IP; the VM is registered under
    TABLE_MONITOR (pk="vms") so destroy_monitoring_vm can find it.

    ``vms`` injects a GceVmManager (tests pass a fake runner).
    """
    import base64
    import io
    import tarfile
    import tempfile

    from batch_shipyard_tpu.state import names as _names

    if vms is None:
        from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
        vms = GceVmManager(project, zone=zone, network=network)
    with tempfile.TemporaryDirectory() as tmp:
        bundle_dir = generate_monitoring_bundle(tmp, **bundle_kwargs)
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            tar.add(bundle_dir, arcname=".")
        payload = base64.b64encode(buf.getvalue()).decode("ascii")
    startup = f"""#!/usr/bin/env bash
set -euo pipefail
# batch-shipyard-tpu monitoring VM bootstrap
if ! command -v docker >/dev/null 2>&1; then
  apt-get update
  apt-get install -y docker.io docker-compose-v2
fi
mkdir -p /opt/shipyard-monitoring
echo '{payload}' | base64 -d | \\
  tar -xz -C /opt/shipyard-monitoring
sed -i 's#WorkingDirectory=.*#WorkingDirectory=/opt/shipyard-monitoring#' \\
  /opt/shipyard-monitoring/shipyard-monitoring.service
cp /opt/shipyard-monitoring/shipyard-monitoring.service \\
  /etc/systemd/system/
systemctl daemon-reload
systemctl enable --now shipyard-monitoring.service
"""
    ip = vms.create_vm(name, vm_size, startup_script=startup,
                       public_ip=public_ip,
                       tags=("shipyard-monitor",))
    store.upsert_entity(_names.TABLE_MONITOR, "vms", name, {
        "internal_ip": ip, "state": "running",
        "created_at": util.datetime_utcnow_iso(),
    })
    logger.info("monitoring VM %s provisioned at %s", name, ip)
    return ip


def _monitor_vms(project, zone, vms):
    from batch_shipyard_tpu.utils import service_vm
    return service_vm.default_vms(project, zone, vms)


def _monitor_record(store, name: str) -> dict:
    from batch_shipyard_tpu.state import names as _names
    from batch_shipyard_tpu.state.base import NotFoundError
    try:
        return store.get_entity(_names.TABLE_MONITOR, "vms", name)
    except NotFoundError:
        raise ValueError(f"monitoring VM {name} is not registered")


def monitoring_vm_status(store, project: Optional[str] = None,
                         zone: Optional[str] = None,
                         name: str = "shipyard-monitor",
                         vms=None) -> dict:
    """Stored record + live instance status (reference
    `monitor status`, shipyard.py:2540)."""
    from batch_shipyard_tpu.utils import service_vm
    record = _monitor_record(store, name)
    return service_vm.vm_status(_monitor_vms(project, zone, vms),
                                name, record)


def suspend_monitoring_vm(store, project: Optional[str] = None,
                          zone: Optional[str] = None,
                          name: str = "shipyard-monitor",
                          vms=None) -> None:
    """Stop the monitoring VM in place (reference `monitor suspend`,
    convoy/fleet.py:4735)."""
    from batch_shipyard_tpu.state import names as _names
    from batch_shipyard_tpu.utils import service_vm
    _monitor_record(store, name)
    service_vm.suspend_vm(_monitor_vms(project, zone, vms), name,
                          store, _names.TABLE_MONITOR, "vms")


def start_monitoring_vm(store, project: Optional[str] = None,
                        zone: Optional[str] = None,
                        name: str = "shipyard-monitor",
                        vms=None) -> None:
    """Restart a suspended monitoring VM (reference `monitor start`,
    convoy/fleet.py:4749)."""
    from batch_shipyard_tpu.state import names as _names
    from batch_shipyard_tpu.utils import service_vm
    _monitor_record(store, name)
    service_vm.start_vm(_monitor_vms(project, zone, vms), name,
                        store, _names.TABLE_MONITOR, "vms")


def monitoring_vm_ssh_argv(store, username: Optional[str] = None,
                           ssh_private_key: Optional[str] = None,
                           name: str = "shipyard-monitor",
                           command: Optional[str] = None
                           ) -> list[str]:
    """ssh argv to the monitoring VM (reference `monitor ssh`,
    convoy/fleet.py:4721)."""
    from batch_shipyard_tpu.utils import service_vm
    record = _monitor_record(store, name)
    return service_vm.ssh_argv(record["internal_ip"], username,
                               ssh_private_key, command)


def destroy_monitoring_vm(store, project: str,
                          zone: Optional[str] = None,
                          name: str = "shipyard-monitor",
                          vms=None) -> None:
    """Delete the monitoring VM and its registration (reference
    convoy/monitor.py delete_monitoring_resource analog)."""
    from batch_shipyard_tpu.state import names as _names
    from batch_shipyard_tpu.state.base import NotFoundError

    from batch_shipyard_tpu.utils import service_vm
    vms = service_vm.default_vms(project, zone, vms)
    vms.delete_vm(name)
    try:
        store.delete_entity(_names.TABLE_MONITOR, "vms", name)
    except NotFoundError:
        pass

"""Offline perf analysis: coalesce perf events into per-node phase
durations and render a timeline.

Reference analog: cascade/graph.py — coalesce_data(:169) computing
per-node deltas for nodeprep, docker_install, global_resources_loaded
and per-image pull/save, and graph_data(:270) rendering a matplotlib
gantt. This drives the pool-add -> task-start latency breakdown
(BASELINE.md metric 2).
"""

from __future__ import annotations

from typing import Optional

from batch_shipyard_tpu.agent import perf
from batch_shipyard_tpu.state.base import StateStore

# Phase = (start event, end event) per source.
_PHASES = [
    ("nodeprep", "nodeprep", "start", "end"),
    ("pool_create", "pool", "create.start", "create.end"),
]


def coalesce_data(store: StateStore, pool_id: str) -> dict:
    """Per-node phase durations + per-image pull timings.

    Returns {node_id: {phase: {start, end, seconds}},
             "images": {node_id: {image: seconds}}}.
    """
    events = perf.query(store, pool_id)
    by_node: dict[str, list[dict]] = {}
    for event in events:
        by_node.setdefault(event["node_id"], []).append(event)
    out: dict = {"nodes": {}, "images": {}}
    for node_id, rows in by_node.items():
        phases: dict[str, dict] = {}
        for name, source, start_ev, end_ev in _PHASES:
            start = next((r["timestamp"] for r in rows
                          if r["source"] == source and
                          r["event"] == start_ev), None)
            end = next((r["timestamp"] for r in rows
                        if r["source"] == source and
                        r["event"] == end_ev), None)
            if start is not None and end is not None:
                phases[name] = {"start": start, "end": end,
                                "seconds": end - start}
        # Per-image pulls: cascade pull.start:<image> / pull.end:<image>
        pulls: dict[str, float] = {}
        starts: dict[str, float] = {}
        for row in rows:
            event = row["event"]
            if event.startswith("pull.start:"):
                starts[event.split(":", 1)[1]] = row["timestamp"]
            elif event.startswith("pull.end:"):
                image = event.split(":", 1)[1]
                if image in starts:
                    pulls[image] = row["timestamp"] - starts[image]
        grl = next((r["timestamp"] for r in rows
                    if r["event"] == "global_resources_loaded"), None)
        if grl is not None and "nodeprep" in phases:
            phases["global_resources_loaded"] = {
                "start": phases["nodeprep"]["start"], "end": grl,
                "seconds": grl - phases["nodeprep"]["start"]}
        if phases:
            out["nodes"][node_id] = phases
        if pulls:
            out["images"][node_id] = pulls
    return out


def render_text_gantt(data: dict, width: int = 60) -> str:
    """ASCII gantt of node phases (matplotlib-free default; the
    reference's graph_data drew the same bars with matplotlib)."""
    lines: list[str] = []
    all_times = [p[k] for node in data["nodes"].values()
                 for p in node.values() for k in ("start", "end")]
    if not all_times:
        return "(no perf events)"
    t0, t1 = min(all_times), max(all_times)
    span = max(t1 - t0, 1e-9)
    for node_id in sorted(data["nodes"]):
        for phase, info in sorted(data["nodes"][node_id].items()):
            begin = int((info["start"] - t0) / span * width)
            end = max(begin + 1, int((info["end"] - t0) / span * width))
            bar = " " * begin + "#" * (end - begin)
            lines.append(f"{node_id:24s} {phase:24s} |{bar:<{width}}| "
                         f"{info['seconds']:.3f}s")
    return "\n".join(lines)


def graph_data(store: StateStore, pool_id: str,
               output_path: Optional[str] = None) -> str:
    """Coalesce + render; writes a PNG via matplotlib when available
    and an output path is given, else returns the ASCII gantt."""
    data = coalesce_data(store, pool_id)
    text = render_text_gantt(data)
    if output_path:
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            fig, ax = plt.subplots(figsize=(12, 6))
            ypos = 0
            labels = []
            for node_id in sorted(data["nodes"]):
                for phase, info in sorted(data["nodes"][node_id].items()):
                    ax.barh(ypos, info["seconds"], left=info["start"],
                            height=0.8)
                    labels.append(f"{node_id}:{phase}")
                    ypos += 1
            ax.set_yticks(range(len(labels)))
            ax.set_yticklabels(labels, fontsize=6)
            ax.set_xlabel("unix time (s)")
            fig.tight_layout()
            fig.savefig(output_path, dpi=120)
            plt.close(fig)
        except ImportError:
            import logging
            logging.getLogger(__name__).warning(
                "matplotlib not available; %s not written (ASCII "
                "gantt returned instead)", output_path)
    return text

"""`shipyard lint` framework: findings, rules, suppression, baseline.

A distributed-systems reproduction of this size cannot rely on hand
review to hold its invariants: every hard bug so far (the PR 5
gang-row claim-marker leak, the PR 10 router duplicate-request race,
the PR 7 double-ingest inode race) was one *instance* of a bug class
with many sites. This package turns those classes into registered,
machine-checked rules.

Same cheap-by-design philosophy as tests/test_names_consistency.py
(which is now a thin wrapper over these rules): pure AST scans over
``batch_shipyard_tpu/**/*.py`` plus line scans over the shell layer
(install.sh, tools/*.sh). Rule modules import only *leaf registries*
(state.names, goodput.events, goodput.accounting, trace.spans,
chaos.plan) — never agent/serving/parallel modules, and never JAX —
so the whole analyzer runs in milliseconds anywhere pytest runs.

Surfaces:

  * ``shipyard lint``              CLI gate (exit 1 on new findings)
  * ``shipyard lint --baseline-update``  triage workflow
  * tests/test_analysis.py         tier-1 pytest gate
  * ``# shipyard-lint: disable=<rule-id>``  inline suppression, on the
    offending line or the line directly above it

Baseline semantics: findings whose fingerprint (rule, path, message —
line numbers excluded, so unrelated edits don't churn the file) is
recorded in ``.shipyard-lint-baseline.json`` warn instead of failing.
The baseline is written sorted and path-relative so diffs review like
code.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from collections import Counter
from typing import Callable, Iterable, Optional

PACKAGE_NAME = "batch_shipyard_tpu"
BASELINE_FILENAME = ".shipyard-lint-baseline.json"

_SUPPRESS_RE = re.compile(
    r"#\s*shipyard-lint:\s*disable=([A-Za-z0-9_,\-]+)")
# File-level form, honored only in a file's first 10 lines (it is a
# prologue statement about the whole file, not a scatter mechanism).
_FILE_SUPPRESS_RE = re.compile(
    r"#\s*shipyard-lint:\s*disable-file=([A-Za-z0-9_,\-]+)")


def repo_root() -> pathlib.Path:
    """The source tree this package lives in (the scan default)."""
    return pathlib.Path(__file__).resolve().parents[2]


# ------------------------------ findings -------------------------------

@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str       # repo-root-relative, posix separators
    line: int
    message: str

    def fingerprint(self) -> tuple[str, str, str]:
        """Baseline identity: line numbers excluded so edits elsewhere
        in a file don't invalidate (or churn) the baseline."""
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


# ----------------------------- source files ----------------------------

class SourceFile:
    """One scanned file: raw lines, parsed AST (python only), and the
    per-line suppression directives."""

    def __init__(self, rel: str, source: str) -> None:
        self.rel = rel.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.is_python = self.rel.endswith(".py")
        self.tree: Optional[ast.AST] = (
            ast.parse(source, filename=self.rel) if self.is_python
            else None)
        self._suppressions: Optional[dict[int, set[str]]] = None
        self._file_suppressions: Optional[set[str]] = None

    def _suppression_map(self) -> dict[int, set[str]]:
        if self._suppressions is None:
            out: dict[int, set[str]] = {}
            for idx, text in enumerate(self.lines, start=1):
                match = _SUPPRESS_RE.search(text)
                if not match:
                    continue
                ids = {t.strip() for t in match.group(1).split(",")
                       if t.strip()}
                # A directive applies to its own line (trailing
                # comment form); a COMMENT-ONLY directive line also
                # covers the line directly below it. A trailing
                # directive must not bleed onto the next line — that
                # would silently hide an unrelated adjacent finding.
                out.setdefault(idx, set()).update(ids)
                if text.lstrip().startswith("#"):
                    out.setdefault(idx + 1, set()).update(ids)
            self._suppressions = out
        return self._suppressions

    def _file_suppression_set(self) -> set[str]:
        if self._file_suppressions is None:
            ids: set[str] = set()
            for text in self.lines[:10]:
                match = _FILE_SUPPRESS_RE.search(text)
                if match:
                    ids.update(t.strip()
                               for t in match.group(1).split(",")
                               if t.strip())
            self._file_suppressions = ids
        return self._file_suppressions

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        if rule_id in self._file_suppression_set():
            return True
        ids = self._suppression_map().get(line, ())
        return rule_id in ids or "all" in ids


# ------------------------------- context -------------------------------

class AnalysisContext:
    """Everything one analyzer run sees: the parsed python files of
    the package plus the shell layer. Rules never read the filesystem
    themselves, so tests feed synthetic trees via from_strings()."""

    def __init__(self, root: pathlib.Path,
                 files: list[SourceFile]) -> None:
        self.root = pathlib.Path(root)
        self.files = files
        self._by_rel = {f.rel: f for f in files}

    @classmethod
    def from_tree(cls, root: Optional[pathlib.Path] = None,
                  ) -> "AnalysisContext":
        root = pathlib.Path(root) if root else repo_root()
        files: list[SourceFile] = []
        package = root / PACKAGE_NAME
        for path in sorted(package.rglob("*.py")):
            rel = path.relative_to(root).as_posix()
            files.append(SourceFile(
                rel, path.read_text(encoding="utf-8")))
        shell_paths = [root / "install.sh"]
        shell_paths += sorted((root / "tools").glob("*.sh"))
        for path in shell_paths:
            if path.exists():
                rel = path.relative_to(root).as_posix()
                files.append(SourceFile(
                    rel, path.read_text(encoding="utf-8")))
        return cls(root, files)

    @classmethod
    def from_strings(cls, sources: dict[str, str],
                     ) -> "AnalysisContext":
        """Synthetic context for rule tests: {relpath: source}."""
        return cls(pathlib.Path("."),
                   [SourceFile(rel, src)
                    for rel, src in sorted(sources.items())])

    @property
    def python_files(self) -> list[SourceFile]:
        return [f for f in self.files if f.is_python]

    @property
    def shell_files(self) -> list[SourceFile]:
        return [f for f in self.files if f.rel.endswith(".sh")]

    def get(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)


# -------------------------------- rules --------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    family: str
    doc: str    # includes the real bug the rule descends from
    fn: Callable[[AnalysisContext], list[Finding]]


RULES: dict[str, Rule] = {}

# Rule families (docs/34-static-analysis.md inventories them).
FAMILIES = ("store", "loop", "env", "registry", "jax", "wiring",
            "shell", "sim", "serving")


def rule(rule_id: str, family: str):
    """Register an analyzer rule. The decorated function's docstring
    is the rule's documentation and MUST name the real bug it descends
    from (bug provenance is part of the contract)."""
    if family not in FAMILIES:
        raise ValueError(f"unknown rule family {family!r}")

    def decorate(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        if not fn.__doc__:
            raise ValueError(f"rule {rule_id!r} has no docstring")
        RULES[rule_id] = Rule(id=rule_id, family=family,
                              doc=fn.__doc__, fn=fn)
        return fn
    return decorate


def _select(rule_ids: Optional[Iterable[str]]) -> list[Rule]:
    if rule_ids is None:
        return [RULES[k] for k in sorted(RULES)]
    out = []
    for rid in rule_ids:
        if rid not in RULES:
            raise KeyError(
                f"unknown rule {rid!r}; known: {sorted(RULES)}")
        out.append(RULES[rid])
    return out


# ------------------------------- running -------------------------------

@dataclasses.dataclass
class Report:
    """One analyzer run, split by disposition."""

    new: list[Finding]          # fail the gate
    baselined: list[Finding]    # warn: pre-existing, triage pending
    suppressed: list[Finding]   # inline shipyard-lint: disable=
    stale_baseline: list[tuple[str, str, str]]  # fixed but still listed

    @property
    def all_active(self) -> list[Finding]:
        return sorted(self.new + self.baselined)

    def to_dict(self) -> dict:
        return {
            "new": [f.render() for f in sorted(self.new)],
            "baselined": [f.render() for f in sorted(self.baselined)],
            "suppressed": len(self.suppressed),
            "stale_baseline": [list(fp) for fp
                               in sorted(self.stale_baseline)],
        }


def run_rules(ctx: AnalysisContext,
              rule_ids: Optional[Iterable[str]] = None,
              ) -> tuple[list[Finding], list[Finding]]:
    """(active, suppressed) findings of the selected rules, sorted."""
    active: list[Finding] = []
    suppressed: list[Finding] = []
    for rule_obj in _select(rule_ids):
        for finding in rule_obj.fn(ctx):
            src = ctx.get(finding.path)
            if src is not None and src.is_suppressed(
                    finding.rule, finding.line):
                suppressed.append(finding)
            else:
                active.append(finding)
    return sorted(active), sorted(suppressed)


def analyze(root: Optional[pathlib.Path] = None,
            ctx: Optional[AnalysisContext] = None,
            rule_ids: Optional[Iterable[str]] = None,
            baseline: Optional[Counter] = None) -> Report:
    """Full run: scan, suppress, then split against the baseline."""
    if ctx is None:
        ctx = AnalysisContext.from_tree(root)
    if baseline is None:
        baseline = load_baseline(ctx.root / BASELINE_FILENAME)
    if rule_ids is not None:
        # Partial-rule run: judge only the selected rules' slice of
        # the baseline — other rules' triaged entries are out of
        # scope, not stale.
        rule_ids = list(rule_ids)
        selected = set(rule_ids)
        baseline = Counter({fp: count
                            for fp, count in baseline.items()
                            if fp[0] in selected})
    active, suppressed = run_rules(ctx, rule_ids)
    remaining = Counter(baseline)
    new: list[Finding] = []
    baselined: list[Finding] = []
    for finding in active:
        fp = finding.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            baselined.append(finding)
        else:
            new.append(finding)
    stale = sorted(fp for fp, count in remaining.items()
                   if count > 0)
    return Report(new=new, baselined=baselined,
                  suppressed=suppressed, stale_baseline=stale)


# ------------------------------- baseline ------------------------------

def load_baseline(path: pathlib.Path) -> Counter:
    """Fingerprint multiset from the checked-in baseline; empty when
    the file is absent (a repo with no triage debt needs no file)."""
    path = pathlib.Path(path)
    if not path.exists():
        return Counter()
    data = json.loads(path.read_text(encoding="utf-8"))
    out: Counter = Counter()
    for item in data.get("findings", []):
        out[(item["rule"], item["path"], item["message"])] += 1
    return out


def write_baseline(path: pathlib.Path,
                   findings: list[Finding]) -> None:
    """Deterministic baseline write: sorted by fingerprint, line
    numbers omitted, trailing newline — two runs over the same tree
    produce byte-identical files, so baseline diffs review like
    code."""
    items = [
        {"rule": f.rule, "path": f.path, "message": f.message}
        for f in sorted(findings, key=Finding.fingerprint)]
    payload = {"version": 1, "findings": items}
    pathlib.Path(path).write_text(
        json.dumps(payload, indent=2, sort_keys=True) + "\n",
        encoding="utf-8")


# --------------------------- shared AST helpers ------------------------

def call_name(node: ast.Call) -> Optional[str]:
    """Terminal name of a call: foo(...) -> "foo",
    a.b.merge_entity(...) -> "merge_entity"."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def keyword_arg(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def const_str(node: Optional[ast.expr]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_str_consts(tree: ast.AST) -> dict[str, str]:
    """Module-level NAME = "literal" assignments (the _SCHED_TABLE /
    *_ENV constant idiom) — lets rules resolve Name/Attribute
    references one hop deep without importing the module."""
    out: dict[str, str] = {}
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = node.value.value
    return out


def functions(tree: ast.AST):
    """Every (async) function definition in a module, nested ones
    included."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node

"""Shared-scratch (auto_scratch: shared) fault injection: the NFS
export/mount synthesis path, its failure modes, and the deferred
host-side teardown — paths that the same-filesystem substrates
shortcut past (VERDICT r3 weak #4 + advisor r3 medium finding)."""

import os
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def make_env(pool_id, accel, agent_kwargs):
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "tpu": {"accelerator_type": accel},
        "max_wait_time_seconds": 60,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    substrate.agent_kwargs = agent_kwargs
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return store, substrate, pool


class Runners:
    """Fake NFS plumbing: mount materializes as a symlink to the
    host's exported dir (one shared namespace, like real NFS), and
    every call is recorded."""

    def __init__(self):
        self.mounts = []
        self.umounts = []
        self.exports = []
        self.unexports = []
        self.mount_rc = 0
        self.export_rc = 0

    def mount(self, remote, mount_point):
        self.mounts.append((remote, mount_point))
        if self.mount_rc:
            return self.mount_rc
        host_path = remote.split(":", 1)[1]
        os.rmdir(mount_point)
        os.symlink(host_path, mount_point)
        return 0

    def umount(self, mount_point):
        self.umounts.append(mount_point)
        if os.path.islink(mount_point):
            os.unlink(mount_point)
        return 0

    def export(self, path):
        self.exports.append(path)
        return self.export_rc

    def unexport(self, path):
        self.unexports.append(path)
        return 0

    def kwargs(self, **extra):
        return dict(scratch_mount_runner=self.mount,
                    scratch_umount_runner=self.umount,
                    scratch_export_runner=self.export,
                    scratch_unexport_runner=self.unexport,
                    force_remote_scratch=True,
                    scratch_finalize_timeout=15.0, **extra)


def test_remote_scratch_export_mount_and_teardown():
    """With same-fs detection disabled (as on real multi-VM pools),
    non-host workers NFS-mount worker 0's export; writes through the
    mounts land in one namespace; release unmounts, and the host
    unexports + deletes only after the whole fan-out completes."""
    runners = Runners()
    store, substrate, pool = make_env(
        "rscratch", "v5litepod-16", runners.kwargs())
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "rj", "auto_scratch": "shared",
            "auto_complete": True,
            "tasks": [
                {"id": "writers",
                 "command": "sh -c 'echo from-$SHIPYARD_NODE_INDEX > "
                            "$SHIPYARD_JOB_SCRATCH/"
                            "w$SHIPYARD_NODE_INDEX'",
                 "multi_instance": {"num_instances": 4}},
            ]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "rscratch", "rj",
                                        timeout=90)
        assert all(t["state"] == "completed" for t in tasks), tasks
        node0 = FakePodSubstrate.node_id("rscratch", 0, 0)
        host_scratch = os.path.join(substrate.work_root, "rscratch",
                                    node0, "scratch", "rj")
        # All four writers wrote through ONE namespace.
        deadline = time.monotonic() + 30
        while os.path.isdir(host_scratch):
            assert time.monotonic() < deadline, \
                "host scratch never finalized"
            time.sleep(0.2)
        # Worker 0 exported once; 3 non-host workers mounted;
        # releases unmounted them; finalize unexported.
        assert runners.exports == [host_scratch]
        assert len(runners.mounts) == 3
        # Every mount targets worker 0's export.
        assert all(m[0] == f"10.0.0.1:{host_scratch}"
                   for m in runners.mounts), runners.mounts
        assert len(runners.umounts) == 3
        assert runners.unexports == [host_scratch]
        # The finalize path removes the scratch dir BEFORE deleting
        # the host row — poll with a FRESH budget so a loaded machine
        # can't race this assertion into a flake.
        deadline = time.monotonic() + 30
        while True:
            try:
                store.get_entity(names.TABLE_JOBPREP, "rscratch$rj",
                                 "#scratchhost")
            except NotFoundError:
                break
            assert time.monotonic() < deadline, \
                "#scratchhost row never deleted"
            time.sleep(0.1)
    finally:
        substrate.stop_all()


def test_export_failure_fails_job_prep():
    runners = Runners()
    runners.export_rc = 1
    store, substrate, pool = make_env(
        "xfail", "v5litepod-4", runners.kwargs())
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "xj", "auto_scratch": "shared",
            "tasks": [{"id": "t", "command": "echo never"}]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "xfail", "xj",
                                        timeout=60)
        assert tasks[0]["state"] == "failed"
        assert "job preparation failed" in tasks[0].get("error", "")
        assert runners.exports  # the export WAS attempted
        assert runners.mounts == []
    finally:
        substrate.stop_all()


def test_mount_failure_fails_the_instance():
    runners = Runners()
    runners.mount_rc = 32  # classic mount(8) failure code
    store, substrate, pool = make_env(
        "mfail", "v5litepod-8", runners.kwargs())
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "mj", "auto_scratch": "shared",
            "tasks": [{"id": "gang",
                       "command": "echo hi",
                       "multi_instance": {"num_instances": 2}}]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            task = jobs_mgr.get_task(store, "mfail", "mj", "gang")
            if task.get("state") == "failed":
                break
            time.sleep(0.25)
        assert task.get("state") == "failed", task
        assert runners.mounts  # the mount WAS attempted and refused
    finally:
        substrate.stop_all()


def test_incomplete_release_fanout_preserves_tree():
    """A node whose harvest fails never records release completion;
    worker 0's finalize must time out PRESERVING the exported tree
    (deleting would vanish data a peer was still copying — advisor
    r3 medium finding)."""
    runners = Runners()
    store, substrate, pool = make_env(
        "preserve", "v5litepod-8",
        runners.kwargs() | {"scratch_finalize_timeout": 2.0})
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "pj", "auto_scratch": "shared",
            "auto_complete": True,
            # Harvest fails ONLY on the non-host worker.
            "job_release": {
                "command": "sh -c 'test $SHIPYARD_NODE_INDEX -eq 0'"},
            "tasks": [
                {"id": "g",
                 "command": "sh -c 'echo data > "
                            "$SHIPYARD_JOB_SCRATCH/"
                            "d$SHIPYARD_NODE_INDEX'",
                 "multi_instance": {"num_instances": 2}},
            ]}]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "preserve", "pj",
                                        timeout=90)
        assert all(t["state"] == "completed" for t in tasks), tasks
        node0 = FakePodSubstrate.node_id("preserve", 0, 0)
        host_scratch = os.path.join(substrate.work_root, "preserve",
                                    node0, "scratch", "pj")
        # Give release fan-out + finalize timeout room to play out.
        time.sleep(6.0)
        assert os.path.isdir(host_scratch), \
            "preserved tree was deleted despite incomplete fan-out"
        assert os.path.isfile(os.path.join(host_scratch, "d0"))
        assert os.path.isfile(os.path.join(host_scratch, "d1"))
        # The host record survives for the operator's manual harvest.
        store.get_entity(names.TABLE_JOBPREP, "preserve$pj",
                         "#scratchhost")
    finally:
        substrate.stop_all()


def test_stale_local_dir_not_mistaken_for_shared_namespace(tmp_path):
    """The same-fs decision reads the published NONCE through the
    path — a stale directory at the identical layout path (preserved
    scratch of a reused job id) must NOT be silently used as the
    shared namespace (advisor r3 low finding)."""
    from batch_shipyard_tpu.agent.node_agent import (
        NodeAgent, NodeIdentity, _SCRATCH_NONCE)
    store = MemoryStateStore()
    conf = {"pool_specification": {
        "id": "np", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-8"},
        "max_wait_time_seconds": 30}}
    pool = settings_mod.pool_settings(conf)
    mounted = []

    def fake_mount(remote, mount_point):
        mounted.append(remote)
        return 0

    agent = NodeAgent(
        store, NodeIdentity(pool_id="np", node_id="np-s0-w1",
                            node_index=1, hostname="h",
                            internal_ip="10.0.0.2"),
        pool, work_dir=str(tmp_path / "w1"), poll_interval=0.05,
        scratch_mount_runner=fake_mount)
    # A stale dir exists at the host's path with a DIFFERENT nonce.
    host_path = tmp_path / "w0" / "scratch" / "job1"
    host_path.mkdir(parents=True)
    (host_path / _SCRATCH_NONCE).write_text("stale-nonce")
    store.upsert_entity(names.TABLE_JOBPREP, "np$job1",
                        "#scratchhost", {
                            "path": str(host_path),
                            "host_ip": "10.0.0.1",
                            "node_id": "np-s0-w0",
                            "nonce": "fresh-nonce"})
    path = agent._resolve_scratch("job1", {"auto_scratch": "shared"})
    assert mounted == [f"10.0.0.1:{host_path}"]
    assert "scratch-nfs" in path
    # Matching nonce -> same filesystem, no mount.
    mounted.clear()
    (host_path / _SCRATCH_NONCE).write_text("fresh-nonce")
    agent2 = NodeAgent(
        store, NodeIdentity(pool_id="np", node_id="np-s0-w2",
                            node_index=2, hostname="h2",
                            internal_ip="10.0.0.3"),
        pool, work_dir=str(tmp_path / "w2"), poll_interval=0.05,
        scratch_mount_runner=fake_mount)
    path2 = agent2._resolve_scratch("job1", {"auto_scratch": "shared"})
    assert mounted == []
    assert path2 == str(host_path)

"""Pallas paged-attention decode kernel (vLLM-style block tables).

The XLA formulation of paged decode attention
(models/transformer.py:_decode_attend_paged) gathers every slot's
pages into a dense [B, max_blocks*page, H, D] view before the score
matmul — it reads the full logical table width from HBM every step,
even for slots holding ten tokens. Decode attention is HBM-bandwidth
bound, so that gather IS the step time.

This kernel reads only real pages: the block table rides Pallas scalar
prefetch (pltpu.PrefetchScalarGridSpec), the k/v page BlockSpec index
maps translate grid step j into the slot's j-th physical page id, and
Mosaic DMAs exactly that page into VMEM. Pages past a slot's live
length are skipped (the index map clamps to the slot's last live page
so the prefetched DMA never fetches garbage, and @pl.when skips the
compute). Online softmax accumulates across the (sequential) page grid
dimension in VMEM scratch — the flash-attention recurrence over the
page list.

Reference analog: none — the reference (Azure batch-shipyard) has no
serving runtime; this is net-new TPU compute-path work alongside
ops/attention.py. The block-table design follows the public
vLLM/PagedAttention scheme (PAPERS.md).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _accumulate_page(q_row, k_tile, v_tile, j, length, o_acc, m_acc,
                     l_acc, *, page: int, scale: float):
    """ONE online-softmax block update over a (pre-dequantized) page
    tile — the recurrence shared by the fp and int8 kernels (a fix to
    the mask/correction/denominator logic lands in both)."""
    scores = jax.lax.dot_general(
        q_row, k_tile, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale       # [1, page]
    pos = j * page + jax.lax.broadcasted_iota(
        jnp.int32, (1, page), 1)
    scores = jnp.where(pos < length, scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1, keepdims=True)       # [1, 1]
    m_new = jnp.maximum(m_acc[...], m_blk)
    correction = jnp.exp(m_acc[...] - m_new)
    p = jnp.exp(scores - m_new)                            # [1, page]
    l_new = (l_acc[...] * correction +
             jnp.sum(p, axis=-1, keepdims=True))
    pv = jax.lax.dot_general(
        p.astype(v_tile.dtype), v_tile, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # [1, D]
    o_acc[...] = o_acc[...] * correction + pv
    m_acc[...] = m_new
    l_acc[...] = l_new


def _init_and_emit(j, num_blocks, o_ref, o_acc, m_acc, l_acc):
    @pl.when(j == 0)
    def _init():
        o_acc[...] = jnp.zeros_like(o_acc)
        m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
        l_acc[...] = jnp.zeros_like(l_acc)

    def _emit():
        l_final = l_acc[...]
        denom = jnp.where(l_final == 0.0, 1.0, l_final)
        o_ref[...] = (o_acc[...] / denom).astype(o_ref.dtype)
    return _emit


def _paged_decode_kernel(table_ref, len_ref, q_ref, k_ref, v_ref,
                         o_ref, o_acc, m_acc, l_acc, *,
                         page: int, scale: float):
    """One (slot, head, page-step) program.

    q_ref: [1, D] this slot+head's query row.
    k_ref/v_ref: [page, D] the physical page selected by the BlockSpec
    index map (table_ref[b, j]).
    Scratch persists across the sequential page dimension: o_acc [1, D]
    fp32 numerator, m_acc/l_acc [1, 1] running max / denominator.
    """
    b = pl.program_id(0)
    j = pl.program_id(2)
    num_blocks = pl.num_programs(2)
    length = len_ref[b]
    emit = _init_and_emit(j, num_blocks, o_ref, o_acc, m_acc, l_acc)

    @pl.when(j * page < length)
    def _accumulate():
        _accumulate_page(q_ref[...], k_ref[...], v_ref[...], j,
                         length, o_acc, m_acc, l_acc, page=page,
                         scale=scale)

    pl.when(j == num_blocks - 1)(emit)


def _paged_decode_kernel_int8(table_ref, len_ref, q_ref, k_ref,
                              ks_ref, v_ref, vs_ref, o_ref, o_acc,
                              m_acc, l_acc, *, page: int,
                              scale: float):
    """int8-page variant: the same recurrence with the K/V tiles
    dequantized in VMEM (k int8 [page, D] * scale [page, 1]) right
    before the dots — HBM traffic stays int8."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    num_blocks = pl.num_programs(2)
    length = len_ref[b]
    emit = _init_and_emit(j, num_blocks, o_ref, o_acc, m_acc, l_acc)

    @pl.when(j * page < length)
    def _accumulate():
        k_tile = k_ref[...].astype(jnp.float32) * ks_ref[...]
        v_tile = v_ref[...].astype(jnp.float32) * vs_ref[...]
        _accumulate_page(q_ref[...].astype(jnp.float32), k_tile,
                         v_tile, j, length, o_acc, m_acc, l_acc,
                         page=page, scale=scale)

    pl.when(j == num_blocks - 1)(emit)


def paged_decode_attention_kernel(q, k_pages, v_pages, block_table,
                                  lengths, k_scales=None,
                                  v_scales=None):
    """Pallas path. q: [B, 1, H, D]; k_pages/v_pages:
    [P, page, H, D]; block_table: [B, max_blocks] int32; lengths: [B]
    int32 valid-key counts (INCLUDING the token written this step, so
    every attended slot has length >= 1 — a length-0 slot yields zeros
    here but softmax-of-all-masked garbage from the XLA path; the
    decode contract never attends an unwritten slot).
    k_scales/v_scales: [P, page, H] fp32 when the pages are int8
    (dequantized in-kernel per tile). Returns [B, 1, H, D] in
    q.dtype."""
    batch, seq, heads, depth = q.shape
    assert seq == 1, "decode consumes one token per call"
    _pages, page, _heads, _depth = k_pages.shape
    max_blocks = block_table.shape[1]
    scale = 1.0 / (depth ** 0.5)
    q_r = q.reshape(batch, heads, 1, depth)
    int8_pages = k_scales is not None

    def page_index(b, h, j, tbl, ln):
        # Clamp dead steps to the slot's LAST live page: the prefetch
        # pipeline fetches block j+1 while computing block j, and an
        # unclamped map would DMA whatever stale id sits in the dead
        # tail of the table row. Page 0 fallback covers length == 0.
        live = jnp.maximum((ln[b] + page - 1) // page - 1, 0)
        return (tbl[b, jnp.minimum(j, live)], 0, h, 0)

    page_spec = pl.BlockSpec((None, page, None, depth), page_index)
    in_specs = [
        pl.BlockSpec((None, None, 1, depth),
                     lambda b, h, j, tbl, ln: (b, h, 0, 0)),
        page_spec,
    ]
    operands = [q_r, k_pages]
    if int8_pages:
        scale_spec = pl.BlockSpec((None, page, None, 1), page_index)
        in_specs.append(scale_spec)
        operands.append(
            k_scales.reshape(*k_scales.shape, 1))
    in_specs.append(page_spec)
    operands.append(v_pages)
    if int8_pages:
        in_specs.append(scale_spec)
        operands.append(
            v_scales.reshape(*v_scales.shape, 1))
    kern = (_paged_decode_kernel_int8 if int8_pages
            else _paged_decode_kernel)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, heads, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, None, 1, depth),
                               lambda b, h, j, tbl, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, depth), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(kern, page=page, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, heads, 1, depth),
                                       q.dtype),
    )(block_table.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)
    return out.transpose(0, 2, 1, 3)  # [B, 1, H, D]


def paged_decode_attention_xla(q, k_pages, v_pages, block_table,
                               lengths, k_scales=None,
                               v_scales=None):
    """XLA gather formulation (the CPU/fallback path): materialize each
    slot's full logical [max_blocks*page, H, D] view, then one masked
    softmax. Same math as the kernel; reads the whole table width.
    With int8 pages, only the GATHERED slices dequantize — never the
    whole pool."""
    batch, seq, heads, depth = q.shape
    assert seq == 1
    page = k_pages.shape[1]
    max_blocks = block_table.shape[1]
    k_all = k_pages[block_table].reshape(
        batch, max_blocks * page, heads, depth)
    v_all = v_pages[block_table].reshape(
        batch, max_blocks * page, heads, depth)
    if k_scales is not None:
        ks = k_scales[block_table].reshape(
            batch, max_blocks * page, heads)
        vs = v_scales[block_table].reshape(
            batch, max_blocks * page, heads)
        k_all = (k_all.astype(jnp.float32) *
                 ks[..., None]).astype(q.dtype)
        v_all = (v_all.astype(jnp.float32) *
                 vs[..., None]).astype(q.dtype)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_all,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(depth))
    key_pos = jax.lax.broadcasted_iota(
        jnp.int32, (max_blocks * page, 1), 0)[:, 0]
    mask = key_pos[None, :] < lengths[:, None]
    scores = jnp.where(mask[:, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v_all,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_table, lengths,
                           impl: Optional[str] = None,
                           k_scales=None, v_scales=None):
    """Dispatch: 'kernel' (Pallas) or 'xla'. Default: kernel on TPU,
    xla elsewhere (mirrors ops/attention.attention's dispatch).
    k_scales/v_scales switch both paths to int8-page dequant."""
    if impl is None:
        impl = "kernel" if jax.default_backend() == "tpu" else "xla"
    if impl == "kernel":
        return paged_decode_attention_kernel(
            q, k_pages, v_pages, block_table, lengths,
            k_scales=k_scales, v_scales=v_scales)
    if impl == "xla":
        return paged_decode_attention_xla(
            q, k_pages, v_pages, block_table, lengths,
            k_scales=k_scales, v_scales=v_scales)
    raise ValueError(f"unknown paged attention impl {impl!r}")

"""Paged-attention Pallas kernel tests (interpret mode): the kernel
must agree with the XLA gather formulation for random block tables and
ragged lengths, and the transformer's paged decode path must produce
identical tokens under either implementation."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.experimental.pallas import tpu as pltpu

from batch_shipyard_tpu.ops import paged_attention as pa


@pytest.fixture()
def interpret_mode():
    with pltpu.force_tpu_interpret_mode():
        yield


def _random_case(rng, dtype, batch=4, heads=4, depth=64, page=8,
                 max_blocks=6, num_pages=32):
    q = jnp.asarray(rng.randn(batch, 1, heads, depth), dtype)
    k_pages = jnp.asarray(rng.randn(num_pages, page, heads, depth),
                          dtype)
    v_pages = jnp.asarray(rng.randn(num_pages, page, heads, depth),
                          dtype)
    # Distinct physical pages per slot (the allocator's invariant).
    table = jnp.asarray(
        rng.permutation(num_pages)[:batch * max_blocks].reshape(
            batch, max_blocks), jnp.int32)
    return q, k_pages, v_pages, table


def test_kernel_matches_xla_fp32(interpret_mode):
    rng = np.random.RandomState(0)
    q, k_pages, v_pages, table = _random_case(rng, jnp.float32)
    lengths = jnp.asarray([1, 5, 23, 48], jnp.int32)
    ref = pa.paged_decode_attention_xla(q, k_pages, v_pages, table,
                                        lengths)
    got = pa.paged_decode_attention_kernel(q, k_pages, v_pages, table,
                                           lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def test_kernel_matches_xla_bf16(interpret_mode):
    rng = np.random.RandomState(1)
    q, k_pages, v_pages, table = _random_case(rng, jnp.bfloat16)
    lengths = jnp.asarray([3, 8, 17, 41], jnp.int32)
    ref = pa.paged_decode_attention_xla(q, k_pages, v_pages, table,
                                        lengths)
    got = pa.paged_decode_attention_kernel(q, k_pages, v_pages, table,
                                           lengths)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2)


def test_kernel_ignores_dead_table_tail(interpret_mode):
    """Stale ids in the dead tail of a table row must not affect the
    output (the index map clamps to the last live page)."""
    rng = np.random.RandomState(2)
    q, k_pages, v_pages, table = _random_case(rng, jnp.float32)
    lengths = jnp.asarray([4, 9, 12, 30], jnp.int32)
    ref = pa.paged_decode_attention_kernel(q, k_pages, v_pages, table,
                                           lengths)
    page = k_pages.shape[1]
    poisoned = np.asarray(table).copy()
    for b, ln in enumerate(np.asarray(lengths)):
        live = (int(ln) + page - 1) // page
        poisoned[b, live:] = 0  # stale/reused page ids
    got = pa.paged_decode_attention_kernel(
        q, k_pages, v_pages, jnp.asarray(poisoned), lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=0, rtol=0)


def test_dispatch_auto_is_xla_off_tpu():
    rng = np.random.RandomState(3)
    q, k_pages, v_pages, table = _random_case(rng, jnp.float32)
    lengths = jnp.asarray([2, 2, 2, 2], jnp.int32)
    auto = pa.paged_decode_attention(q, k_pages, v_pages, table,
                                     lengths)
    xla = pa.paged_decode_attention_xla(q, k_pages, v_pages, table,
                                        lengths)
    assert jax.default_backend() != "tpu"
    np.testing.assert_allclose(np.asarray(auto), np.asarray(xla))


def test_transformer_paged_decode_kernel_equals_xla(interpret_mode):
    """End-to-end: the transformer's paged decode step produces the
    same output under impl='kernel' and impl='xla'."""
    from batch_shipyard_tpu.models import transformer as tfm

    def run(impl):
        cfg = tfm.TransformerConfig(
            vocab_size=128, d_model=64, n_layers=2, n_heads=2,
            d_head=32, d_ff=128, dtype=jnp.float32, decode=True,
            max_decode_len=16, kv_page_size=8, kv_num_pages=16,
            paged_attention_impl=impl)
        model = tfm.TransformerLM(cfg)
        tokens = jnp.asarray([[5], [9]], jnp.int32)
        variables = model.init(jax.random.PRNGKey(0), tokens,
                               positions=jnp.zeros((2, 1), jnp.int32))
        params, cache = variables["params"], variables["cache"]

        # Give the two slots disjoint, non-contiguous physical pages
        # (block tables init to zeros, which would collide both slots
        # onto page 0 and mask indexing bugs).
        def assign_tables(leaf_dict):
            if isinstance(leaf_dict, dict) and "block_table" in \
                    leaf_dict:
                table = jnp.asarray([[3, 7], [11, 5]], jnp.int32)
                return {**leaf_dict, "block_table": table}
            return leaf_dict

        cache = jax.tree_util.tree_map(
            assign_tables, cache,
            is_leaf=lambda x: isinstance(x, dict) and
            "block_table" in x)
        outs = []
        for step in range(3):
            tok = jnp.asarray([[5 + step], [9 + step]], jnp.int32)
            pos = jnp.full((2, 1), step, jnp.int32)
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tok, positions=pos,
                mutable=["cache"])
            cache = mutated["cache"]
            outs.append(np.asarray(logits))
        return np.stack(outs)

    np.testing.assert_allclose(run("kernel"), run("xla"),
                               atol=1e-5, rtol=1e-5)


def _int8_case(rng, batch=4, heads=4, depth=64, page=8,
               max_blocks=6, num_pages=32):
    from batch_shipyard_tpu.ops.quantization import quantize_int8_rows
    q = jnp.asarray(rng.randn(batch, 1, heads, depth), jnp.float32)
    k_f = jnp.asarray(rng.randn(num_pages, page, heads, depth),
                      jnp.float32)
    v_f = jnp.asarray(rng.randn(num_pages, page, heads, depth),
                      jnp.float32)
    k_pages, k_scales = quantize_int8_rows(k_f)
    v_pages, v_scales = quantize_int8_rows(v_f)
    table = jnp.asarray(
        rng.permutation(num_pages)[:batch * max_blocks].reshape(
            batch, max_blocks), jnp.int32)
    lengths = jnp.asarray([1, 7, 23, 48], jnp.int32)
    return (q, k_pages, v_pages, table, lengths, k_scales, v_scales,
            k_f, v_f)


def test_int8_kernel_matches_int8_xla(interpret_mode):
    """The in-kernel per-tile dequant must agree exactly with the
    gathered-slice dequant of the XLA path (same int8 inputs)."""
    rng = np.random.RandomState(23)
    (q, kp, vp, table, lengths, ks, vs, _kf, _vf) = _int8_case(rng)
    got = pa.paged_decode_attention_kernel(
        q, kp, vp, table, lengths, k_scales=ks, v_scales=vs)
    want = pa.paged_decode_attention_xla(
        q, kp, vp, table, lengths, k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_int8_xla_close_to_fp(interpret_mode):
    """int8 paged attention stays within quantization noise of the
    full-precision pages it was quantized from."""
    rng = np.random.RandomState(29)
    (q, kp, vp, table, lengths, ks, vs, k_f, v_f) = _int8_case(rng)
    got = pa.paged_decode_attention_xla(
        q, kp, vp, table, lengths, k_scales=ks, v_scales=vs)
    ref = pa.paged_decode_attention_xla(q, k_f, v_f, table, lengths)
    rel = (np.linalg.norm(np.asarray(got - ref)) /
           np.linalg.norm(np.asarray(ref)))
    assert rel < 0.02, rel

"""On-demand step profiling: `shipyard jobs profile <job> --steps N`.

Flow (store flag -> agent -> train harness -> artifact):

  1. The fleet action stamps ``profile_request: {steps, requested_at}``
     on the job entity (one request at a time; a new request
     supersedes).
  2. The node agent forwards the request to its tasks: at launch it
     exports $SHIPYARD_PROFILE_REQUEST_FILE / $SHIPYARD_PROFILE_DIR
     (docker path remap like the progress file), and — for tasks
     ALREADY running — its heartbeat loop drops the request file into
     the live task dirs, so profiling is genuinely on-demand, not
     launch-time-only.
  3. The train harness calls ``StepProfiler.tick(step)`` once per
     step: when the request file appears, the next N steps run inside
     ``jax.profiler.trace`` writing into the profile dir; the request
     file is consumed (removed) when capture starts so one request is
     one capture.
  4. Post-task, the agent uploads the profile dir through the store
     next to the task's other outputs and stamps
     ``profile_artifact`` on the task entity (shown by
     ``jobs tasks list``).

Everything is best-effort: a failed profiler start (no TensorBoard
plugin, unsupported backend) logs and disarms instead of failing the
training step that triggered it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from batch_shipyard_tpu.trace import spans as trace_spans
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Env contract (exported by the node agent; docker remap in
# task_runner).
PROFILE_REQUEST_FILE_ENV = "SHIPYARD_PROFILE_REQUEST_FILE"
PROFILE_DIR_ENV = "SHIPYARD_PROFILE_DIR"

# Job-entity column the fleet action writes and the agent polls.
COL_PROFILE_REQUEST = "profile_request"
# Task-entity column the agent stamps after uploading the artifact.
COL_PROFILE_ARTIFACT = "profile_artifact"


def read_request(path: Optional[str]) -> Optional[dict]:
    """Parse a request file; None when absent/junk (task-controlled
    surface — junk must never crash the step loop)."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fh:
            request = json.load(fh)
    except (OSError, ValueError):
        return None
    return request if isinstance(request, dict) else None


def write_request(path: str, steps: int,
                  requested_at: Optional[str] = None) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump({"steps": int(steps),
                   "requested_at": requested_at
                   or util.datetime_utcnow_iso()}, fh)
    os.replace(tmp, path)


class StepProfiler:
    """Per-process profiling driver the train loops tick every step.

    ``tick(step)`` is O(one os.path.exists) while disarmed — cheap
    enough for every step of a CPU test loop, invisible next to a
    real TPU step. ``close()`` stops a capture cut short by loop
    exit."""

    def __init__(self,
                 request_path: Optional[str] = None,
                 profile_dir: Optional[str] = None) -> None:
        self.request_path = (request_path if request_path is not None
                             else os.environ.get(
                                 PROFILE_REQUEST_FILE_ENV))
        self.profile_dir = (profile_dir if profile_dir is not None
                            else os.environ.get(PROFILE_DIR_ENV))
        self._remaining = 0
        self._requested = 0
        self._active = False
        self._started_at = 0.0
        self._start_step: Optional[int] = None
        self._broken = False  # profiler start failed; stay disarmed

    @property
    def active(self) -> bool:
        return self._active

    def tick(self, step: int) -> None:
        """Call once per train step, BEFORE running the step: arms on
        a pending request, counts captured steps, stops after N."""
        if self._active:
            self._remaining -= 1
            if self._remaining <= 0:
                self._stop(step)
            return
        if self._broken or not self.request_path or \
                not self.profile_dir:
            return
        request = read_request(self.request_path)
        if request is None:
            return
        try:
            steps = max(1, int(request.get("steps", 1)))
        except (TypeError, ValueError):
            steps = 1
        # Consume the request BEFORE starting: one request, one
        # capture, even if the start fails below.
        try:
            os.remove(self.request_path)
        except OSError:
            pass
        self._start(step, steps)

    def _start(self, step: int, steps: int) -> None:
        try:
            os.makedirs(self.profile_dir, exist_ok=True)
            import jax
            jax.profiler.start_trace(self.profile_dir)
        except Exception:  # noqa: BLE001 - never fail the step loop
            logger.exception("jax.profiler start failed; profiling "
                             "disarmed for this process")
            self._broken = True
            return
        self._active = True
        self._remaining = steps
        self._requested = steps
        self._started_at = time.time()
        self._start_step = step
        logger.info("profiling %d step(s) from step %d into %s",
                    steps, step, self.profile_dir)

    def _stop(self, end_step: int) -> None:
        """``end_step`` is EXCLUSIVE: the capture covers the
        half-open step range [start_step, end_step) — tick(N) stops
        the trace before step N runs, so N itself is never in the
        artifact."""
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            logger.exception("jax.profiler stop failed")
        self._active = False
        # The capture window joins the task's trace (span ingested
        # post-task like every program phase). step_end is the same
        # half-open bound, so step_end - step_start = steps captured.
        trace_spans.record(
            trace_spans.SPAN_PROFILE, self._started_at, time.time(),
            step_start=self._start_step, step_end=end_step,
            profile_dir=self.profile_dir)
        logger.info("profile capture complete (steps [%s, %s))",
                    self._start_step, end_step)

    def close(self) -> None:
        """Stop a capture cut short by loop exit (fewer steps ran
        than requested): the honest exclusive bound is start +
        steps-actually-run. The arming tick precedes its step, so by
        the time a loop-exit close runs, one more step has completed
        than the remaining counter saw."""
        if self._active:
            captured = min(self._requested,
                           self._requested - self._remaining + 1)
            self._remaining = 0
            self._stop((self._start_step or 0) + captured)

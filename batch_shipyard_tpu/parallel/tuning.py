"""XLA/libtpu tuning-flag profiles (the conv/collective autotune lever
from ROADMAP.md).

XLA reads ``XLA_FLAGS`` at backend initialization and libtpu reads
``LIBTPU_INIT_ARGS`` at TPU client creation, so profiles must be
applied BEFORE the first jax device query — callers (bench.py, user
launch scripts via `jobs/launcher.py` env synthesis) apply them at
process start.

Profiles are additive sets of publicly documented flags (the MaxText /
scaling-book lineage); "default" is intentionally empty — flags are
workload-dependent and a wrong flag silently regresses, so anything
non-empty is opt-in via ``SHIPYARD_XLA_TUNING=<profile>`` and should
be validated by a measured A/B on the target workload (tools/
tpu_checks.py --tuning runs the compile-sanity half of that).
"""

from __future__ import annotations

import os

PROFILES: dict[str, dict[str, str]] = {
    # No flags: trust the compiler defaults.
    "default": {},
    # Overlap collectives with compute (multi-chip training): the
    # standard async-collective set from public large-model configs.
    "async-collectives": {
        "XLA_FLAGS": " ".join([
            "--xla_tpu_enable_async_collective_fusion=true",
            "--xla_tpu_enable_async_collective_fusion_fuse_all_gather"
            "=true",
            "--xla_tpu_enable_async_collective_fusion_multiple_steps"
            "=true",
            "--xla_tpu_overlap_compute_collective_tc=true",
            "--xla_enable_async_all_gather=true",
        ]),
    },
    # Data-parallel all-reduce scheduling (dp/fsdp training).
    "dp-allreduce": {
        "XLA_FLAGS": " ".join([
            "--xla_tpu_enable_data_parallel_all_reduce_opt=true",
            "--xla_tpu_data_parallel_opt_different_sized_ops=true",
        ]),
    },
    # Larger scoped VMEM for conv/fusion tiling headroom (the conv
    # autotune lever: gives XLA's fusion cost model more on-chip
    # scratch to tile ResNet convs into).
    "vmem-high": {
        "XLA_FLAGS": "--xla_tpu_scoped_vmem_limit_kib=65536",
    },
}


def apply_tuning_env(profile: str | None = None,
                     environ: dict | None = None) -> str:
    """Merge the chosen profile's flags into the environment
    (appending to any user-set XLA_FLAGS rather than clobbering).
    Profile resolution: explicit arg > SHIPYARD_XLA_TUNING > default.
    Returns the profile name applied."""
    env = os.environ if environ is None else environ
    name = profile or env.get("SHIPYARD_XLA_TUNING", "default")
    if name not in PROFILES:
        raise KeyError(
            f"unknown tuning profile {name!r} "
            f"(have: {sorted(PROFILES)})")
    for var, flags in PROFILES[name].items():
        existing = env.get(var, "")
        # Idempotent: don't append the same flags twice.
        if flags and flags not in existing:
            env[var] = f"{existing} {flags}".strip()
    return name

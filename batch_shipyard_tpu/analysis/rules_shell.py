"""Shell-layer rules: the shellcheck-equivalent pass.

The reference Batch Shipyard's CI was lint-only but it DID lint its
shell (shellcheck over the nodeprep/task-runner scripts,
SURVEY.md:264-268). This container has no shellcheck binary and
nothing may be installed, so these rules implement the small,
high-signal subset that matters for our two-file shell layer
(install.sh, tools/*.sh), documented as the shellcheck stand-in in
docs/34-static-analysis.md. Rules key on raw lines, not AST.
"""

from __future__ import annotations

import re

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, rule)

_STRICT_RE = re.compile(r"^\s*set\s+-[a-zA-Z]*e")
_COMMENT_RE = re.compile(r"^\s*#")
# Unquoted $VAR (or ${VAR}) as an argument to a path-consuming
# command: word-splitting/globbing on the expansion (shellcheck
# SC2086's highest-stakes instances).
_UNQUOTED_RE = re.compile(
    r"(?:^|[;&|]\s*|\s)(?:cd|rm|cp|mv|mkdir|rmdir|touch|source|\.)"
    r"\s+(?:-[\w-]+\s+)*\$\{?[A-Za-z_]")
_BACKTICK_RE = re.compile(r"`[^`]+`")


@rule("shell-strict-mode", family="shell")
def check_strict_mode(ctx: AnalysisContext) -> list[Finding]:
    """A shell script without ``set -e`` (errexit) in its prologue
    keeps running after a failed step — for install.sh that means a
    half-built venv reported as success.

    Provenance: the reference's install.sh ships `set -euo pipefail`
    on line 2; ours must not regress below it. Scripts that handle
    failure deliberately (probe loops) suppress inline with a
    justification comment."""
    findings = []
    for src in ctx.shell_files:
        head = src.lines[:15]
        if any(_STRICT_RE.search(line) for line in head):
            continue
        findings.append(Finding(
            rule="shell-strict-mode", path=src.rel, line=1,
            message=("no `set -e` in the first 15 lines; failures "
                     "cascade silently")))
    return findings


@rule("shell-unquoted-var", family="shell")
def check_unquoted_var(ctx: AnalysisContext) -> list[Finding]:
    """An unquoted ``$VAR`` argument to a path-consuming command
    (cd/rm/cp/mv/mkdir/touch/source) word-splits and globs — with
    ``rm`` the classic catastrophic form (shellcheck SC2086).

    Provenance: the reference repo's shellcheck gate; our install.sh
    quotes every expansion and stays that way."""
    findings = []
    for src in ctx.shell_files:
        for idx, line in enumerate(src.lines, start=1):
            if _COMMENT_RE.match(line):
                continue
            match = _UNQUOTED_RE.search(line)
            if match is None:
                continue
            # Text inside an echo/printf message isn't a command —
            # the cheap quoting-free check: anything echoed before
            # the match is data, not code.
            if re.search(r"\b(echo|printf)\b", line[:match.start()]):
                continue
            findings.append(Finding(
                rule="shell-unquoted-var", path=src.rel,
                line=idx,
                message=("unquoted $VAR argument to a "
                         "path-consuming command; quote the "
                         "expansion")))
    return findings


@rule("shell-backtick-subst", family="shell")
def check_backtick_subst(ctx: AnalysisContext) -> list[Finding]:
    """Backtick command substitution doesn't nest and swallows
    backslashes; use ``$(...)`` (shellcheck SC2006).

    Provenance: the reference's shellcheck gate; kept so new tooling
    scripts start from the modern form."""
    findings = []
    for src in ctx.shell_files:
        for idx, line in enumerate(src.lines, start=1):
            if _COMMENT_RE.match(line):
                continue
            if _BACKTICK_RE.search(line):
                findings.append(Finding(
                    rule="shell-backtick-subst", path=src.rel,
                    line=idx,
                    message="backtick command substitution; "
                            "use $(...)"))
    return findings

"""batch_shipyard_tpu: TPU-native batch/HPC container-workload orchestration.

A ground-up re-design of the capabilities of Azure/batch-shipyard
(reference: /root/reference, v3.9.1) for Cloud TPU VM pods: a stateless
CLI + storage-mediated control plane that provisions TPU pools, executes
containerized batch and gang-scheduled multi-worker tasks (JAX
distributed over ICI/DCN instead of MPI over Infiniband), moves data,
and provides task factories, job DAGs/schedules, autoscale, monitoring,
federation scheduling, and Slurm bursting.

Layer map (mirrors SURVEY.md section 1, re-imagined for TPU):

  L6 cli/        click command tree
  L5 fleet.py    orchestration: action_* per CLI verb
  L4 pool/ jobs/ data/ monitor/ federation/ slurm/ remotefs/  domain services
  L3 config/     schema validation + typed settings (the de-facto type system)
  L2 state/      object/table/queue/lease state store (GCS or local/memory)
     substrate/  compute substrate (Cloud TPU pods, fake pods, localhost)
  L1 agent/      node-side: nodeprep, task runner, cascade image replicator
  L0 models/ ops/ parallel/  the TPU compute path (JAX/XLA/pallas) that the
     reference delegated to MPI+CUDA third parties
"""

from batch_shipyard_tpu.version import __version__  # noqa: F401

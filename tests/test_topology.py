"""Tests for the TPU topology oracle."""

import math

import pytest

from batch_shipyard_tpu.parallel import topology


@pytest.mark.parametrize("atype,chips,workers,cpw", [
    ("v5litepod-16", 16, 4, 4),
    ("v5e-16", 16, 4, 4),
    ("v5litepod-8", 8, 2, 4),
    ("v5litepod-4", 4, 1, 4),
    ("v5litepod-1", 1, 1, 1),
    ("v5litepod-256", 256, 64, 4),
    ("v4-32", 16, 4, 4),
    ("v4-8", 4, 1, 4),
    ("v3-8", 4, 1, 4),
    ("v5p-128", 64, 16, 4),
    ("v6e-64", 64, 16, 4),
])
def test_lookup_shapes(atype, chips, workers, cpw):
    info = topology.lookup(atype)
    assert info.num_chips == chips
    assert info.num_workers == workers
    assert info.chips_per_worker == cpw
    assert math.prod(info.mesh_shape) == chips


def test_explicit_topology():
    info = topology.lookup("v5litepod-16", topology="2x8")
    assert info.mesh_shape == (2, 8)
    with pytest.raises(ValueError):
        topology.lookup("v5litepod-16", topology="3x3")


def test_3d_torus_for_large_v4():
    info = topology.lookup("v4-128")  # 64 chips
    assert len(info.mesh_shape) == 3
    assert math.prod(info.mesh_shape) == 64


def test_unknown_rejected():
    with pytest.raises(ValueError):
        topology.lookup("a100-8")
    assert not topology.is_tpu_accelerator("a100-8")
    assert topology.is_tpu_accelerator("v6e-8")


def test_capability_numbers():
    info = topology.lookup("v5litepod-16")
    assert info.total_hbm_gib == 16 * 16
    assert info.total_bf16_tflops > 3000

"""Parameter/activation sharding rules: how models map onto the mesh.

The scaling-book recipe: pick a mesh (parallel/mesh.py), annotate
shardings (this module), let XLA insert the collectives. Rules are
path-pattern based so the model code stays sharding-agnostic.

Transformer (Megatron-style tensor parallel over 'tp', optional fsdp
over 'fsdp'):
  - q/k/v/gate/up projections: columns over tp  -> P(fsdp?, 'tp')
  - o/down projections:        rows over tp     -> P('tp', fsdp?)
  - embedding:                 vocab over tp    -> P('tp', fsdp?)
  - norms/scales: replicated
Activations: batch over (dp, fsdp), sequence over sp.

ResNet: pure data parallel (convs don't tensor-parallelize profitably
at this scale) — all params replicated, batch over every mesh axis.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_TRANSFORMER_RULES: list[tuple[str, P]] = [
    (r".*(q_proj|k_proj|v_proj|gate_proj|up_proj)/kernel$",
     P("fsdp", "tp")),
    # Fused-norm path (models/transformer.py fused_norm): the merged
    # qkv / gate-up projections are column-sharded like their unfused
    # counterparts.
    (r".*(qkv_kernel|gate_up_kernel)$", P("fsdp", "tp")),
    (r".*(o_proj|down_proj)/kernel$", P("tp", "fsdp")),
    (r".*embed/embedding$", P("tp", "fsdp")),
    # MoE: experts over ep, expert-internal dims over fsdp/tp.
    (r".*moe/router/kernel$", P()),
    (r".*moe/(w_gate|w_up)$", P("ep", "fsdp", "tp")),
    (r".*moe/w_down$", P("ep", "tp", "fsdp")),
    (r".*(scale|bias)$", P()),
]


def _path_str(path) -> str:
    parts = []
    for key in path:
        if hasattr(key, "key"):
            parts.append(str(key.key))
        elif hasattr(key, "idx"):
            parts.append(str(key.idx))
        else:
            parts.append(str(key))
    return "/".join(parts)


def transformer_param_specs(params) -> Any:
    """PartitionSpec pytree for TransformerLM params."""
    def rule(path, leaf):
        path_s = _path_str(path)
        for pattern, spec in _TRANSFORMER_RULES:
            if re.match(pattern, path_s):
                return spec
        return P()
    return jax.tree_util.tree_map_with_path(rule, params)


def replicated_specs(params) -> Any:
    return jax.tree_util.tree_map(lambda _: P(), params)


def to_shardings(mesh: Mesh, spec_tree) -> Any:
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), spec_tree,
        is_leaf=lambda s: isinstance(s, P))


def place(mesh: Mesh, tree, spec_tree):
    """Device-put a pytree according to a spec tree."""
    shardings = to_shardings(mesh, spec_tree)
    return jax.device_put(tree, shardings)


# ------------------------- reshard on restore ---------------------------

def place_like(template, tree):
    """Re-lay-out ``tree``'s leaves onto ``template``'s shardings and
    dtypes (host round trip: works for ANY source layout, including
    plain numpy and int8-quantized leaves — the dtype is preserved
    bit-for-bit, never promoted through float)."""
    import numpy as np

    def _place(t, v):
        if not hasattr(t, "sharding") or not hasattr(v, "shape"):
            return v
        arr = np.asarray(v)
        if tuple(arr.shape) != tuple(t.shape):
            raise ValueError(
                f"reshard-on-restore shape mismatch: checkpoint leaf "
                f"{arr.shape} vs template {t.shape} — global shapes "
                f"are mesh-independent, so this checkpoint belongs "
                f"to a different model config")
        if arr.dtype != t.dtype:
            arr = arr.astype(t.dtype)
        return jax.device_put(arr, t.sharding)

    return jax.tree_util.tree_map(_place, template, tree)


def reshard_on_restore(checkpoint_dir: str, params_template,
                       opt_state_template):
    """Elastic resume: load the latest COMMITTED checkpoint — saved
    at mesh size N — and re-shard params/opt-state onto the
    templates' mesh (size M). Returns (params, opt_state, step) or
    None when nothing is committed.

    The mechanism is deliberately layout-agnostic: full arrays are
    restored HOST-side against shape/dtype templates (no device
    shardings handed to Orbax — the checkpoint's layout metadata may
    describe a mesh that no longer exists), then laid out onto the
    M-mesh shardings the templates carry. Global shapes are
    mesh-independent, so N->M needs no tensor surgery — only a
    re-placement. The equivalence oracle (tests/test_reshard_restore)
    pins the contract: a resume-at-M loss trajectory matches a
    fresh-at-M run restored from the same step."""
    import numpy as np

    from batch_shipyard_tpu.goodput import events as goodput_events
    from batch_shipyard_tpu.trace import spans as trace_spans
    from batch_shipyard_tpu.workloads import checkpoint as ckpt_mod

    step = ckpt_mod.latest_step(checkpoint_dir)
    if step is None:
        return None
    path = ckpt_mod._step_path(checkpoint_dir, step)
    template = {"params": params_template,
                "opt_state": opt_state_template, "step": step}

    def _host_leaf(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return np.zeros(leaf.shape, dtype=leaf.dtype)
        return leaf

    host_template = jax.tree_util.tree_map(_host_leaf, template)
    import orbax.checkpoint as ocp
    with goodput_events.phase(
            goodput_events.PROGRAM_CHECKPOINT_RESTORE, step=step,
            resharded=True), \
            trace_spans.phase(trace_spans.SPAN_CKPT_RESTORE,
                              step=step, resharded=True):
        restored = ckpt_mod._checkpointer().restore(
            path, item=host_template,
            restore_args=ocp.checkpoint_utils.construct_restore_args(
                host_template))
        params = place_like(params_template, restored["params"])
        opt_state = place_like(opt_state_template,
                               restored["opt_state"])
    return params, opt_state, int(restored["step"])

"""Canonical state-store naming: tables, queues, object key prefixes.

Reference analog: the _STORAGE_CONTAINERS registry (convoy/storage.py:68)
that names every blob container/table/queue. Centralized so clients,
daemons, and node agents agree on the schema.
"""

from __future__ import annotations

# Tables (partition key scheme in comments)
TABLE_POOLS = "pools"          # pk="pools",           rk=pool_id
TABLE_NODES = "nodes"          # pk=pool_id,           rk=node_id
TABLE_JOBS = "jobs"            # pk=pool_id,           rk=job_id
TABLE_TASKS = "tasks"          # pk=f"{pool}${job}",   rk=task_id
TABLE_GANGS = "gangs"          # pk=f"{pool}${job}${task}", rk=f"i{k}"
TABLE_JOBPREP = "jobprep"      # pk=f"{pool}${job}",   rk=node_id
TABLE_PERF = "perf"            # pk=f"{pool}",         rk=f"{ts}${uniq}"
TABLE_IMAGES = "images"        # pk=pool_id,           rk=image hash
TABLE_MONITOR = "monitor"      # pk="monitor",         rk=resource id
TABLE_FEDERATIONS = "federations"  # pk="fed",         rk=federation_id
TABLE_FEDJOBS = "fedjobs"      # pk=federation_id,     rk=job id
TABLE_SLURM = "slurm"          # pk=cluster_id,        rk=host/partition
TABLE_REMOTEFS = "remotefs"    # pk="remotefs",        rk=cluster_id
TABLE_REMOTEFS_NODES = "remotefs_nodes"  # pk=cluster_id, rk=node name


def task_pk(pool_id: str, job_id: str) -> str:
    return f"{pool_id}${job_id}"


def gang_pk(pool_id: str, job_id: str, task_id: str) -> str:
    return f"{pool_id}${job_id}${task_id}"


# Queues
def task_queue(pool_id: str) -> str:
    return f"taskq-{pool_id}"


def control_queue(pool_id: str, node_id: str) -> str:
    """Per-node control messages (job release, shutdown, reboot)."""
    return f"ctrlq-{pool_id}-{node_id}"


def federation_queue(federation_id: str) -> str:
    return f"fedq-{federation_id}"


# Object key prefixes
def resource_file_key(pool_id: str, filename: str) -> str:
    return f"resourcefiles/{pool_id}/{filename}"


def task_output_key(pool_id: str, job_id: str, task_id: str,
                    filename: str) -> str:
    return f"taskdata/{pool_id}/{job_id}/{task_id}/{filename}"


def node_log_key(pool_id: str, node_id: str, filename: str) -> str:
    return f"nodelogs/{pool_id}/{node_id}/{filename}"


def global_resource_lock_key(pool_id: str, resource_hash: str,
                             slot: int) -> str:
    """Cascade concurrency-gate lock names (reference: hash.{0..N} lock
    blobs, storage.py:1946)."""
    return f"grlocks/{pool_id}/{resource_hash}.{slot}"


def federation_job_blob_key(federation_id: str, job_id: str,
                            unique: str) -> str:
    return f"fedjobs/{federation_id}/{job_id}/{unique}"

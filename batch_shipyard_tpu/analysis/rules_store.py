"""Store-race rules: the lost-update bug class.

Every coordination surface in this system is an optimistic-concurrency
table (state/base.py): multi-writer rows are safe only through
insert-as-claim (EntityExistsError = somebody else won) or
etag-guarded merge (EtagMismatchError = re-fetch and re-decide).
``upsert_entity`` replaces the WHOLE row unconditionally — on a
shared-mutation table it silently erases a concurrent writer's
columns, which is exactly the shape behind the PR 5 gang-row
claim-marker leaks and the jobschedules double-launch fixed in this
PR.
"""

from __future__ import annotations

import ast
from typing import Optional

from batch_shipyard_tpu.analysis.core import (
    AnalysisContext, Finding, call_name, const_str, keyword_arg,
    module_str_consts, rule)
from batch_shipyard_tpu.state import names

# Tables with MULTI-WRITER row mutation: tasks/gangs/jobs rows are
# written by the submitting client, every claiming/requeueing agent,
# and the leader sweeps; pool rows by autoscale + CLI; jobschedules
# rows by every concurrent schedule evaluator (CLI daemon and service
# VM are both documented run modes, docs/04). Single-writer-per-row
# tables (nodes: the owning agent; monitor: heimdall; jobprep: the
# publishing worker) are exempt — a blind write there races nobody.
SHARED_MUTATION_TABLE_ATTRS = frozenset({
    "TABLE_TASKS", "TABLE_GANGS", "TABLE_JOBS", "TABLE_POOLS",
    "TABLE_JOBSCHEDULES",
})
SHARED_MUTATION_TABLE_VALUES = frozenset(
    getattr(names, attr) for attr in SHARED_MUTATION_TABLE_ATTRS)

_WRITE_METHODS = {"upsert_entity", "merge_entity"}
_FETCH_NAMES = {"get_entity", "get_task", "get_job", "get_node"}


def _table_token(call: ast.Call,
                 consts: dict[str, str]) -> Optional[str]:
    """Resolve a store call's table argument to its string value:
    handles names.TABLE_X attributes, string literals, and
    module-level constants (_SCHED_TABLE = ... / _TABLE = names.X)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Attribute):
        return getattr(names, arg.attr, arg.attr)
    value = const_str(arg)
    if value is not None:
        return value
    if isinstance(arg, ast.Name):
        return consts.get(arg.id)
    return None


def _attr_table_map(tree: ast.AST) -> dict[str, str]:
    """Extend the module constant map with NAME = names.TABLE_X
    assignments resolved through the registry."""
    out = module_str_consts(tree)
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Attribute):
            resolved = getattr(names, node.value.attr, None)
            if isinstance(resolved, str):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out[target.id] = resolved
    return out


@rule("store-blind-upsert", family="store")
def check_blind_upsert(ctx: AnalysisContext) -> list[Finding]:
    """``upsert_entity`` on a shared-mutation table (tasks, gangs,
    jobs, pools, jobschedules) replaces the whole row with no
    concurrency guard: a racing writer's columns are silently lost.

    Provenance: the PR 5 chaos drills exposed gang claim markers
    leaked by exactly this lost-update shape, and the jobschedules
    read-modify-write-upsert let two concurrent schedule evaluators
    double-launch the same recurrence (fixed in this PR —
    jobs/schedules.py now claims the run with insert/etag-merge).
    Fix: insert_entity as a claim, merge_entity with if_match, or
    move the row to a single-writer table."""
    findings = []
    for src in ctx.python_files:
        consts = _attr_table_map(src.tree)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node) == "upsert_entity"):
                continue
            table = _table_token(node, consts)
            if table in SHARED_MUTATION_TABLE_VALUES:
                findings.append(Finding(
                    rule="store-blind-upsert", path=src.rel,
                    line=node.lineno,
                    message=(f"blind upsert_entity on shared-mutation "
                             f"table {table!r}; use insert_entity "
                             f"(claim) or etag-guarded merge_entity")))
    return findings


def _tainted_names(body: list[ast.stmt]) -> dict[str, int]:
    """Names bound (directly or one assignment hop) from a fetched
    entity, mapped to the line the taint was introduced."""
    tainted: dict[str, int] = {}

    def expr_tainted(expr: ast.expr) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and \
                    call_name(sub) in _FETCH_NAMES:
                return True
            if isinstance(sub, ast.Name) and sub.id in tainted and \
                    isinstance(sub.ctx, ast.Load):
                return True
        return False

    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign) and \
                    expr_tainted(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        tainted.setdefault(target.id, node.lineno)
            elif isinstance(node, ast.AnnAssign) and node.value and \
                    isinstance(node.target, ast.Name) and \
                    expr_tainted(node.value):
                tainted.setdefault(node.target.id, node.lineno)
    return tainted


@rule("store-rmw-no-etag", family="store")
def check_rmw_no_etag(ctx: AnalysisContext) -> list[Finding]:
    """Read-modify-write without ``if_match`` on a shared-mutation
    table: an entity is fetched, a value derived from it is written
    back via merge_entity/upsert_entity with no etag guard — between
    the read and the write any concurrent writer's update is lost.

    Provenance: the jobschedules double-launch (this PR): two
    evaluators both read run_number=N and both launched instance N.
    The blessed shape is the terminate_task idiom (jobs/manager.py):
    merge with if_match=entity["_etag"], re-fetch on
    EtagMismatchError."""
    findings = []
    for src in ctx.python_files:
        consts = _attr_table_map(src.tree)
        for fn in [n for n in ast.walk(src.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            tainted = _tainted_names(fn.body)
            if not tainted:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and call_name(node) in _WRITE_METHODS):
                    continue
                if keyword_arg(node, "if_match") is not None:
                    continue
                table = _table_token(node, consts)
                if table not in SHARED_MUTATION_TABLE_VALUES:
                    continue
                entity_arg = (keyword_arg(node, "entity")
                              or (node.args[3] if len(node.args) > 3
                                  else None))
                if entity_arg is None:
                    continue
                derived = any(
                    isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                    and sub.id in tainted
                    and tainted[sub.id] < node.lineno
                    for sub in ast.walk(entity_arg))
                if derived:
                    findings.append(Finding(
                        rule="store-rmw-no-etag", path=src.rel,
                        line=node.lineno,
                        message=(f"read-modify-write on {table!r} "
                                 f"writes fetched-entity data back "
                                 f"without if_match; pass the read's "
                                 f"_etag and handle "
                                 f"EtagMismatchError")))
    return findings


_BATCHABLE_WRITES = {
    "insert_entity": "insert_entities",
    "put_message": "put_messages",
}


@rule("store-write-in-loop", family="store")
def check_write_in_loop(ctx: AnalysisContext) -> list[Finding]:
    """Per-item ``insert_entity``/``put_message`` inside a ``for``
    loop: each iteration is a store round trip, so the loop costs
    O(n) wire latency where the batch APIs (``insert_entities``,
    ``put_messages``) cost O(n / chunk). At submission scale the
    difference is the whole ballgame — the 10^6-task bench's submit
    leg is built entirely out of the batch forms.

    Provenance: the streaming-bulk-submission PR — `migrate_job`'s
    copy loop wrote one row and one message per task (a 10^5-task
    migration paid 2x10^5 round trips); rewritten to build the rows
    and per-queue message lists first and commit via the batch APIs.
    Legitimate per-iteration writes (distinct per-node control
    queues, the base-class batch fallbacks themselves) carry an
    inline suppression stating why."""
    findings = []
    seen: set[tuple[str, int]] = set()
    for src in ctx.python_files:
        for loop in ast.walk(src.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor)):
                continue
            for node in ast.walk(loop):
                if not (isinstance(node, ast.Call)
                        and call_name(node) in _BATCHABLE_WRITES):
                    continue
                key = (src.rel, node.lineno)
                if key in seen:
                    continue
                seen.add(key)
                name = call_name(node)
                findings.append(Finding(
                    rule="store-write-in-loop", path=src.rel,
                    line=node.lineno,
                    message=(f"{name} inside a for loop is one store "
                             f"round trip per iteration; collect the "
                             f"items and use "
                             f"{_BATCHABLE_WRITES[name]} (or "
                             f"suppress with a comment saying why "
                             f"per-item is required)")))
    return findings


@rule("store-etag-retry-no-refetch", family="store")
def check_etag_retry_no_refetch(ctx: AnalysisContext) -> list[Finding]:
    """An ``except EtagMismatchError`` handler that writes again
    WITHOUT re-fetching retries the same stale decision: the mismatch
    means the row changed, so every retry must re-read and re-decide
    (it may no longer be valid — the task may have completed, the
    gang may have resized).

    Provenance: the PR 10 preemption-sweep review — a stale-etag
    retry on the victim stamp would have re-preempted a task that had
    already exited. The blessed shape re-fetches first
    (jobs/manager.py terminate_task)."""
    findings = []
    for src in ctx.python_files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            handled = node.type
            mentions = handled is not None and any(
                isinstance(sub, (ast.Name, ast.Attribute)) and
                ("EtagMismatchError" == getattr(sub, "id", None)
                 or "EtagMismatchError" == getattr(sub, "attr", None))
                for sub in ast.walk(handled))
            if not mentions:
                continue
            fetch_lines = []
            write_calls = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = call_name(sub)
                    if name in _FETCH_NAMES:
                        fetch_lines.append(sub.lineno)
                    elif name in _WRITE_METHODS or \
                            name == "insert_entity":
                        write_calls.append(sub)
            for write in write_calls:
                if not any(line <= write.lineno
                           for line in fetch_lines):
                    findings.append(Finding(
                        rule="store-etag-retry-no-refetch",
                        path=src.rel, line=write.lineno,
                        message=("store write inside an "
                                 "EtagMismatchError handler without "
                                 "re-fetching the entity first; the "
                                 "row changed — re-read and "
                                 "re-decide")))
    return findings

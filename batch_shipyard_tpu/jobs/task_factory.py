"""Task factories: expand one task spec into many.

Reference analog: convoy/task_factory.py generate_task(:305) with
factory kinds ``custom`` (user module import :319), ``file`` (enumerate
objects :348), ``repeat`` (:393), ``random`` (:398 — uniform/randint/
and the distribution zoo), ``parametric_sweep`` (:409 — product /
product_iterables / combinations / permutations / zip).

The expansion is substrate-independent (it was the one piece of the
reference that ports unchanged in spirit); ``file`` enumerates our
state store objects instead of Azure blobs.
"""

from __future__ import annotations

import importlib
import itertools
import random as _random
from typing import Any, Iterator, Optional

from batch_shipyard_tpu.state.base import StateStore


def _format_command(template: str, args) -> str:
    if isinstance(args, dict):
        return template.format(**args)
    if isinstance(args, (list, tuple)):
        return template.format(*args)
    return template.format(args)


def _random_generator(spec: dict) -> Iterator[Any]:
    distribution = spec.get("distribution", "uniform")
    count = spec.get("generate", 1)
    seed = spec.get("seed")
    rng = _random.Random(seed)
    dist_args = spec.get(distribution, {})
    for _ in range(count):
        if distribution == "uniform":
            yield rng.uniform(dist_args.get("a", 0.0),
                              dist_args.get("b", 1.0))
        elif distribution == "randint":
            yield rng.randint(dist_args["a"], dist_args["b"])
        elif distribution == "triangular":
            yield rng.triangular(
                dist_args.get("low", 0.0), dist_args.get("high", 1.0),
                dist_args.get("mode",
                              (dist_args.get("low", 0.0) +
                               dist_args.get("high", 1.0)) / 2))
        elif distribution == "beta":
            yield rng.betavariate(dist_args["alpha"], dist_args["beta"])
        elif distribution == "exponential":
            yield rng.expovariate(dist_args["lambda"])
        elif distribution == "gamma":
            yield rng.gammavariate(dist_args["alpha"], dist_args["beta"])
        elif distribution == "gauss":
            yield rng.gauss(dist_args["mu"], dist_args["sigma"])
        elif distribution == "lognormal":
            yield rng.lognormvariate(dist_args["mu"], dist_args["sigma"])
        elif distribution == "pareto":
            yield rng.paretovariate(dist_args["alpha"])
        elif distribution == "weibull":
            yield rng.weibullvariate(dist_args["alpha"],
                                     dist_args["beta"])
        else:
            raise ValueError(
                f"unknown random distribution {distribution!r}")


def _sweep_generator(spec: dict) -> Iterator[Any]:
    kind = spec.get("generator", "product")
    if kind == "product":
        axes = []
        for param in spec["product"]:
            if "values" in param:
                axes.append(list(param["values"]))
            else:
                start, stop, step = (param["start"], param["stop"],
                                     param.get("step", 1))
                axes.append(list(range(start, stop, step)))
        yield from itertools.product(*axes)
    elif kind == "product_iterables":
        yield from itertools.product(*spec["product_iterables"])
    elif kind == "combinations":
        yield from itertools.combinations(
            spec["combinations"]["iterable"],
            spec["combinations"]["length"])
    elif kind == "permutations":
        yield from itertools.permutations(
            spec["permutations"]["iterable"],
            spec["permutations"].get("length"))
    elif kind == "zip":
        yield from zip(*spec["zip"])
    else:
        raise ValueError(f"unknown sweep generator {kind!r}")


def _file_generator(spec: dict, store: Optional[StateStore]
                    ) -> Iterator[dict]:
    if store is None:
        raise ValueError("file task factory requires a state store")
    prefix = spec.get("prefix", "")
    for key in store.list_objects(prefix):
        name = key[len(prefix):].lstrip("/") if prefix else key
        yield {"url": key, "file_path": key,
               "file_path_with_container": key, "file_name": name,
               "file_name_no_extension": name.rsplit(".", 1)[0]}


def _custom_generator(spec: dict) -> Iterator[Any]:
    module = importlib.import_module(spec["module"])
    if spec.get("package"):
        module = importlib.import_module(spec["module"], spec["package"])
    yield from module.generate(*spec.get("input_args", []),
                               **spec.get("input_kwargs", {}))


def expand_task_factory(raw_task: dict,
                        store: Optional[StateStore] = None,
                        ) -> Iterator[dict]:
    """Yield concrete task dicts from a (possibly factory) task spec."""
    factory = raw_task.get("task_factory")
    if not factory:
        yield dict(raw_task)
        return
    base = {k: v for k, v in raw_task.items() if k != "task_factory"}
    command = base.get("command", "")
    if "repeat" in factory:
        for _ in range(int(factory["repeat"])):
            yield dict(base)
    elif "parametric_sweep" in factory:
        for args in _sweep_generator(factory["parametric_sweep"]):
            task = dict(base)
            task["command"] = _format_command(command, args)
            yield task
    elif "random" in factory:
        for value in _random_generator(factory["random"]):
            task = dict(base)
            task["command"] = _format_command(command, value)
            yield task
    elif "file" in factory:
        for file_info in _file_generator(factory["file"], store):
            task = dict(base)
            task["command"] = _format_command(command, file_info)
            # The enumerated object becomes task input data. Copy the
            # base list — dict(base) is shallow and a shared list would
            # accumulate every enumerated file onto every task.
            task["input_data"] = list(base.get("input_data", [])) + [{
                "kind": "statestore", "key": file_info["url"],
                "file_path": file_info["file_name"]}]
            yield task
    elif "custom" in factory:
        for args in _custom_generator(factory["custom"]):
            task = dict(base)
            task["command"] = _format_command(command, args)
            yield task
    else:
        raise ValueError(
            f"unknown task factory kind: {sorted(factory)}")
